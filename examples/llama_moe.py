"""Llama + mixture-of-experts over an expert-parallel mesh.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/llama_moe.py

Experts shard over the ``ep`` mesh axis (GShard-style einsum dispatch,
compiled to all-to-alls by XLA); everything else rides the same train
step and flash checkpoint path as the GPT family.
"""

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models.gpt import cross_entropy_loss
from dlrover_tpu.models.llama import Llama, LlamaConfig
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.train_step import (
    build_train_step,
    default_optimizer,
    init_train_state,
)


def main():
    n = len(jax.devices())
    ep = 4 if n % 4 == 0 else 1
    mesh = build_mesh(MeshConfig(dp=n // ep, fsdp=1, ep=ep))
    print("mesh:", dict(mesh.shape))

    cfg = LlamaConfig.tiny(num_experts=ep * 2, moe_every=2, max_seq_len=128)
    model = Llama(cfg)
    tx = default_optimizer(warmup_steps=5)
    batch = 2 * (n // ep)

    tokens = jnp.zeros((batch, cfg.max_seq_len), jnp.int32)
    state, shardings = init_train_state(model, tokens, mesh, tx)
    step_fn = build_train_step(model, tx, cross_entropy_loss, mesh, shardings)

    rng = np.random.default_rng(0)
    for step in range(30):
        x = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq_len)),
            jnp.int32,
        )
        y = jnp.roll(x, -1, axis=1)
        state, loss = step_fn(state, x, y)
        if step % 5 == 0:
            print(f"step {step}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
