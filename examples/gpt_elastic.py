"""Elastic GPT training with flash checkpointing — the flagship workflow.

Run on one host (spawns a local master automatically):

    tpurun --standalone --nnodes 1 examples/gpt_elastic.py

Or against a running master on a multi-host slice:

    DLROVER_MASTER_ADDR=<master:port> tpurun --nnodes 4 examples/gpt_elastic.py

Kill the worker (or the whole host) mid-run: the agent re-rendezvouses,
the script rebuilds the mesh from whatever world it lands in, and
``engine.load`` resumes from the shm-staged step — storage only if the
memory copy is gone. (Reference workflow: examples/pytorch/gpt elastic
jobs + flash_checkpoint.)
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.models.gpt import GPT, GPTConfig, cross_entropy_loss
from dlrover_tpu.parallel.mesh import build_mesh, choose_mesh_shape
from dlrover_tpu.parallel.train_step import (
    build_train_step,
    default_optimizer,
    init_train_state,
)
from dlrover_tpu.trainer.elastic import elastic_context

TOTAL_STEPS = int(os.environ.get("TOTAL_STEPS", "200"))
CKPT_DIR = os.environ.get("CKPT_DIR", "/tmp/gpt_elastic_ckpt")
BATCH_PER_DEVICE = 2


def main():
    ctx = elastic_context()  # jax.distributed bootstrap from the agent env

    n = len(jax.devices())
    mesh = build_mesh(choose_mesh_shape(n, tp=1))
    cfg = GPTConfig.tiny() if n <= 8 else GPTConfig.gpt2_small()
    model = GPT(cfg)
    tx = default_optimizer()
    batch = BATCH_PER_DEVICE * n

    tokens = jnp.zeros((batch, cfg.max_seq_len), jnp.int32)
    state, shardings = init_train_state(model, tokens, mesh, tx)
    step_fn = build_train_step(model, tx, cross_entropy_loss, mesh, shardings)

    engine = CheckpointEngine(CKPT_DIR, mesh=mesh)
    # ElasticTrainLoop handles consistent resume (hosts agree on ONE
    # step after a replacement), the shm/storage save cadence, and step
    # reports feeding the master's PerfMonitor/goodput/hang machinery.
    from dlrover_tpu.trainer.loop import ElasticTrainLoop

    rng = np.random.default_rng(ctx.process_id)

    def data():
        # Host numpy on purpose: ElasticTrainLoop prefetches this
        # generator on a background thread (docs/recovery.md) — batch
        # prep belongs on the host there; the jitted step moves the
        # batch to the device on the main thread.
        while True:
            x = rng.integers(
                0, cfg.vocab_size, (batch, cfg.max_seq_len)
            ).astype(np.int32)
            yield x, np.roll(x, -1, axis=1)

    loop = ElasticTrainLoop(
        engine, step_fn, ctx=ctx, max_steps=TOTAL_STEPS, storage_every=50
    )
    loop.run(state, data())
    print("done")


if __name__ == "__main__":
    main()
