"""Elastic GPT training with flash checkpointing — the flagship workflow.

Run on one host (spawns a local master automatically):

    tpurun --standalone --nnodes 1 examples/gpt_elastic.py

Or against a running master on a multi-host slice:

    DLROVER_MASTER_ADDR=<master:port> tpurun --nnodes 4 examples/gpt_elastic.py

Kill the worker (or the whole host) mid-run: the agent re-rendezvouses,
the script rebuilds the mesh from whatever world it lands in, and
``engine.load`` resumes from the shm-staged step — storage only if the
memory copy is gone. (Reference workflow: examples/pytorch/gpt elastic
jobs + flash_checkpoint.)
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.models.gpt import GPT, GPTConfig, cross_entropy_loss
from dlrover_tpu.parallel.mesh import build_mesh, choose_mesh_shape
from dlrover_tpu.parallel.train_step import (
    build_train_step,
    default_optimizer,
    init_train_state,
)
from dlrover_tpu.trainer.elastic import elastic_context

TOTAL_STEPS = int(os.environ.get("TOTAL_STEPS", "200"))
CKPT_DIR = os.environ.get("CKPT_DIR", "/tmp/gpt_elastic_ckpt")
BATCH_PER_DEVICE = 2


def main():
    ctx = elastic_context()  # jax.distributed bootstrap from the agent env

    n = len(jax.devices())
    mesh = build_mesh(choose_mesh_shape(n, tp=1))
    cfg = GPTConfig.tiny() if n <= 8 else GPTConfig.gpt2_small()
    model = GPT(cfg)
    tx = default_optimizer()
    batch = BATCH_PER_DEVICE * n

    tokens = jnp.zeros((batch, cfg.max_seq_len), jnp.int32)
    state, shardings = init_train_state(model, tokens, mesh, tx)
    step_fn = build_train_step(model, tx, cross_entropy_loss, mesh, shardings)

    engine = CheckpointEngine(CKPT_DIR, mesh=mesh)
    start = 0
    # load_consistent: hosts restore independently (shm/peer/storage) and
    # can land on different steps after a replacement — on disagreement
    # every host reloads the common storage step so shards never mix.
    loaded, restored = engine.load_consistent(state)
    if loaded >= 0 and restored is not None:
        state, start = restored, loaded + 1
        print(f"resumed from step {loaded}")

    rng = np.random.default_rng(ctx.process_id)
    for step in range(start, TOTAL_STEPS):
        x = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (batch, cfg.max_seq_len)),
            jnp.int32,
        )
        y = jnp.roll(x, -1, axis=1)
        ctx.start_step_timer()
        state, loss = step_fn(state, x, y)
        if step % 50 == 0:
            engine.save_to_storage(step, state)  # stages + async persist
        else:
            engine.save_to_memory(step, state)  # sub-second stage to shm
        ctx.report_step(step)  # feeds master PerfMonitor + hang detector
        if step % 10 == 0:
            # fetch the scalar only when printing: a per-step float()
            # would force a host-device sync and defeat async dispatch
            print(f"step {step}: loss {float(loss):.4f}")
    engine.wait_saving()
    print("done")


if __name__ == "__main__":
    main()
