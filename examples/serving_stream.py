"""Continuous-batching rollout server demo (models/serving.py).

Streams a mixed-length prompt workload through a fixed number of
decode slots, with a WeightBus-style hot swap landing mid-stream —
the serving shape the reference delegates to a vLLM deployment per
rollout role (examples/unified/rl/openrlhf/ppo/main.py:26-60
upstream). Run it anywhere:

    python examples/serving_stream.py            # CPU-pinned demo

On a real chip, drop the force_virtual_cpu call and size up the model.
"""

import time

from dlrover_tpu.common.platform import force_virtual_cpu

force_virtual_cpu(1)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from dlrover_tpu.models.generation import SamplingConfig  # noqa: E402
from dlrover_tpu.models.gpt import GPT, GPTConfig  # noqa: E402
from dlrover_tpu.models.serving import ContinuousBatchingEngine  # noqa: E402


def main():
    model = GPT(
        GPTConfig(
            vocab_size=512, max_seq_len=512, num_layers=4, num_heads=4,
            head_dim=16, embed_dim=64, use_remat=False,
        )
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    sampling = SamplingConfig(max_new_tokens=32, temperature=0.8, top_k=40)
    eng = ContinuousBatchingEngine(
        model, params, sampling, batch_size=8, prompt_width=64,
        decode_chunk=8,
    )

    r = np.random.default_rng(0)
    prompts = [
        [int(x) for x in r.integers(1, 512, r.integers(4, 60))]
        for _ in range(64)
    ]
    print(
        f"streaming {len(prompts)} prompts (len 4..59) through "
        f"{eng.B} slots, {sampling.max_new_tokens} tokens each ..."
    )
    eng.run(prompts[:8])  # warmup compiles prefill + decode chunk

    # enqueue everything, then drive the scheduler by hand so a weight
    # push can land mid-stream (a rollout role does this on every
    # learner publish)
    for p in prompts:
        eng.submit(p)
    rng = jax.random.PRNGKey(1)
    t0 = time.perf_counter()
    swapped = False
    chunks = 0
    while eng.pending:
        rng, sub = jax.random.split(rng)
        eng.step(sub)
        chunks += 1
        if not swapped and chunks == 10:
            host_push = jax.tree_util.tree_map(
                lambda x: np.asarray(x) * 1.0001, jax.device_get(params)
            )
            lat = eng.set_params(host_push)
            print(f"  weight hot-swap mid-stream: {lat * 1e3:.1f} ms")
            swapped = True
    dt = time.perf_counter() - t0
    done = eng.drain_completions()
    n_tok = sum(len(c.tokens) for c in done)
    print(
        f"served {len(done)} requests / {n_tok} tokens in {dt:.2f}s "
        f"({n_tok / dt:.0f} tokens/s) over {chunks} chunks"
    )
    ttfts = sorted(c.ttft_s for c in done)
    queues = sorted(c.queue_s for c in done)
    print(
        f"  ttft p50/p95: {ttfts[len(ttfts) // 2] * 1e3:.0f}/"
        f"{ttfts[int(len(ttfts) * 0.95)] * 1e3:.0f} ms, "
        f"queue p95: {queues[int(len(queues) * 0.95)] * 1e3:.0f} ms"
    )
    sample = done[0]
    print(f"  e.g. uid {sample.uid}: {len(sample.tokens)} tokens, "
          f"first logprob {sample.logprobs[0]:.3f}")


if __name__ == "__main__":
    main()
