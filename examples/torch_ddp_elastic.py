"""Elastic torch DDP (gloo) training on the same runtime — the
framework-agnostic workflow (reference parity: the TF/PS stack role,
SURVEY.md §2.12).

    tpurun --standalone --nnodes 2 examples/torch_ddp_elastic.py

The SAME master/rendezvous/agent/flash-ckpt stack supervises torch
workers: the rendezvous coordinator address becomes the TCPStore
endpoint, and state_dicts stage through the shm checkpoint engine.
"""

import os

import numpy as np
import torch

from dlrover_tpu.trainer.torch_elastic import (
    TorchCheckpointEngine,
    TorchElasticContext,
)

TOTAL_STEPS = int(os.environ.get("TOTAL_STEPS", "200"))
CKPT_DIR = os.environ.get("CKPT_DIR", "/tmp/torch_ddp_ckpt")


def main():
    ctx = TorchElasticContext.from_env()
    distributed = ctx.initialize_torch()

    torch.manual_seed(0)  # identical init everywhere (DDP invariant)
    model = torch.nn.Sequential(
        torch.nn.Linear(16, 64), torch.nn.ReLU(), torch.nn.Linear(64, 1)
    )
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    # Materialize Adam's slot state before building the load template:
    # engine.load restores only leaves present in the template, and a
    # never-stepped Adam has an empty state dict.
    model(torch.zeros(1, 16)).sum().backward()
    opt.step()
    opt.zero_grad()

    engine = TorchCheckpointEngine(
        os.path.join(CKPT_DIR, f"rank{ctx.node_rank}"),
        host_rank=ctx.node_rank,
        num_hosts=1,
    )
    start = 0
    # load_consistent: a replaced rank with no local checkpoint receives
    # the best surviving rank's full state by broadcast, so every rank
    # enters the loop with identical weights AND the same step count.
    step0, restored = engine.load_consistent(
        {"model": model.state_dict(), "opt": opt.state_dict()}
    )
    if step0 >= 0 and restored is not None:
        model.load_state_dict(restored["model"])
        opt.load_state_dict(restored["opt"])
        start = step0 + 1
        print(f"rank {ctx.process_id} resumed from step {step0}")

    rng = np.random.default_rng(ctx.process_id)
    w_true = torch.randn(16, 1)
    for step in range(start, TOTAL_STEPS):
        x = torch.tensor(rng.standard_normal((32, 16)), dtype=torch.float32)
        y = x @ w_true
        loss = torch.nn.functional.mse_loss(model(x), y)
        opt.zero_grad()
        loss.backward()
        if distributed:
            # hand-rolled DDP allreduce (SUM/world: AVG is NCCL-only on
            # older torch builds; SUM+divide is portable across backends)
            world = torch.distributed.get_world_size()
            for p in model.parameters():
                torch.distributed.all_reduce(
                    p.grad, op=torch.distributed.ReduceOp.SUM
                )
                p.grad /= world
        opt.step()
        engine.save_to_memory(
            step, {"model": model.state_dict(), "opt": opt.state_dict()}
        )
        if step % 20 == 0:
            print(f"rank {ctx.process_id} step {step}: loss {loss.item():.5f}")
    if distributed:
        ctx.shutdown()
    print("done")


if __name__ == "__main__":
    main()
