"""Serve a trained checkpoint: restore params, generate completions.

The train→serve loop in one script (proven in
tests/test_train_to_serve.py): train briefly with the sharded train
step, flash-checkpoint, restore into a fresh process-style template,
and sample through the jit-compiled KV-cache generation engine — the
rollout surface the reference delegates to a separate vLLM deployment
(docs/generation.md).

Run:  python examples/generate_from_checkpoint.py [--steps 20]
"""

import argparse
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--prompt", default="5,9,11", help="token ids")
    parser.add_argument("--max-new", type=int, default=16)
    parser.add_argument("--temperature", type=float, default=0.8)
    ns = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dlrover_tpu.checkpoint.engine import CheckpointEngine
    from dlrover_tpu.models.generation import (
        SamplingConfig,
        generate,
        left_pad_prompts,
    )
    from dlrover_tpu.models.gpt import GPT, GPTConfig, token_loss_mean
    from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
    from dlrover_tpu.parallel.train_step import (
        build_train_step,
        default_optimizer,
        init_train_state,
    )

    cfg = GPTConfig(
        vocab_size=256,
        max_seq_len=128,
        num_layers=2,
        num_heads=4,
        head_dim=16,
        embed_dim=64,
        use_remat=False,
        ce_chunk=32,  # fused head+CE: no whole-sequence logits
    )
    model = GPT(cfg)
    mesh = build_mesh(MeshConfig(dp=-1), jax.devices()[:1])
    tx = default_optimizer(learning_rate=3e-3, warmup_steps=5)
    x0 = jnp.zeros((8, cfg.max_seq_len), jnp.int32)
    state, shardings = init_train_state(model, x0, mesh, tx)
    step = build_train_step(model, tx, token_loss_mean, mesh, shardings)

    r = np.random.default_rng(0)
    for i in range(ns.steps):
        xb = jnp.asarray(
            r.integers(0, cfg.vocab_size, (8, cfg.max_seq_len)), jnp.int32
        )
        state, loss = step(state, xb, jnp.roll(xb, -1, axis=1))
        if (i + 1) % 10 == 0:
            print(f"step {i + 1}: loss={float(loss):.3f}", flush=True)

    ckpt_dir = ns.ckpt_dir or tempfile.mkdtemp(prefix="gen_ckpt_")
    engine = CheckpointEngine(ckpt_dir, mesh=mesh, standalone=True)
    try:
        assert engine.save_to_storage(int(state.step), state)
        assert engine.wait_saving(timeout=300)
        print(f"checkpointed step {int(state.step)} -> {ckpt_dir}")

        # fresh template (what a separate rollout process would build)
        template, _ = init_train_state(model, x0, mesh, tx)
        restored_step, restored = engine.load(template)
        assert restored is not None, "restore failed"
        print(f"restored step {restored_step}")
    finally:
        engine.shm.unlink()
        engine.close()

    prompt = [int(t) for t in ns.prompt.split(",") if t.strip()]
    toks, mask = left_pad_prompts([prompt], pad_id=0)
    out, omask, logp = generate(
        model,
        restored.params,
        toks,
        mask,
        jax.random.PRNGKey(0),
        SamplingConfig(
            max_new_tokens=ns.max_new, temperature=ns.temperature, top_k=40
        ),
    )
    n = int(np.asarray(omask[0]).sum())
    print(f"prompt {prompt} -> completion {out[0, :n].tolist()}")
    print(f"mean token logprob {float(np.asarray(logp[0, :n]).mean()):.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
