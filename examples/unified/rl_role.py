"""One role process of the demo RL job: real code would run the JAX
trainer / inference rollout here; the demo just proves the contract."""

import os

role = os.environ["DLROVER_ROLE"]
index = os.environ["DLROVER_ROLE_INDEX"]
world = os.environ["DLROVER_ROLE_WORLD"]
slot = os.environ["DLROVER_NODE_SLOT"]
print(f"{role}[{index}/{world}] on node slot {slot}: step done", flush=True)
