"""GRPO over a REAL transformer policy — generation-engine rollout.

The step up from ``grpo_jax.py`` (which learns an 8x8 policy *table*):
here the policy is an actual Llama model and the rollout role samples
completions through the jit-compiled KV-cache generation engine
(:mod:`dlrover_tpu.models.generation`) — the same architecture a real
RLHF job uses, minus only the scale. The reference reaches this shape
by bolting vLLM engines onto Ray actors
(examples/unified/rl/openrlhf/ppo/main.py:26-60); this framework needs
no second inference stack: rollout and learner share one flax module,
weights sync as the raw param pytree, and the engine's behavior
logprobs feed the GRPO importance ratio directly.

Roles (all on the unified runtime, same as grpo_jax.py):

- ``rollout``: ``build_generate_fn`` once, then per batch: group-sample
  G completions per prompt, score via the reward role's typed RPC
  proxy, compute group-normalized advantages, ship
  (prompts, completions, masks, advantages, behavior logprobs) on the
  cluster data queue. Weight refresh = unpack the new param pytree and
  call the SAME compiled function — no reload, no conversion.
- ``reward``: one point per TARGET_TOKEN in the completion.
- ``learner``: teacher-forces prompt+completion through the plain
  training forward, recomputes per-token logps, GRPO clipped objective
  against the engine's behavior logps, adam update, publishes the new
  pytree to MasterKV.

Convergence proof: p(TARGET_TOKEN) under the policy rises from ~1/V to
a clear majority only if generation, queue payloads, reward RPCs, and
pytree weight syncs all carry faithful data end to end.

Run standalone:  python examples/unified/grpo_llm.py
"""

import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

from dlrover_tpu.unified.comm import WeightBus, rpc  # noqa: E402

VOCAB = 16
TARGET_TOKEN = 5
GROUP = 4
PROMPT_LEN = 4  # fixed-length prompts: learner scores on the plain
# training forward (dense slot positions); variable-length/left-padded
# scoring is exercised by tests/test_generation.py
GEN_LEN = 6
PROMPTS_PER_BATCH = int(os.environ.get("GRPO_PROMPTS", "16"))
UPDATES = int(os.environ.get("GRPO_UPDATES", "30"))
OUT_DIR = os.environ.get("GRPO_OUT_DIR", "/tmp/grpo_llm")
CLIP = 0.2
LR = float(os.environ.get("GRPO_LR", "0.05"))


def policy_model():
    """One shared definition — rollout and learner must agree exactly."""
    from dlrover_tpu.models.llama import Llama, LlamaConfig

    return Llama(
        LlamaConfig(
            vocab_size=VOCAB,
            max_seq_len=PROMPT_LEN + GEN_LEN + 2,
            num_layers=1,
            num_heads=2,
            num_kv_heads=1,
            head_dim=8,
            embed_dim=16,
            mlp_dim=32,
            use_remat=False,
        )
    )


# -- reward role -------------------------------------------------------------


class RewardService:
    @rpc()
    def score_batch(self, completions):
        """[B][GEN_LEN] token ids -> [B] float scores."""
        return [
            float(sum(1.0 for t in row if t == TARGET_TOKEN))
            for row in completions
        ]

    @rpc()
    def target_token(self) -> int:
        return TARGET_TOKEN


def _stop_requested(kv, state) -> bool:
    stopped = bool(kv.get("stop"))
    state["stopped"] = stopped
    if not stopped:
        state["saw_running"] = True
        return False
    return state["saw_running"]


def _serve_until_stop(kv, banner: str) -> int:
    print(banner, flush=True)
    stop_state = {"saw_running": False}
    while not _stop_requested(kv, stop_state):
        time.sleep(0.5)
    return 0


def run_reward() -> int:
    from dlrover_tpu.unified import MasterKV
    from dlrover_tpu.unified.comm import export_rpc_instance

    export_rpc_instance("reward", RewardService())
    rc = _serve_until_stop(MasterKV(), "reward service up")
    print("reward done", flush=True)
    return rc


# -- rollout role ------------------------------------------------------------


def run_rollout() -> int:
    import numpy as np

    from dlrover_tpu.common.platform import force_virtual_cpu

    force_virtual_cpu(1)

    import jax
    import jax.numpy as jnp

    from dlrover_tpu.models.generation import (
        SamplingConfig,
        build_generate_fn,
    )
    from dlrover_tpu.unified import (
        MasterDataQueue,
        MasterKV,
        create_rpc_proxy,
    )
    from dlrover_tpu.unified.comm import current_role_index, pack_array

    queue = MasterDataQueue("grpo_experience")
    kv = MasterKV()
    model = policy_model()
    template = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    gen_fn = build_generate_fn(
        model,
        SamplingConfig(max_new_tokens=GEN_LEN, temperature=1.0),
        prompt_width=PROMPT_LEN,
    )
    reward = create_rpc_proxy(
        "reward", RewardService, ns="reward", retry_for=30.0
    )
    try:
        assert reward.target_token() == TARGET_TOKEN
    except (ConnectionError, OSError):
        if kv.get("stop"):
            return 0
        raise

    rng = jax.random.PRNGKey(100 + current_role_index())
    prompt_rng = np.random.default_rng(7 + current_role_index())
    params = template
    bus = WeightBus(kv, name="policy")
    stop_state = {"saw_running": False}
    while True:
        fresh, version = bus.poll(template)
        if fresh is not None:
            params = fresh
        if _stop_requested(kv, stop_state):
            break
        if stop_state["stopped"]:
            time.sleep(0.2)
            continue

        prompts = prompt_rng.integers(
            0, VOCAB, (PROMPTS_PER_BATCH, PROMPT_LEN)
        ).astype(np.int32)
        # group sampling through the compiled engine: repeat each prompt
        # G times, one generate call covers the whole group batch
        flat_prompts = jnp.asarray(np.repeat(prompts, GROUP, axis=0))
        mask = jnp.ones_like(flat_prompts, dtype=bool)
        rng, sub = jax.random.split(rng)
        comps, comp_mask, logps = gen_fn(params, flat_prompts, mask, sub)
        comps = np.asarray(comps)  # [B*G, GEN_LEN]
        comp_mask = np.asarray(comp_mask)
        behavior_logp = np.asarray(logps)

        fut = reward.score_batch.async_call(comps.tolist())
        try:
            scores = np.asarray(fut.result(timeout=60), dtype=np.float32)
        except (ConnectionError, OSError):
            if kv.get("stop"):
                break
            raise
        scores = scores.reshape(PROMPTS_PER_BATCH, GROUP)
        adv = (scores - scores.mean(axis=1, keepdims=True)) / (
            scores.std(axis=1, keepdims=True) + 1e-6
        )
        try:
            queue.put(
                {
                    "prompts": pack_array(prompts),
                    "completions": pack_array(comps),
                    "comp_mask": pack_array(comp_mask),
                    "advantages": pack_array(adv.astype(np.float32)),
                    "behavior_logp": pack_array(
                        behavior_logp.astype(np.float32)
                    ),
                    "theta_version": version,
                },
                timeout=10.0,
                retry_for=30.0,
            )
        except (TimeoutError, ConnectionError, OSError):
            continue
    print("rollout done", flush=True)
    return 0


# -- learner role ------------------------------------------------------------


def run_learner() -> int:
    from dlrover_tpu.common.platform import force_virtual_cpu

    force_virtual_cpu(1)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dlrover_tpu.unified import MasterDataQueue, MasterKV
    from dlrover_tpu.unified.comm import unpack_array

    queue = MasterDataQueue("grpo_experience")
    kv = MasterKV()
    kv.set("stop", False)

    model = policy_model()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    opt = optax.adam(LR)
    opt_state = opt.init(params)

    def loss_fn(params, prompts, comps, comp_mask, adv, behavior_logp):
        # teacher-force prompt+completion on the training forward —
        # identical math to the engine's decode (tests prove it token-
        # exact), so the ratio below is 1.0 on fresh batches
        full = jnp.concatenate([prompts, comps], axis=1)
        logits = model.apply({"params": params}, full).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits[:, PROMPT_LEN - 1 : -1], axis=-1)
        tok_lp = jnp.take_along_axis(lp, comps[..., None], axis=-1)[..., 0]
        m = comp_mask.astype(jnp.float32)
        cur = (tok_lp * m).sum(axis=-1)  # [B*G]
        beh = (behavior_logp * m).sum(axis=-1)
        ratio = jnp.exp(cur - beh)
        clipped = jnp.clip(ratio, 1.0 - CLIP, 1.0 + CLIP)
        obj = jnp.minimum(ratio * adv, clipped * adv)
        return -obj.mean()

    @jax.jit
    def update_step(params, opt_state, prompts, comps, comp_mask, adv, beh):
        g = jax.grad(loss_fn)(params, prompts, comps, comp_mask, adv, beh)
        updates, opt_state = opt.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    @jax.jit
    def p_target(params, prompts):
        logits = model.apply({"params": params}, prompts)
        probs = jax.nn.softmax(logits[:, -1].astype(jnp.float32), axis=-1)
        return probs[:, TARGET_TOKEN].mean()

    bus = WeightBus(kv, name="policy")

    def publish(version):
        bus.publish(params, version)

    publish(0)
    probe_prompts = jnp.asarray(
        np.random.default_rng(0).integers(0, VOCAB, (32, PROMPT_LEN)),
        dtype=jnp.int32,
    )
    p0 = float(p_target(params, probe_prompts))
    history = []
    update = 0
    while update < UPDATES:
        # staleness control as in grpo_jax.py: drain, train on the
        # freshest batch, drop the rest (the sample-reuse limit)
        items = queue.get(8, timeout=60.0, retry_for=60.0)
        if not items:
            continue
        item = max(items, key=lambda i: i["theta_version"])
        if item["theta_version"] < update - 2:
            continue
        prompts = jnp.asarray(unpack_array(item["prompts"]))
        comps = jnp.asarray(unpack_array(item["completions"]))
        comp_mask = jnp.asarray(unpack_array(item["comp_mask"]))
        adv = jnp.asarray(unpack_array(item["advantages"]))
        beh = jnp.asarray(unpack_array(item["behavior_logp"]))
        # prompts arrive [B, P]; completions/advantages are grouped —
        # flatten the group axis into the batch for the update
        prompts_rep = jnp.repeat(prompts, GROUP, axis=0)
        adv_flat = adv.reshape(-1)
        params, opt_state = update_step(
            params, opt_state, prompts_rep, comps, comp_mask, adv_flat, beh
        )
        update += 1
        publish(update)
        pt = float(p_target(params, probe_prompts))
        history.append(pt)
        if update % 5 == 0:
            print(f"update {update}: p(target)={pt:.3f}", flush=True)
    kv.set("stop", True)
    os.makedirs(OUT_DIR, exist_ok=True)
    with open(os.path.join(OUT_DIR, "learner_result.json"), "w") as f:
        json.dump(
            {
                "p_target": history[-1] if history else p0,
                "p_target_initial": p0,
                "updates": len(history),
            },
            f,
        )
    print(
        f"learner done: p(target) {p0:.3f} -> {history[-1]:.3f}", flush=True
    )
    return 0


def submit() -> int:
    from dlrover_tpu.unified import RLJobBuilder

    me = [sys.executable, str(pathlib.Path(__file__).resolve())]
    os.environ.setdefault("DLROVER_UNIFIED_P2P_INLINE_MAX", "2048")
    job = (
        RLJobBuilder("grpo-llm")
        .node_num(1)
        .device_per_node(4)
        .trainer(me, num=1, device=2.0)
        .rollout(me, num=1, device=1.0)
        .reward(me, num=1, device=1.0)
        .build()
    )
    master = job.submit(log_dir=os.path.join(OUT_DIR, "logs"))
    status = master.wait(timeout=900)
    print("job finished:", status)
    return 0 if master.succeeded() else 1


def main() -> int:
    role = os.environ.get("DLROVER_ROLE", "")
    if role == "trainer":
        return run_learner()
    if role == "rollout":
        return run_rollout()
    if role == "reward":
        return run_reward()
    if not role:
        return submit()
    print(f"unknown role {role!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
