"""Toy PPO-shaped loop proving the unified role data path end-to-end.

The TPU-native analogue of the reference PPO example
(examples/unified/rl/openrlhf/ppo/main.py:26-60 — rollout generates,
trainer consumes, weights sync back) shrunk to a scalar policy so the
whole loop runs in milliseconds in tests:

- rollout[i]: samples x ~ U(-1,1), acts y = w_rollout * x + noise, puts
  (x, y) experience batches on the shared ``DataQueue("experience")``;
  exports ``set_weights`` (trainer pushes fresh w) and ``shutdown``.
- trainer: owns the queue; SGD-fits w_train so y ≈ TARGET * x from the
  experience stream, pushes w_train to every rollout each SYNC_EVERY
  updates (``RoleGroup("rollout").call(...)``), records progress, and
  shuts the rollouts down when done.

Every arrow rides framework primitives (unified/comm.py): the queue is
the rollout→trainer data path, ``call_role``/``RoleGroup`` the
trainer→rollout weight path, and ``retry_for`` carries both across a
mid-loop rollout kill + restart (the failover e2e in test_unified.py).

Run standalone:  python examples/unified/ppo_toy.py
"""

import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

TARGET = 3.0
UPDATES = int(os.environ.get("PPO_UPDATES", "40"))
BATCH = int(os.environ.get("PPO_BATCH", "8"))
SYNC_EVERY = int(os.environ.get("PPO_SYNC_EVERY", "5"))
OUT_DIR = os.environ.get("PPO_OUT_DIR", "/tmp/ppo_toy")


def run_rollout() -> int:
    import random

    from dlrover_tpu.unified.comm import (
        DataQueue,
        current_role_index,
        export_rpc_method,
    )

    state = {"w": 0.0, "version": -1, "stop": False}

    def set_weights(w: float, version: int) -> int:
        state["w"], state["version"] = float(w), int(version)
        return state["version"]

    def shutdown() -> bool:
        state["stop"] = True
        return True

    export_rpc_method("set_weights", set_weights)
    export_rpc_method("shutdown", shutdown)

    queue = DataQueue("experience")  # trainer owns it; connect by name
    rng = random.Random(1234 + current_role_index())
    sent = 0
    while not state["stop"]:
        batch = []
        for _ in range(BATCH):
            x = rng.uniform(-1.0, 1.0)
            noise = rng.gauss(0.0, 0.05)
            batch.append({"x": x, "y": state["w"] * x + noise})
        try:
            queue.put(batch, timeout=10.0)
            sent += 1
        except (TimeoutError, ConnectionError, OSError):
            # trainer busy or mid-restart: drop the batch, stay alive
            time.sleep(0.1)
        time.sleep(0.005)
    print(f"rollout exiting cleanly after {sent} batches", flush=True)
    return 0


def run_trainer() -> int:
    from dlrover_tpu.unified.comm import DataQueue, RoleGroup

    queue = DataQueue("experience", is_master=True, size=64)
    rollouts = RoleGroup("rollout")  # world from DLROVER_ROLE_WORLDS
    w = 0.0
    lr = 0.4
    history = []
    for update in range(UPDATES):
        samples = []
        while not samples:
            batch = queue.get(1, timeout=30.0, retry_for=60.0)
            samples = batch[0] if batch else []
        # Policy-improvement step on the OBSERVED actions: advantage of
        # the target action over the taken one, (TARGET*x - y) * x. The
        # taken action y came from the rollout's (lagging) weights, so
        # the fixed point w = TARGET is only reached if the queue
        # payloads AND the weight sync-back both carry real data — a
        # corrupted y breaks convergence, which the e2e asserts on.
        g = 0.0
        for s in samples:
            g += (TARGET * s["x"] - s["y"]) * s["x"]
        w += lr * g / len(samples)
        history.append(w)
        if (update + 1) % SYNC_EVERY == 0:
            # Weight sync back: every rollout instance, with retries
            # riding over a mid-loop rollout restart.
            versions = rollouts.call(
                "set_weights", w, update, retry_for=60.0
            )
            print(f"update {update}: w={w:.3f} synced v{versions}", flush=True)
    os.makedirs(OUT_DIR, exist_ok=True)
    with open(os.path.join(OUT_DIR, "trainer_result.json"), "w") as f:
        json.dump({"w": w, "updates": len(history)}, f)
    rollouts.call("shutdown", retry_for=60.0)
    print(f"trainer done: w={w:.4f} (target {TARGET})", flush=True)
    return 0


def main() -> int:
    role = os.environ.get("DLROVER_ROLE", "")
    if role == "trainer":
        return run_trainer()
    if role == "rollout":
        return run_rollout()
    print(f"unknown role {role!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
