"""GRPO on the unified control plane — a real-array RL pipeline.

The step up from ``ppo_toy.py`` (scalar weights, unix-socket queue):
this example moves REAL jax/numpy tensors through the cluster-wide
runtime the way an LLM RLHF job would (reference shape:
examples/unified/rl/openrlhf/ppo — rollout engines generate, a reward
model scores, the learner updates, weights flow back):

- ``rollout`` (N instances): holds the policy table, samples G
  completions per prompt (group sampling), scores them through the
  REWARD role via a **typed RPC proxy** (``create_rpc_proxy`` —
  same signatures as the server class, ``async_call`` overlaps scoring
  with generation), computes per-group GRPO advantages, and ships
  (prompts, completions, advantages, behavior logits) as packed arrays
  on the cluster-wide ``MasterDataQueue`` — batches above the inline
  threshold ride the **peer-to-peer payload path** (bytes go
  producer→learner; the master brokers envelopes).
- ``reward`` (1 instance): exports a ``RewardService`` instance
  (``@rpc`` methods) — completions earn one point per TARGET_TOKEN.
- ``learner`` (trainer): drains the queue, does REAL jax grads (group
  advantage-weighted policy gradient with an importance-ratio clip —
  the GRPO objective), and publishes fresh weights to ``MasterKV``
  every update; rollouts refresh between batches.

Convergence is the end-to-end proof: the learned policy emits
TARGET_TOKEN with high probability ONLY if queue payloads, reward RPCs,
and KV weight syncs all carry faithful data.

Run standalone:  python examples/unified/grpo_jax.py
"""

import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

from dlrover_tpu.unified.comm import rpc  # noqa: E402

VOCAB = 8
TARGET_TOKEN = 5
GROUP = 4  # completions per prompt (the G in GRPO)
GEN_LEN = 4
PROMPTS_PER_BATCH = int(os.environ.get("GRPO_PROMPTS", "64"))
UPDATES = int(os.environ.get("GRPO_UPDATES", "40"))
OUT_DIR = os.environ.get("GRPO_OUT_DIR", "/tmp/grpo_jax")
CLIP = 0.2


# -- reward role -------------------------------------------------------------


class RewardService:
    """Typed protocol both sides share: the reward role exports an
    instance; rollouts talk to it through ``create_rpc_proxy`` with
    these exact signatures. Methods are ``@rpc``-decorated here, on the
    shared class, so the proxy side resolves the same wire names."""

    @rpc()
    def score_batch(self, completions):
        """completions: [B][GEN_LEN] token ids -> [B] float scores."""
        return [
            float(sum(1.0 for t in row if t == TARGET_TOKEN))
            for row in completions
        ]

    @rpc()
    def target_token(self) -> int:
        return TARGET_TOKEN


def run_reward() -> int:
    from dlrover_tpu.unified import MasterKV
    from dlrover_tpu.unified.comm import export_rpc_instance

    export_rpc_instance("reward", RewardService())
    rc = _serve_until_stop(MasterKV(), "reward service up")
    print("reward done", flush=True)
    return rc


# -- dataset role ------------------------------------------------------------


def run_dataset() -> int:
    """Index-addressed prompt server (reference ray_dataloader_iter
    shape: the dataset lives in ONE role, consumers iterate it remotely
    with prefetch). Deterministic by index: the same index always
    yields the same batch, so consumers control replay/resume purely by
    the indices they issue."""
    import numpy as np

    from dlrover_tpu.unified import MasterKV
    from dlrover_tpu.unified.comm import export_rpc_method

    def fetch_prompts(index: int):
        rng = np.random.default_rng(1000 + int(index))
        return rng.integers(0, VOCAB, PROMPTS_PER_BATCH).tolist()

    export_rpc_method("fetch_prompts", fetch_prompts)
    rc = _serve_until_stop(MasterKV(), "dataset role up")
    print("dataset done", flush=True)
    return rc


# -- rollout role ------------------------------------------------------------


def _stop_requested(kv, state) -> bool:
    """Stale-stop-aware check shared by reward and rollout: a stop flag
    seen BEFORE the job was ever observed running is residue of a prior
    incarnation (the KV survives whole-job restarts) and is ignored
    until the restarted learner clears it. The raw flag is stashed in
    ``state["stopped"]`` so callers branch without a second KV read."""
    stopped = bool(kv.get("stop"))
    state["stopped"] = stopped
    if not stopped:
        state["saw_running"] = True
        return False
    return state["saw_running"]


def _serve_until_stop(kv, banner: str) -> int:
    """Passive server roles (reward, dataset) park here until the
    learner's stop flag — stale-stop aware via _stop_requested."""
    print(banner, flush=True)
    stop_state = {"saw_running": False}
    while not _stop_requested(kv, stop_state):
        time.sleep(0.5)
    return 0


def _softmax(x, axis=-1):
    import numpy as np

    x = x - x.max(axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


def run_rollout() -> int:
    import numpy as np

    from dlrover_tpu.unified import (
        MasterDataQueue,
        MasterKV,
        RemoteBatchIterator,
        create_rpc_proxy,
    )
    from dlrover_tpu.unified.comm import (
        current_role_index,
        current_role_world,
        pack_array,
    )

    rng = np.random.default_rng(7 + current_role_index())
    queue = MasterDataQueue("grpo_experience")
    kv = MasterKV()
    # Prompts come from the DATASET role through the prefetching remote
    # iterator (2 fetches in flight, so generation overlaps the RPC);
    # each rollout instance reads a disjoint index stride derived from
    # the role world, so streams never overlap at any instance count.
    # (A restarted rollout REPLAYS its stride from the top — fine for
    # this i.i.d. toy; true resume would persist a start offset.)
    my_index = current_role_index()
    stride = max(1, current_role_world())
    # Split tolerances: boot_retry_for covers a slow-booting dataset
    # role (first fetch), retry_for bounds the worst-case shutdown
    # stall (in-flight fetches retrying against an exited dataset
    # before the stop flag is seen).
    prompt_iter = RemoteBatchIterator(
        "dataset",
        "fetch_prompts",
        prefetch=2,
        index_fn=lambda i: i * stride + my_index,
        retry_for=15.0,
        boot_retry_for=60.0,
    )
    reward = create_rpc_proxy(
        "reward", RewardService, ns="reward", retry_for=30.0
    )
    try:
        assert reward.target_token() == TARGET_TOKEN  # typed round-trip
    except (ConnectionError, OSError):
        # reward already gone: the job is shutting down (stop persists
        # in KV) — exit cleanly instead of burning restarts
        if kv.get("stop"):
            return 0
        raise

    theta = np.zeros((VOCAB, VOCAB), dtype=np.float32)
    version = -1
    stop_state = {"saw_running": False}
    while True:
        blob = kv.get("policy")
        if blob is not None and blob["version"] != version:
            from dlrover_tpu.unified.comm import unpack_array

            theta = unpack_array(blob["theta"])
            version = int(blob["version"])
        if _stop_requested(kv, stop_state):
            break
        if stop_state["stopped"]:  # stale flag: wait for it to clear
            time.sleep(0.2)
            continue

        try:
            prompts = np.asarray(next(prompt_iter), dtype=np.int32)
        except (StopIteration, ConnectionError, OSError):
            if kv.get("stop"):
                break
            raise
        # group sampling: G completions per prompt under the CURRENT
        # policy (token distribution conditioned on the previous token)
        comps = np.zeros(
            (PROMPTS_PER_BATCH, GROUP, GEN_LEN), dtype=np.int32
        )
        prev = np.repeat(prompts[:, None], GROUP, axis=1)
        for t in range(GEN_LEN):
            probs = _softmax(theta[prev])  # [B, G, V]
            # vectorized inverse-CDF draw: one rng call per step
            cdf = probs.reshape(-1, VOCAB).cumsum(axis=1)
            u = rng.random((cdf.shape[0], 1)) * cdf[:, -1:]
            choice = (
                (u < cdf).argmax(axis=1).astype(np.int32).reshape(prev.shape)
            )
            comps[:, :, t] = choice
            prev = choice

        # reward via the typed proxy, async so the next block of numpy
        # work overlaps the RPC
        fut = reward.score_batch.async_call(
            comps.reshape(-1, GEN_LEN).tolist()
        )
        try:
            scores = np.asarray(fut.result(timeout=60), dtype=np.float32)
        except (ConnectionError, OSError):
            # reward exiting under us: the learner just declared stop
            if kv.get("stop"):
                break
            raise
        scores = scores.reshape(PROMPTS_PER_BATCH, GROUP)
        # GRPO: advantage is the group-normalized score
        adv = (scores - scores.mean(axis=1, keepdims=True)) / (
            scores.std(axis=1, keepdims=True) + 1e-6
        )
        try:
            queue.put(
                {
                    "prompts": pack_array(prompts),
                    "completions": pack_array(comps),
                    "advantages": pack_array(adv.astype(np.float32)),
                    # behavior policy weights for the importance ratio
                    "theta_version": version,
                    "theta": pack_array(theta),
                },
                timeout=10.0,
                retry_for=30.0,
            )
        except (TimeoutError, ConnectionError, OSError):
            # learner finished or mid-failover: re-check stop, stay up
            continue
    print("rollout done", flush=True)
    return 0


# -- learner role ------------------------------------------------------------


def run_learner() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dlrover_tpu.common.platform import force_virtual_cpu

    force_virtual_cpu(1)

    from dlrover_tpu.unified import MasterDataQueue, MasterKV
    from dlrover_tpu.unified.comm import pack_array, unpack_array

    queue = MasterDataQueue("grpo_experience")
    kv = MasterKV()
    # a whole-job restart must not inherit the previous run's stop flag
    kv.set("stop", False)

    def loss_fn(theta, prompts, comps, adv, behavior_theta):
        # [B, G, T] token ids; logp under current + behavior policies
        prev = jnp.concatenate(
            [
                jnp.repeat(prompts[:, None, None], GROUP, axis=1),
                comps[:, :, :-1],
            ],
            axis=2,
        )
        def logp_under(th):
            logits = th[prev]  # [B, G, T, V]
            logz = jax.nn.logsumexp(logits, axis=-1)
            tok = jnp.take_along_axis(
                logits, comps[..., None], axis=-1
            )[..., 0]
            return (tok - logz).sum(axis=-1)  # [B, G]

        logp = logp_under(theta)
        logp_b = jax.lax.stop_gradient(logp_under(behavior_theta))
        ratio = jnp.exp(logp - logp_b)
        clipped = jnp.clip(ratio, 1.0 - CLIP, 1.0 + CLIP)
        # GRPO objective: clipped importance-weighted group advantages
        obj = jnp.minimum(ratio * adv, clipped * adv)
        return -obj.mean()

    grad_fn = jax.jit(jax.grad(loss_fn))

    theta = jnp.zeros((VOCAB, VOCAB), dtype=jnp.float32)
    kv.set(
        "policy",
        {"version": 0, "theta": pack_array(np.asarray(theta))},
    )
    lr = 2.5
    mean_rewards = []
    update = 0
    while update < UPDATES:
        # Staleness control: the clip nullifies gradients from batches
        # whose behavior policy lags far behind (that is its JOB), so
        # an off-policy learner that blindly consumes the backlog
        # crawls. Drain what's queued, train on the FRESHEST batch,
        # drop the rest — the sample-reuse limit every real RLHF
        # system applies.
        items = queue.get(8, timeout=60.0, retry_for=60.0)
        if not items:
            continue
        item = max(items, key=lambda i: i["theta_version"])
        if item["theta_version"] < update - 2:
            continue  # entire backlog stale; wait for a fresh rollout
        prompts = jnp.asarray(unpack_array(item["prompts"]))
        comps = jnp.asarray(unpack_array(item["completions"]))
        adv = jnp.asarray(unpack_array(item["advantages"]))
        behavior = jnp.asarray(unpack_array(item["theta"]))
        g = grad_fn(theta, prompts, comps, adv, behavior)
        theta = theta - lr * g
        kv.set(
            "policy",
            {
                "version": update + 1,
                "theta": pack_array(np.asarray(theta)),
            },
        )
        update += 1
        # bookkeeping: how often does the current policy emit TARGET?
        p_target = float(
            np.mean(_softmax(np.asarray(theta))[:, TARGET_TOKEN])
        )
        mean_rewards.append(p_target)
        if update % 5 == 0:
            print(f"update {update}: p(target)={p_target:.3f}", flush=True)
    kv.set("stop", True)
    os.makedirs(OUT_DIR, exist_ok=True)
    with open(os.path.join(OUT_DIR, "learner_result.json"), "w") as f:
        json.dump(
            {"p_target": mean_rewards[-1], "updates": len(mean_rewards)}, f
        )
    print(f"learner done: p(target)={mean_rewards[-1]:.3f}", flush=True)
    return 0


def submit() -> int:
    """Self-submitting driver (reference main.py:26-60 builder shape)."""
    from dlrover_tpu.unified import RLJobBuilder

    me = [sys.executable, str(pathlib.Path(__file__).resolve())]
    # batches here are a few KB; lower the inline threshold so they
    # genuinely ride the peer-to-peer payload path (the claim above)
    os.environ.setdefault("DLROVER_UNIFIED_P2P_INLINE_MAX", "2048")
    job = (
        RLJobBuilder("grpo-jax")
        .node_num(1)
        .device_per_node(4)
        .trainer(me, num=1, device=1.5)
        .rollout(me, num=2, device=0.5)
        .reward(me, num=1, device=0.5)
        .role("dataset", me, num=1, device=0.5)
        .build()
    )
    master = job.submit(log_dir=os.path.join(OUT_DIR, "logs"))
    status = master.wait(timeout=600)
    print("job finished:", status)
    return 0 if master.succeeded() else 1


def main() -> int:
    role = os.environ.get("DLROVER_ROLE", "")
    if role == "trainer":
        return run_learner()
    if role == "rollout":
        return run_rollout()
    if role == "reward":
        return run_reward()
    if role == "dataset":
        return run_dataset()
    if not role:
        return submit()
    print(f"unknown role {role!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
