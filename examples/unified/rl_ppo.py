"""Minimal multi-role RL job on the unified control plane.

The TPU-native analogue of the reference's builder examples
(examples/unified/rl/openrlhf/ppo/main.py:26-60): declare the roles,
their instance counts and per-host device fractions, collocate the
actor with its rollout engine, and submit. Role processes read their
identity from the DLROVER_ROLE* env contract.

Run:  python examples/unified/rl_ppo.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

from dlrover_tpu.unified import RLJobBuilder

HERE = pathlib.Path(__file__).parent


def main() -> int:
    role_script = str(HERE / "rl_role.py")
    job = (
        RLJobBuilder("ppo-demo")
        .node_num(2)
        .device_per_node(4)
        .trainer([sys.executable, role_script], num=2, device=2.0)
        .rollout([sys.executable, role_script], num=2, device=1.0)
        .reward([sys.executable, role_script], num=1, device=0.5)
        .with_collocation("trainer", "rollout")
        .build()
    )
    master = job.submit(log_dir="/tmp/ppo-demo-logs")
    status = master.wait(timeout=60)
    print("job finished:", status)
    return 0 if master.succeeded() else 1


if __name__ == "__main__":
    sys.exit(main())
