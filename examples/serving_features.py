"""Tour of the serving engine's capability pillars in one script.

The reference's serving answer is "deploy vLLM next to the trainer"
(examples/unified/rl/openrlhf/ppo/main.py:26-60 upstream); this
framework owns the stack instead. Each section below exercises one
pillar of models/serving.py on a tiny CPU model:

1. per-row cache layout  — continuous batching with no compaction
2. prefix caching        — a shared system prompt prefilled once
3. constrained decoding  — allowed_tokens (RL action spaces)
4. cancellation          — abort mid-decode, slot freed
5. int8 KV cache         — half the cache bytes per slot
6. speculative serving   — draft K + one-forward verify per round

Run anywhere:

    python examples/serving_features.py

On a real chip, drop the force_virtual_cpu call and size up the model.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from dlrover_tpu.common.platform import force_virtual_cpu  # noqa: E402

force_virtual_cpu(1)

import dataclasses  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from dlrover_tpu.models.generation import SamplingConfig  # noqa: E402
from dlrover_tpu.models.gpt import GPT, GPTConfig  # noqa: E402
from dlrover_tpu.models.serving import (  # noqa: E402
    ContinuousBatchingEngine,
    SpeculativeBatchingEngine,
)

CFG = GPTConfig(
    vocab_size=128, max_seq_len=512, num_layers=2, num_heads=4,
    head_dim=8, embed_dim=32, use_remat=False,
)


def main():
    model = GPT(CFG)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    greedy = SamplingConfig(max_new_tokens=12, temperature=0.0)

    # 1. per-row continuous batching (no compaction, per-request slots)
    eng = ContinuousBatchingEngine(
        model, params, greedy, batch_size=3, prompt_width=16,
        decode_chunk=4, cache_layout="per_row",
    )
    out = eng.run([[5, 9, 2], [7, 1], [3, 3, 8], [11, 4, 2, 6]])
    print(f"1. per_row: {len(out)} completions, "
          f"ttft {out[0].ttft_s * 1e3:.1f} ms")

    # 2. prefix caching: the system prompt's KV is computed once
    pid = eng.register_prefix([42, 17, 5, 9])
    for sfx in ([7], [3, 1], [8, 8, 2]):
        eng.submit(sfx, prefix_id=pid)
    out = eng.run()
    print(f"2. prefix: {len(out)} suffix-only admissions "
          f"(stats: {eng.stats()['prefix_states_cached']} cached prefix)")

    # 3. constrained decoding: an RL action space of 4 token ids
    actions = [10, 20, 30, 40]
    uid = eng.submit([5, 9, 2], allowed_tokens=actions)
    out = {c.uid: c for c in eng.run()}
    assert all(t in actions for t in out[uid].tokens)
    print(f"3. constrained: emitted {out[uid].tokens[:6]}... all in "
          f"{actions}")

    # 4. cancellation: abort an in-flight request, slot frees
    uid_a = eng.submit(list(range(1, 9)))
    uid_b = eng.submit([2, 2])
    rng = jax.random.PRNGKey(0)
    rng, sub = jax.random.split(rng)
    eng.step(sub)
    eng.cancel(uid_a)
    while eng.pending:
        rng, sub = jax.random.split(rng)
        eng.step(sub)
    done = {c.uid for c in eng.drain_completions()}
    assert uid_a not in done and uid_b in done
    print("4. cancel: aborted request recorded no completion")

    # 5. int8 KV cache: same scheduler, half the cache bytes per slot
    eng8 = ContinuousBatchingEngine(
        GPT(dataclasses.replace(CFG, kv_cache_int8=True)), params,
        greedy, batch_size=6, prompt_width=16, cache_layout="per_row",
    )
    out = eng8.run([[5, 9, 2], [7, 1]])
    print(f"5. int8 cache: {len(out)} completions at 2x the slots of "
          f"the bf16 HBM budget")

    # 6. speculative serving: self-draft 3, verify in one forward
    sp = SpeculativeBatchingEngine(
        model, params, greedy, batch_size=2, prompt_width=16,
        num_draft=3,
    )
    out = sp.run([[5, 9, 2], [7, 1], [3, 3, 8]])
    st = sp.stats()
    print(f"6. speculative: {len(out)} completions, acceptance "
          f"{st['spec_acceptance']} over {st['spec_rounds']} rounds")


if __name__ == "__main__":
    main()
