"""MFU attribution probe (r5): decompose the GPT-2 headline step and
re-search the batch/chunk space in a FRESH process.

Why fresh: the driver bench measures the batch ladder late, after the
checkpoint/serving/llama sections have churned HBM — the r5 capture
shows batch48 at 104.5k tok/s (vs 114.9k at b32), a regression that
may be allocator fragmentation rather than a real scaling cliff, and
the ladder's early-break then never tried b64. This probe measures the
same configs with a clean allocator, plus a fwd / fwd+bwd / full-step
decomposition that attributes the non-matmul residual the profiler doc
promises to chase (docs/profiler.md "MFU ceiling analysis").

Run ON the chip (plain env):  python experiments/mfu_probe.py
Emits one JSON line and writes experiments/MFU_PROBE_<ts>.json.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402 — reuse _build/_time_steps/_dispatch_floor/_mfu


def _timed(fn, *args, iters=6, sync=None):
    """Median wall time of fn(*args) minus the dispatch floor, syncing
    on a scalar derived from the output (same methodology as
    bench._time_steps)."""
    import numpy as np

    out = fn(*args)  # compile + warmup
    scalar = sync(out) if sync else out
    _ = float(scalar)
    floor_s = bench._dispatch_floor(scalar)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        scalar = sync(out) if sync else out
        _ = float(scalar)
        times.append(time.perf_counter() - t0)
    return max(float(np.median(times)) - floor_s, 1e-9)


def main():
    smoke = bool(int(os.environ.get("MFU_PROBE_SMOKE", "0")))
    if smoke:
        # sitecustomize overrides jax_platforms post-env-resolution, so
        # JAX_PLATFORMS=cpu alone still grabs the real chip — pin hard.
        from dlrover_tpu.common.platform import force_virtual_cpu

        force_virtual_cpu(1)
    import jax
    import jax.numpy as jnp

    res = {"device": str(jax.devices()[0]), "ts": int(time.time())}
    on_tpu = jax.default_backend() == "tpu"
    res["backend"] = jax.default_backend()
    from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(dp=-1), jax.devices()[:1])
    seq = 128 if smoke else 1024
    base = dict(attention_impl="flash", use_remat=False)
    if smoke:
        base.update(num_layers=2, vocab_size=512)
    b_head = 2 if smoke else 32
    ladder = (2, 3) if smoke else (32, 48, 64)
    chunks = ((2, 64),) if smoke else ((32, 256), (32, 512), (64, 256), (64, 512))

    # --- 1. step decomposition at the headline config (b32) ----------
    n_params = 0
    state = step_fn = x = y = None
    try:
        from dlrover_tpu.models.gpt import cross_entropy_loss

        cfg, state, step_fn, x, y = bench._build(base, b_head, seq, mesh)
        n_params = sum(
            v.size for v in jax.tree_util.tree_leaves(state.params)
        )
        res["n_params_m"] = round(n_params / 1e6, 1)

        from dlrover_tpu.models.gpt import GPT

        model_apply = GPT(cfg).apply

        @jax.jit
        def fwd_only(params, x, y):
            logits = model_apply({"params": params}, x)
            return cross_entropy_loss(logits, y)

        @jax.jit
        def fwd_bwd(params, x, y):
            loss, grads = jax.value_and_grad(
                lambda p: cross_entropy_loss(
                    model_apply({"params": p}, x), y
                )
            )(params)
            # one scalar that depends on every grad leaf: forces the
            # whole backward without fetching the grads to host
            gsum = sum(
                jnp.sum(jnp.abs(g)).astype(jnp.float32)
                for g in jax.tree_util.tree_leaves(grads)
            )
            return loss + 0.0 * gsum

        t_fwd = _timed(fwd_only, state.params, x, y)
        t_fb = _timed(fwd_bwd, state.params, x, y)
        t_step, _st = bench._time_steps(state, step_fn, x, y)
        res[f"b{b_head}_fwd_s"] = round(t_fwd, 4)
        res[f"b{b_head}_fwd_bwd_s"] = round(t_fb, 4)
        res[f"b{b_head}_full_step_s"] = round(t_step, 4)
        res[f"b{b_head}_bwd_s"] = round(t_fb - t_fwd, 4)
        res[f"b{b_head}_opt_overhead_s"] = round(t_step - t_fb, 4)
        res[f"b{b_head}_mfu"] = round(bench._mfu(cfg, n_params, b_head, seq, t_step), 4)
        # fwd MFU on the 2N fwd accounting (2/6 of train FLOPs)
        res[f"b{b_head}_fwd_mfu"] = round(
            bench._mfu(cfg, n_params, b_head, seq, t_fwd) / 3.0, 4
        )
    except Exception as e:  # noqa: BLE001
        res["decomp_error"] = repr(e)[:200]
    finally:
        # release section 1's ~GB of device state even on the failure
        # path — a leaked binding here would fragment HBM into the very
        # ladder this probe exists to measure cleanly
        state = step_fn = x = y = _st = None  # noqa: F841

    # --- 2. fresh-allocator batch ladder -----------------------------
    for b in ladder:
        try:
            cfg, state, step_fn, x, y = bench._build(base, b, seq, mesh)
            if not n_params:  # section 1 failed before counting
                n_params = sum(
                    v.size for v in jax.tree_util.tree_leaves(state.params)
                )
            t, state = bench._time_steps(state, step_fn, x, y)
            res[f"plain_b{b}_step_s"] = round(t, 4)
            res[f"plain_b{b}_tokens_per_s"] = round(b * seq / t, 1)
            res[f"plain_b{b}_mfu"] = round(
                bench._mfu(cfg, n_params, b, seq, t), 4
            )
        except Exception as e:  # noqa: BLE001
            res[f"plain_b{b}_error"] = repr(e)[:160]
        finally:
            state = step_fn = x = y = None  # noqa: F841

    # --- 3. fused-CE chunk sweep (frees logits HBM; may enable b64) --
    for b, chunk in chunks:
        try:
            cfg, state, step_fn, x, y = bench._build(
                dict(base, ce_chunk=chunk), b, seq, mesh
            )
            t, state = bench._time_steps(state, step_fn, x, y)
            key = f"ce{chunk}_b{b}"
            res[f"{key}_step_s"] = round(t, 4)
            res[f"{key}_tokens_per_s"] = round(b * seq / t, 1)
        except Exception as e:  # noqa: BLE001
            res[f"ce{chunk}_b{b}_error"] = repr(e)[:160]
        finally:
            state = step_fn = x = y = None  # noqa: F841

    out = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"MFU_PROBE_{res['ts']}.json",
    )
    with open(out, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res))
    print("wrote", out, file=sys.stderr)
    return 0 if on_tpu else 1


if __name__ == "__main__":
    sys.exit(main())
