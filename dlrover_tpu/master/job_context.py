"""Singleton job state shared across master components.

Reference: ``master/node/job_context.py`` — node tables, job stage, and the
diagnosis action queues live here so the servicer, job manager, and
diagnosis master all see one consistent view.
"""

import threading
import time
from typing import Dict, Optional

from ..common.constants import JobStage, NodeType, PreCheckStatus
from ..common.node import Node
from .diagnosis.action import DiagnosisActionQueue


class JobContext:
    _instance: Optional["JobContext"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._mu = threading.RLock()
        self._nodes: Dict[str, Dict[int, Node]] = {}
        self.job_stage = JobStage.INIT
        self.job_exit_reason = ""
        self.pre_check_status = PreCheckStatus.CHECKING
        self.pre_check_reason = ""
        self.master_actions = DiagnosisActionQueue()  # consumed by master loop
        self.node_actions = DiagnosisActionQueue()  # delivered via heartbeat
        self.start_time = time.time()
        self.last_training_step = 0
        self.last_step_time = 0.0
        # Tunables the master pushes to trainers (reference: paral config
        # tuner + elastic run config merge).
        self.paral_config = None  # comm.ParallelConfig, set by auto-tuner
        self.elastic_run_config: Dict[str, str] = {}

    # -- nodes -------------------------------------------------------------

    def update_node(self, node: Node) -> None:
        with self._mu:
            self._nodes.setdefault(node.node_type, {})[node.node_id] = node

    def get_node(self, node_type: str, node_id: int) -> Optional[Node]:
        with self._mu:
            return self._nodes.get(node_type, {}).get(node_id)

    def get_nodes(self, node_type: str = NodeType.WORKER) -> Dict[int, Node]:
        with self._mu:
            return dict(self._nodes.get(node_type, {}))

    def remove_node(self, node_type: str, node_id: int) -> None:
        with self._mu:
            self._nodes.get(node_type, {}).pop(node_id, None)

    def clear_nodes(self) -> None:
        with self._mu:
            self._nodes.clear()

    # -- job stage ---------------------------------------------------------

    def set_stage(self, stage: str, reason: str = "") -> None:
        with self._mu:
            self.job_stage = stage
            if reason:
                self.job_exit_reason = reason

    def is_stopped(self) -> bool:
        return self.job_stage in (JobStage.STOPPING, JobStage.STOPPED)

    # -- training progress (perf/hang input) -------------------------------

    def report_step(self, step: int, timestamp: float) -> None:
        with self._mu:
            if step >= self.last_training_step:
                self.last_training_step = step
                self.last_step_time = timestamp

    # -- persistence (snapshot / replay) -----------------------------------

    _NODE_FIELDS = (
        "node_type", "node_id", "name", "rank_index", "status", "slice_id",
        "host_ip", "relaunch_count", "max_relaunch_count", "relaunchable",
        "is_released", "exit_reason", "heartbeat_time",
    )

    def export_state(self) -> Dict:
        with self._mu:
            nodes = []
            for per_type in self._nodes.values():
                for node in per_type.values():
                    nodes.append(
                        {f: getattr(node, f) for f in self._NODE_FIELDS}
                    )
            return {
                "nodes": nodes,
                "job_stage": self.job_stage,
                "job_exit_reason": self.job_exit_reason,
                "pre_check_status": self.pre_check_status,
                "pre_check_reason": self.pre_check_reason,
                "last_training_step": self.last_training_step,
                "elastic_run_config": dict(self.elastic_run_config),
            }

    def import_state(self, state: Dict) -> None:
        from ..common.node import Node

        with self._mu:
            self._nodes = {}
            for fields in state.get("nodes") or []:
                node = Node(**{
                    k: v
                    for k, v in fields.items()
                    if k in self._NODE_FIELDS
                })
                self._nodes.setdefault(node.node_type, {})[
                    node.node_id
                ] = node
            self.job_stage = state.get("job_stage", self.job_stage)
            self.job_exit_reason = state.get("job_exit_reason", "")
            self.pre_check_status = state.get(
                "pre_check_status", self.pre_check_status
            )
            self.pre_check_reason = state.get("pre_check_reason", "")
            self.last_training_step = int(
                state.get("last_training_step", 0)
            )
            self.elastic_run_config = dict(
                state.get("elastic_run_config") or {}
            )

    def mark_replayed(self) -> None:
        """Post-replay normalization: heartbeat timestamps replayed from
        the journal predate the outage — re-stamp live nodes NOW so the
        dead-node monitor measures silence from this boot, not from the
        dead master's last observation."""
        import time as _time

        now = _time.time()
        with self._mu:
            for per_type in self._nodes.values():
                for node in per_type.values():
                    if not node.exited() and node.heartbeat_time > 0:
                        node.heartbeat_time = now

    # -- singleton ---------------------------------------------------------

    @classmethod
    def singleton(cls) -> "JobContext":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    @classmethod
    def reset(cls) -> "JobContext":
        with cls._lock:
            cls._instance = cls()
        return cls._instance


def get_job_context() -> JobContext:
    return JobContext.singleton()
