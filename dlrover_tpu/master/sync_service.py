"""Named barrier service for worker groups (reference: sync_service.py:25)."""

import threading
from typing import Dict, Set


class SyncService:
    def __init__(self):
        self._lock = threading.Lock()
        self._syncs: Dict[str, Set[int]] = {}
        self._finished: Set[str] = set()
        self._expected = 0  # 0 → any positive count finishes on explicit finish

    def set_expected(self, count: int) -> None:
        with self._lock:
            self._expected = count

    def join(self, sync_name: str, node_id: int) -> bool:
        with self._lock:
            members = self._syncs.setdefault(sync_name, set())
            members.add(node_id)
            if self._expected and len(members) >= self._expected:
                self._finished.add(sync_name)
            return True

    def finish(self, sync_name: str) -> bool:
        with self._lock:
            self._finished.add(sync_name)
            return True

    def is_finished(self, sync_name: str) -> bool:
        with self._lock:
            return sync_name in self._finished
