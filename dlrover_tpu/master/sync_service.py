"""Named barrier service for worker groups (reference: sync_service.py:25).

Protocol: every member calls ``join``; the barrier finishes when
``expected`` members have joined (``expected`` defaults to the job's
worker count, settable per barrier). Members then poll ``is_finished``.
``finish`` force-completes a barrier (master/admin path).

Crash tolerance: with the master journal attached every membership
mutation is WAL'd and the full barrier state rides the snapshot, so a
restarted master answers ``is_finished`` for barriers that completed
before the crash instead of silently dropping them (pre-journal, every
in-flight barrier wedged its members until their own timeouts).
"""

import threading
from typing import Dict, Set


class SyncService:
    def __init__(self, default_expected: int = 0):
        self._lock = threading.Lock()
        self._syncs: Dict[str, Set[int]] = {}
        self._expected: Dict[str, int] = {}
        self._finished: Set[str] = set()
        self._default_expected = default_expected
        self.journal = None  # set by MasterPersistence.attach

    def _record(self, kind: str, payload: Dict) -> None:
        if self.journal is not None:
            self.journal(kind, payload)

    def set_default_expected(self, count: int) -> None:
        with self._lock:
            self._default_expected = count
            self._record("sync.default", {"count": count})

    def set_expected(self, sync_name: str, count: int) -> None:
        with self._lock:
            self._expected[sync_name] = count
            self._maybe_finish(sync_name)
            self._record("sync.expected", {"name": sync_name, "count": count})

    def join(self, sync_name: str, node_id: int) -> bool:
        """Register a member; returns True if the barrier is now finished."""
        with self._lock:
            self._syncs.setdefault(sync_name, set()).add(node_id)
            self._maybe_finish(sync_name)
            self._record("sync.join", {"name": sync_name, "node": node_id})
            return sync_name in self._finished

    def _maybe_finish(self, sync_name: str) -> None:
        expected = self._expected.get(sync_name, self._default_expected)
        if expected > 0 and len(self._syncs.get(sync_name, ())) >= expected:
            self._finished.add(sync_name)

    def finish(self, sync_name: str) -> bool:
        with self._lock:
            self._finished.add(sync_name)
            self._record("sync.finish", {"name": sync_name})
            return True

    def is_finished(self, sync_name: str) -> bool:
        with self._lock:
            return sync_name in self._finished

    # -- persistence (snapshot / replay) -----------------------------------

    def export_state(self) -> Dict:
        with self._lock:
            return {
                "default_expected": self._default_expected,
                "expected": dict(self._expected),
                "syncs": {k: sorted(v) for k, v in self._syncs.items()},
                "finished": sorted(self._finished),
            }

    def import_state(self, state: Dict) -> None:
        with self._lock:
            self._default_expected = int(state.get("default_expected", 0))
            self._expected = {
                k: int(v) for k, v in (state.get("expected") or {}).items()
            }
            self._syncs = {
                k: set(v) for k, v in (state.get("syncs") or {}).items()
            }
            self._finished = set(state.get("finished") or [])
