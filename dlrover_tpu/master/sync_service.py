"""Named barrier service for worker groups (reference: sync_service.py:25).

Protocol: every member calls ``join``; the barrier finishes when
``expected`` members have joined (``expected`` defaults to the job's
worker count, settable per barrier). Members then poll ``is_finished``.
``finish`` force-completes a barrier (master/admin path).
"""

import threading
from typing import Dict, Set


class SyncService:
    def __init__(self, default_expected: int = 0):
        self._lock = threading.Lock()
        self._syncs: Dict[str, Set[int]] = {}
        self._expected: Dict[str, int] = {}
        self._finished: Set[str] = set()
        self._default_expected = default_expected

    def set_default_expected(self, count: int) -> None:
        with self._lock:
            self._default_expected = count

    def set_expected(self, sync_name: str, count: int) -> None:
        with self._lock:
            self._expected[sync_name] = count
            self._maybe_finish(sync_name)

    def join(self, sync_name: str, node_id: int) -> bool:
        """Register a member; returns True if the barrier is now finished."""
        with self._lock:
            self._syncs.setdefault(sync_name, set()).add(node_id)
            self._maybe_finish(sync_name)
            return sync_name in self._finished

    def _maybe_finish(self, sync_name: str) -> None:
        expected = self._expected.get(sync_name, self._default_expected)
        if expected > 0 and len(self._syncs.get(sync_name, ())) >= expected:
            self._finished.add(sync_name)

    def finish(self, sync_name: str) -> bool:
        with self._lock:
            self._finished.add(sync_name)
            return True

    def is_finished(self, sync_name: str) -> bool:
        with self._lock:
            return sync_name in self._finished
