"""In-process / standalone job master.

Reference: ``master/local_master.py:130`` + the standalone-mode master that
``dlrover-run`` spawns (``elastic_run.py:300-329``). Composes the managers,
serves RPC, and runs the supervision loop. The distributed (cluster) master
in :mod:`dlrover_tpu.master.dist_master` builds on the same composition with
platform schedulers and watchers.
"""

import threading
import time
from typing import Dict, Optional

from ..common.config import get_context
from ..common.constants import (
    CommsType,
    JobExitReason,
    JobStage,
    PreCheckStatus,
    RendezvousName,
)
from ..common.events import MasterEvents
from ..common.log import logger
from ..rpc.server import create_master_server
from .diagnosis.action import DiagnosisActionType, JobAbortionAction
from .job_context import JobContext, get_job_context
from .kv_store import KVStoreService
from .monitor.perf_monitor import PerfMonitor
from .node.job_manager import JobManager
from .rdzv.manager import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
    RendezvousManager,
)
from .servicer import MasterServicer
from .shard.task_manager import TaskManager
from .sync_service import SyncService


class LocalJobMaster:
    def __init__(
        self,
        port: int = 0,
        num_workers: int = 1,
        node_unit: int = 1,
        service_type: str = "",
        fresh_context: bool = True,
    ):
        ctx = get_context()
        if fresh_context:
            JobContext.reset()
            # The metric context is a separate singleton: a fresh master
            # inheriting the PREVIOUS job's device/profiler gauges would
            # misread them as this job's state (stale tpu_timer counts
            # from an earlier in-process job made a later job's hang/
            # device-pressure logic — and tests — see ghost activity).
            from .monitor.metric_context import JobMetricContext

            JobMetricContext.reset()
        self._job_ctx = get_job_context()
        self._events = MasterEvents()

        self.job_manager = JobManager(num_workers=num_workers)
        training_rdzv = ElasticTrainingRendezvousManager()
        training_rdzv.update_rdzv_params(
            min_nodes=1,
            max_nodes=num_workers,
            waiting_timeout=ctx.rdzv_timeout_s,
            node_unit=node_unit,
        )
        check_rdzv = NetworkCheckRendezvousManager()
        check_rdzv.update_rdzv_params(
            min_nodes=1,
            max_nodes=num_workers,
            waiting_timeout=ctx.node_check_timeout_s,
            node_unit=node_unit,
        )
        self.rdzv_managers: Dict[str, RendezvousManager] = {
            RendezvousName.TRAINING: training_rdzv,
            RendezvousName.NETWORK_CHECK: check_rdzv,
        }
        self.task_manager = TaskManager()
        self.kv_store = KVStoreService()
        self.sync_service = SyncService(default_expected=num_workers)
        self.perf_monitor = PerfMonitor()
        # Crash tolerance (master/persistence.py): with a state dir
        # configured, bump the boot epoch and replay the journaled
        # coordination state into the components just built — a
        # SIGKILLed master restarted by its orchestrator resumes the
        # job instead of losing it.
        from .persistence import MasterPersistence

        self.persistence = MasterPersistence.from_env()
        self.master_epoch = 0
        if self.persistence is not None:
            self.master_epoch = self.persistence.boot(self)
        self.servicer = MasterServicer(
            job_manager=self.job_manager,
            rdzv_managers=self.rdzv_managers,
            task_manager=self.task_manager,
            kv_store=self.kv_store,
            sync_service=self.sync_service,
            perf_monitor=self.perf_monitor,
            epoch=self.master_epoch,
        )
        service_type = service_type or ctx.master_comms()
        self._server, self.port = create_master_server(
            self.servicer, service_type, port
        )
        self._run_thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self.exit_reason = ""

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def prepare(self) -> None:
        self._server.start()
        self.job_manager.start()
        # Local mode runs no scheduling pre-check; mark passed so agents
        # blocked on wait_pre_check proceed (reference: local_master.py).
        self._job_ctx.pre_check_status = PreCheckStatus.PASSED
        self._job_ctx.set_stage(JobStage.RUNNING)
        self._events.start(port=self.port)
        if self.persistence is not None:
            # Initial snapshot: a crash before the first WAL compaction
            # must still replay the node table and rdzv params.
            self.persistence.tick(force=True)

    def run_in_background(self) -> None:
        self._run_thread = threading.Thread(
            target=self.run, name="master-run", daemon=True
        )
        self._run_thread.start()

    def run(self) -> None:
        """Supervision loop (reference dist_master.py:276-370)."""
        while not self._stopped.is_set():
            time.sleep(1.0)
            try:
                # Master-level diagnosis actions (e.g. job abortion)
                action = self._job_ctx.master_actions.next_action(-1)
                if action.action_type == DiagnosisActionType.JOB_ABORTION:
                    self._exit(action.config.get("reason", JobExitReason.FATAL_ERROR))
                    return
                if self.job_manager.all_workers_exited():
                    if self.job_manager.all_workers_succeeded():
                        self._exit(JobExitReason.SUCCEEDED)
                    else:
                        self._exit(JobExitReason.FATAL_ERROR)
                    return
                slow = self.task_manager.recover_timeout_tasks()
                if slow:
                    logger.warning("recovered timed-out tasks from nodes %s", slow)
                # Post-replay shard reconciliation + WAL compaction.
                self.task_manager.reconcile_unconfirmed()
                if self.persistence is not None:
                    self.persistence.tick()
                if self.task_manager.finished():
                    logger.info("all dataset tasks completed")
            except Exception:
                logger.exception("master run loop error")

    def _exit(self, reason: str) -> None:
        self.exit_reason = reason
        self._job_ctx.set_stage(JobStage.STOPPED, reason)
        self._events.job_stop(reason)
        logger.info("job master exiting: %s", reason)
        self._stopped.set()

    def stop(self) -> None:
        self._stopped.set()
        self.job_manager.stop()
        if self.persistence is not None:
            self.persistence.tick(force=True)
        self._server.stop()


def run_local_master(
    port: int = 0, num_workers: int = 1, node_unit: int = 1, service_type: str = ""
) -> LocalJobMaster:
    master = LocalJobMaster(
        port=port,
        num_workers=num_workers,
        node_unit=node_unit,
        service_type=service_type,
    )
    master.prepare()
    master.run_in_background()
    return master
