"""Training performance monitor (reference: ``monitor/perf_monitor.py:45``).

Tracks global-step progress and derives step speed; feeds hang detection
(step watermark) and goodput accounting.
"""

import threading
import time
from collections import deque
from typing import Deque, Optional, Tuple


class PerfMonitor:
    def __init__(self, window: int = 64):
        self._lock = threading.Lock()
        self._samples: Deque[Tuple[int, float]] = deque(maxlen=window)
        self._start_time = time.time()
        self._total_steps = 0

    def collect_global_step(self, step: int, timestamp: float = 0.0) -> None:
        timestamp = timestamp or time.time()
        with self._lock:
            if self._samples and step <= self._samples[-1][0]:
                return
            self._samples.append((step, timestamp))
            self._total_steps = step

    def steps_per_second(self) -> float:
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            s0, t0 = self._samples[0]
            s1, t1 = self._samples[-1]
            if t1 <= t0:
                return 0.0
            return (s1 - s0) / (t1 - t0)

    def last_step(self) -> Tuple[int, float]:
        with self._lock:
            return self._samples[-1] if self._samples else (0, 0.0)

    def seconds_since_last_step(self) -> Optional[float]:
        with self._lock:
            if not self._samples:
                return None
            return time.time() - self._samples[-1][1]
