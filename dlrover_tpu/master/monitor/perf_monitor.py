"""Training performance monitor (reference: ``monitor/perf_monitor.py:45``).

Tracks global-step progress and derives step speed; feeds hang detection
(step watermark) and goodput accounting — the reference's headline metric
(README.md:55-56: fault tolerance lifted goodput 69% → 95%). Goodput here
is measured, not assumed: productive seconds are credited per observed
step interval, capped at a tolerance over the running median step time,
so rendezvous rounds, restarts, and hangs show up as the gap between
productive and wall-clock time.
"""

import statistics
import threading
import time
from collections import deque
from typing import Deque, Optional, Tuple

# A step interval beyond this multiple of the median step time is
# downtime (re-rendezvous, restart, hang) — only one median's worth of
# it was actual training.
_STALL_TOLERANCE = 3.0
# The FIRST interval has no median to judge against (and legitimately
# includes the jit compile, 20-40 s on TPU); credit at most this much of
# it so an early crash-recovery hour can neither count as productive nor
# poison the median baseline.
_FIRST_INTERVAL_CAP_S = 120.0


class PerfMonitor:
    def __init__(self, window: int = 64):
        self._lock = threading.Lock()
        self._samples: Deque[Tuple[int, float]] = deque(maxlen=window)
        self._start_time = time.time()
        self._total_steps = 0
        self._productive_s = 0.0
        self._step_dts: Deque[float] = deque(maxlen=window)
        # timestamp of the FIRST step report ever (the samples deque is
        # a sliding window, so its head is not the first)
        self._first_sample_ts = 0.0

    def collect_global_step(self, step: int, timestamp: float = 0.0) -> None:
        timestamp = timestamp or time.time()
        with self._lock:
            if self._samples and step <= self._samples[-1][0]:
                return
            if self._samples:
                # Clamp to monotonic: a report from a host with a
                # lagging clock must not rewind the baseline, or the
                # next interval double-counts the rewound seconds (and
                # seconds_since_last_step would inflate).
                timestamp = max(timestamp, self._samples[-1][1])
                dt = timestamp - self._samples[-1][1]
                if dt > 0:
                    if self._step_dts:
                        median = statistics.median(self._step_dts)
                        if dt <= _STALL_TOLERANCE * median:
                            self._step_dts.append(dt)
                            self._productive_s += dt
                        else:
                            # stall: the step itself cost ~median; the
                            # rest of the gap was downtime
                            self._productive_s += median
                    else:
                        # first interval: no baseline to judge a stall
                        # by; credit it capped (includes jit compile)
                        credited = min(dt, _FIRST_INTERVAL_CAP_S)
                        self._step_dts.append(credited)
                        self._productive_s += credited
            if not self._first_sample_ts:
                self._first_sample_ts = timestamp
            self._samples.append((step, timestamp))
            self._total_steps = step

    def goodput(self) -> float:
        """Productive fraction of wall time since the monitor (≈ the
        job) started; 0.0 until the first step interval lands. Elapsed
        extends to the newest report timestamp so reporter-side clocks
        slightly ahead of ours can't inflate the ratio."""
        return self._goodput(since_first_step=False)

    def training_goodput(self) -> float:
        """Productive fraction of wall time since TRAINING began (the
        first step report). The strict :meth:`goodput` charges
        provisioning (pod scheduling, rendezvous, first worker boot) to
        the job; this one isolates the fault-tolerance machinery's own
        efficiency — the number flash checkpointing and fast recovery
        actually control. Both are reported; neither replaces the
        other."""
        return self._goodput(since_first_step=True)

    def _goodput(self, since_first_step: bool) -> float:
        with self._lock:
            now = time.time()
            if self._samples:
                now = max(now, self._samples[-1][1])
            start = self._start_time
            if since_first_step and self._first_sample_ts:
                start = max(start, self._first_sample_ts)
            elapsed = now - start
            if elapsed <= 0 or self._productive_s <= 0:
                return 0.0
            return min(1.0, self._productive_s / elapsed)

    def steps_per_second(self) -> float:
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            s0, t0 = self._samples[0]
            s1, t1 = self._samples[-1]
            if t1 <= t0:
                return 0.0
            return (s1 - s0) / (t1 - t0)

    def last_step(self) -> Tuple[int, float]:
        with self._lock:
            return self._samples[-1] if self._samples else (0, 0.0)

    def seconds_since_last_step(self) -> Optional[float]:
        with self._lock:
            if not self._samples:
                return None
            return time.time() - self._samples[-1][1]
