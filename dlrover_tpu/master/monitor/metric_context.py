"""Job-level metric context: per-node profiler gauges on the master.

Reference: ``JobMetricContext`` (dlrover/python/common/metric/
context.py:26) filled by the agents' xpu_timer scrapes
(xpu_timer_metric_collector.py:28) and consumed by hang/straggler
diagnosis (diagnosis_master.py:359).
"""

import threading
import time
from typing import Dict, List, Optional


class NodeMetrics:
    def __init__(self, node_id: int):
        self.node_id = node_id
        self.gauges: Dict[str, float] = {}
        self.updated_at: float = 0.0


class JobMetricContext:
    _instance: Optional["JobMetricContext"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._mu = threading.Lock()
        self._nodes: Dict[int, NodeMetrics] = {}

    @classmethod
    def singleton(cls) -> "JobMetricContext":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._instance = None

    def report(self, node_id: int, gauges: Dict[str, float]) -> None:
        with self._mu:
            node = self._nodes.setdefault(node_id, NodeMetrics(node_id))
            node.gauges.update(gauges)
            node.updated_at = time.time()

    def all_gauges(self) -> Dict[int, Dict[str, float]]:
        """{node_id: gauges} snapshot (profiler daemon aggregation)."""
        with self._mu:
            return {
                nid: dict(node.gauges) for nid, node in self._nodes.items()
            }

    def gauge(self, node_id: int, name: str, default: float = 0.0) -> float:
        with self._mu:
            node = self._nodes.get(node_id)
            return node.gauges.get(name, default) if node else default

    def fresh_gauge(
        self, node_id: int, name: str, max_age_s: float, default: float = 0.0
    ) -> float:
        """Gauge value only if the node reported within ``max_age_s`` —
        a stale scrape re-read is not a new observation."""
        now = time.time()
        with self._mu:
            node = self._nodes.get(node_id)
            if node is None or now - node.updated_at > max_age_s:
                return default
            return node.gauges.get(name, default)

    def nodes_with(self, name: str) -> Dict[int, float]:
        with self._mu:
            return {
                nid: n.gauges[name]
                for nid, n in self._nodes.items()
                if name in n.gauges
            }

    def hung_nodes(self, stale_after_s: float = 120.0) -> List[int]:
        """Nodes whose profiler reports a hang (fresh gauges only)."""
        now = time.time()
        with self._mu:
            return sorted(
                nid
                for nid, n in self._nodes.items()
                if n.gauges.get("tpu_timer_hang", 0) > 0
                and now - n.updated_at < stale_after_s
            )


def get_metric_context() -> JobMetricContext:
    return JobMetricContext.singleton()
