"""Master process entry: ``python -m dlrover_tpu.master.main``.

Reference: ``dlrover/python/master/main.py:46,91`` — parse args, build the
platform job args, compose the master, serve until the job finishes.
The standalone launcher (`tpurun --standalone`) spawns exactly this module
as a subprocess (reference elastic_run.py:300-329).
"""

import sys

from ..common.log import logger
from .args import parse_master_args
from .local_master import LocalJobMaster


def run(namespace) -> int:
    from ..common.config import get_context
    from ..common.constants import PlatformType
    from ..common.error_handler import init_error_handler

    init_error_handler()

    if getattr(namespace, "brain_addr", ""):
        get_context().brain_addr = namespace.brain_addr

    if namespace.platform in (
        PlatformType.KUBERNETES,
        PlatformType.GKE_TPU,
        PlatformType.RAY,
    ):
        try:
            from .dist_master import DistributedJobMaster
        except ImportError as e:
            raise SystemExit(
                f"platform {namespace.platform!r} needs the distributed "
                f"master, which failed to import: {e}"
            )
        if namespace.platform == PlatformType.RAY:
            master = DistributedJobMaster.from_ray_args(namespace)
        else:
            master = DistributedJobMaster.from_args(namespace)
    else:
        master = LocalJobMaster(
            port=namespace.port,
            num_workers=namespace.num_workers,
            node_unit=namespace.node_unit,
            service_type=namespace.service_type,
        )
    master.prepare()
    if namespace.port_file:
        with open(namespace.port_file, "w") as f:
            f.write(str(master.port))
    logger.info(
        "job master serving job=%s addr=%s workers=%s",
        namespace.job_name,
        master.addr,
        namespace.num_workers,
    )
    try:
        master.run()
    finally:
        master.stop()
    from ..common.constants import JobExitReason

    return 0 if master.exit_reason == JobExitReason.SUCCEEDED else 1


def main(args=None) -> int:
    return run(parse_master_args(args))


if __name__ == "__main__":
    sys.exit(main())
