"""Master-side rendezvous managers.

Re-creates ``dlrover/python/master/elastic_training/rdzv_manager.py`` for a
JAX world: a completed rendezvous assigns each TPU host its
``process_id`` (its rank in the sorted world) and designates rank 0's
address as the ``jax.distributed`` coordinator.  Membership change =
complete a new rendezvous round = rebuild the global device mesh.

Key behaviors carried over (reference line cites in methods):
- completion when waiting == max_nodes, or ≥ min_nodes after a last-call
  timeout, truncated to a multiple of ``node_unit`` (≙ TPU slice size)
- ``num_nodes_waiting`` only triggers a world restart when enough nodes
  wait to form a unit, or a previous member re-joined (crash-restart)
- network-check rendezvous pairs hosts (adjacent, then fastest-with-
  slowest) to isolate faulty hosts; stragglers = elapsed > ratio × median
"""

import dataclasses
import statistics
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ...common import comm
from ...common.config import get_context
from ...common.constants import NodeCheckConstants, RendezvousName
from ...common.log import logger

# Check rounds per sequence: adjacent pairs, then fastest-with-slowest.
CHECK_ROUNDS = NodeCheckConstants.CHECK_ROUNDS


class NodeTopologyMeta(comm.NodeMeta):
    """Alias retained for reference-parity naming (net_topology.py:23)."""


class TopologySorter:
    """Orders a completed world. Hook for topology-aware placement
    (reference: ``DpTopologySorter`` net_topology.py:53). The default
    groups hosts by slice id then switch id then node rank, so
    data-parallel neighbors land on the same ICI domain and collectives
    cross DCN as little as possible."""

    def sort(self, nodes: Dict[int, comm.NodeMeta]) -> List[comm.NodeMeta]:
        return sorted(
            nodes.values(), key=lambda n: (n.slice_id, n.asw, n.node_rank)
        )


class RendezvousManager:
    def __init__(self, name: str):
        self._name = name
        self._lock = threading.Lock()
        ctx = get_context()
        self._waiting_nodes: Dict[int, comm.NodeMeta] = {}  # node_rank → meta
        self._rdzv_nodes: Dict[int, comm.NodeMeta] = {}  # completed world
        self._latest_members: Set[int] = set()  # node_ranks of last world
        self._rdzv_round = 0
        self._min_nodes = 1
        self._max_nodes = 1
        self._node_unit = 1
        self._waiting_timeout = ctx.rdzv_timeout_s
        self._lastcall_timeout = ctx.rdzv_lastcall_s
        self._lastcall_time = 0.0
        self._start_rdzv_time = 0.0
        self._ckpt_sync_nodes: Dict[int, int] = {}  # node_id → step
        self.journal = None  # set by MasterPersistence.attach

    @property
    def name(self) -> str:
        return self._name

    def update_rdzv_params(
        self, min_nodes: int, max_nodes: int, waiting_timeout: float, node_unit: int
    ) -> None:
        with self._lock:
            self._min_nodes = min_nodes
            self._max_nodes = max_nodes
            self._waiting_timeout = waiting_timeout
            self._node_unit = max(1, node_unit)

    def add_alive_node(self, node_rank: int) -> None:
        pass  # membership is driven by joins; hook for the job manager

    def _on_new_wave(self) -> None:
        """Hook: called (lock held) when a join invalidates the old world."""

    def remove_alive_node(self, node_rank: int) -> None:
        """A node died: drop it from any pending rendezvous so completion
        logic doesn't wait on a ghost (reference rdzv_manager.py:239)."""
        with self._lock:
            if node_rank in self._waiting_nodes:
                del self._waiting_nodes[node_rank]
                logger.info(
                    "%s rdzv: removed dead node %s from waiting", self._name, node_rank
                )

    def join_rendezvous(self, meta: comm.NodeMeta) -> int:
        """A host asks to join the next round (reference :280-337).

        Joining invalidates the previously completed world: a new round is
        forming, and get_comm_world must block (return empty) until it
        completes — otherwise the elastic restart cycle would hand agents
        the stale world forever after a fault.
        """
        with self._lock:
            if self._rdzv_nodes:
                self._rdzv_nodes = {}
                self._on_new_wave()
            if not self._waiting_nodes:
                self._start_rdzv_time = time.time()
            self._waiting_nodes[meta.node_rank] = meta
            self._lastcall_time = time.time()
            logger.info(
                "%s rdzv round %s: node %s joined (%s waiting)",
                self._name,
                self._rdzv_round,
                meta.node_rank,
                len(self._waiting_nodes),
            )
            return self._rdzv_round

    def _check_rdzv_completed(self) -> bool:
        """Caller holds the lock. Reference :156-217."""
        waiting = len(self._waiting_nodes)
        if waiting == self._max_nodes:
            self._complete()
            return True
        if waiting >= self._min_nodes:
            if (
                self._lastcall_time > 0
                and time.time() - self._lastcall_time > self._lastcall_timeout
            ):
                # Truncate to a multiple of node_unit (slice granularity);
                # extra hosts stay waiting for the next round.
                usable = (waiting // self._node_unit) * self._node_unit
                if usable >= self._min_nodes and usable > 0:
                    self._complete(limit=usable)
                    return True
        if (
            self._start_rdzv_time > 0
            and time.time() - self._start_rdzv_time > self._waiting_timeout
        ):
            logger.warning(
                "%s rdzv round %s timed out with %s/%s nodes",
                self._name,
                self._rdzv_round,
                waiting,
                self._min_nodes,
            )
        return False

    def _complete(self, limit: Optional[int] = None) -> None:
        members = sorted(self._waiting_nodes)
        if limit is not None:
            members = members[:limit]
        self._rdzv_nodes = {r: self._waiting_nodes[r] for r in members}
        for r in members:
            del self._waiting_nodes[r]
        self._latest_members = set(members)
        self._rdzv_round += 1
        self._lastcall_time = 0.0
        self._start_rdzv_time = 0.0
        logger.info(
            "%s rdzv round %s completed with %s nodes",
            self._name,
            self._rdzv_round - 1,
            len(self._rdzv_nodes),
        )
        if self.journal is not None:
            # A completed world is the coordination fact a restarted
            # master must replay: re-attaching agents keep training on
            # it (zero worker restarts) when the membership still holds.
            self.journal(
                "rdzv.complete",
                {
                    "rdzv": self._name,
                    "round": self._rdzv_round,
                    "world": [
                        dataclasses.asdict(m)
                        for m in self._rdzv_nodes.values()
                    ],
                },
            )

    def get_comm_world(
        self, node_rank: int
    ) -> Tuple[int, int, Dict[int, comm.NodeMeta]]:
        """Poll for the completed world. Returns (round, group, world);
        world is empty until the rendezvous completes. Ranks (process ids)
        are positions in the topology-sorted world (reference :423)."""
        with self._lock:
            if not self._rdzv_nodes:
                self._check_rdzv_completed()
            if not self._rdzv_nodes:
                return self._rdzv_round, 0, {}
            ordered = TopologySorter().sort(self._rdzv_nodes)
            world = {}
            for process_id, meta in enumerate(ordered):
                world[process_id] = meta
            return self._rdzv_round - 1, 0, world

    def num_nodes_waiting(self) -> int:
        """Reference :355-376: only report waiters (→ world restart) when a
        full node_unit can join or a previous member is re-joining."""
        with self._lock:
            waiting = len(self._waiting_nodes)
            if waiting == 0:
                return 0
            rejoin = any(r in self._latest_members for r in self._waiting_nodes)
            if waiting >= self._node_unit or rejoin:
                return waiting
            return 0

    def clear_waiting_nodes(self) -> None:
        with self._lock:
            self._waiting_nodes.clear()

    def world_size(self) -> int:
        with self._lock:
            return len(self._rdzv_nodes)

    def sync_ckpt_nodes(self, node_id: int, step: int) -> bool:
        """All members report the same step → checkpoint sync done
        (reference :378)."""
        with self._lock:
            self._ckpt_sync_nodes[node_id] = step
            if any(s != step for s in self._ckpt_sync_nodes.values()):
                self._ckpt_sync_nodes = {node_id: step}
                return False
            return len(self._ckpt_sync_nodes) >= len(self._rdzv_nodes) > 0

    # -- persistence (snapshot / replay) -----------------------------------

    def export_state(self) -> Dict:
        """Round counter + the completed world (the part re-attaching
        agents depend on). Waiting joins are deliberately NOT exported:
        a join is lost with the master, and the joiner's epoch-fenced
        re-registration (agent/rendezvous.py) replaces it."""
        with self._lock:
            return {
                "round": self._rdzv_round,
                "world": [
                    dataclasses.asdict(m) for m in self._rdzv_nodes.values()
                ],
                "latest_members": sorted(self._latest_members),
            }

    def import_state(self, state: Dict) -> None:
        with self._lock:
            self._rdzv_round = int(state.get("round", 0))
            self._rdzv_nodes = {}
            for meta in state.get("world") or []:
                m = comm.NodeMeta(**meta)
                self._rdzv_nodes[m.node_rank] = m
            self._latest_members = set(state.get("latest_members") or [])
            self._waiting_nodes = {}
            self._lastcall_time = 0.0
            self._start_rdzv_time = 0.0

    def import_completed_world(self, round_: int, world: List[Dict]) -> None:
        """Replay entry for a WAL'd completion newer than the snapshot.
        ``round_`` is the post-completion round counter."""
        with self._lock:
            if round_ < self._rdzv_round:
                return  # older than what the snapshot already holds
            self._rdzv_round = round_
            self._rdzv_nodes = {}
            for meta in world:
                m = comm.NodeMeta(**meta)
                self._rdzv_nodes[m.node_rank] = m
            self._latest_members = set(self._rdzv_nodes)
            self._waiting_nodes = {}
            self._lastcall_time = 0.0
            self._start_rdzv_time = 0.0


class ElasticTrainingRendezvousManager(RendezvousManager):
    def __init__(self):
        super().__init__(RendezvousName.TRAINING)


class NetworkCheckRendezvousManager(RendezvousManager):
    """Pairwise node-check rendezvous (reference :510-799).

    Round 0 pairs adjacent hosts; round 1 pairs the fastest with the
    slowest, so a fault that shows up in both rounds pins the faulty host
    (its two different partners were each otherwise healthy).
    """

    def __init__(self):
        super().__init__(RendezvousName.NETWORK_CHECK)
        self._node_times: Dict[int, Dict[int, float]] = {}  # round → {node: s}
        self._node_status: Dict[int, Dict[int, bool]] = {}  # round → {node: ok}
        self._check_round = 0
        self._fault_nodes: Set[int] = set()
        self._stragglers: Set[int] = set()
        self._group_cache: Dict[int, List[List[int]]] = {}
        # The master owns the wave→check-round mapping: agents report and
        # poll by the globally-unique rendezvous wave number, so an agent
        # restarting its check loop can never desync the round state
        # machine (it simply echoes back the wave it was handed).
        self._wave_check_round: Dict[int, int] = {}
        self._round_members: Dict[int, Set[int]] = {}  # round → expected

    def get_comm_world(
        self, node_rank: int
    ) -> Tuple[int, int, Dict[int, comm.NodeMeta]]:
        with self._lock:
            if not self._rdzv_nodes:
                self._check_rdzv_completed()
                if self._rdzv_nodes:
                    self._group_cache.clear()
            if not self._rdzv_nodes:
                return self._rdzv_round, 0, {}
            groups = self._group_nodes(self._check_round)
            for group_idx, group in enumerate(groups):
                if node_rank in group:
                    world = {}
                    for process_id, rank in enumerate(sorted(group)):
                        world[process_id] = self._rdzv_nodes[rank]
                    return self._rdzv_round - 1, group_idx, world
            return self._rdzv_round - 1, 0, {}

    def _group_nodes(self, round: int) -> List[List[int]]:
        """Caller holds the lock. Round 0: adjacent pairs (:610-631);
        round 1: fastest paired with slowest (:632-655)."""
        round = round % CHECK_ROUNDS
        if round in self._group_cache:
            return self._group_cache[round]
        ranks = sorted(self._rdzv_nodes)
        groups: List[List[int]] = []
        if round == 0:
            pair: List[int] = []
            for r in ranks:
                pair.append(r)
                if len(pair) == 2:
                    groups.append(pair)
                    pair = []
            if pair:
                groups.append(pair)
        else:
            times = self._node_times.get(0, {})
            ordered = sorted(ranks, key=lambda r: times.get(r, 0.0))
            left, right = 0, len(ordered) - 1
            while left < right:
                groups.append([ordered[left], ordered[right]])
                left += 1
                right -= 1
            if left == right:
                groups.append([ordered[left]])
        self._group_cache[round] = groups
        return groups

    def report_network_check_result(
        self, node_id: int, normal: bool, elapsed: float, round_idx: int = -1
    ) -> None:
        """``round_idx`` is the *wave* number the agent was handed by
        ``get_comm_world`` (echoed back); the master maps it to its check
        round. Unknown/absent wave falls back to the current round."""
        with self._lock:
            r = self._wave_check_round.get(round_idx, self._check_round)
            self._node_times.setdefault(r, {})[node_id] = elapsed
            self._node_status.setdefault(r, {})[node_id] = normal

    def _complete(self, limit: Optional[int] = None) -> None:
        """A completed join wave transitions the check-round state machine.

        Same membership with a full result set for the current round →
        the wave begins the next round (round 1 keeps round-0 times for
        its fastest-with-slowest grouping), wrapping to a fresh sequence
        after the last round. Changed membership (replacement host, late
        elastic joiner, shrink) → fresh sequence: all previous results
        belong to a different world and are dropped. Same membership but
        only partial results → a wave fired mid-round (e.g. agents
        relaunched after an aborted sequence): stay on the round, drop
        the partials.
        """
        prev_members = set(self._latest_members)
        super()._complete(limit)
        self._group_cache.clear()
        new_members = set(self._latest_members)
        reported = self._node_status.get(self._check_round, {})
        if prev_members == new_members and len(reported) >= len(new_members):
            self._check_round += 1
            if self._check_round >= CHECK_ROUNDS:
                self._check_round = 0
                self._node_times.clear()
                self._node_status.clear()
                self._round_members.clear()
            else:
                # leftovers for the newly-opened round can't be trusted
                self._node_status.pop(self._check_round, None)
                self._node_times.pop(self._check_round, None)
        elif prev_members != new_members:
            self._check_round = 0
            self._node_times.clear()
            self._node_status.clear()
            self._round_members.clear()
        else:
            self._node_status.pop(self._check_round, None)
            self._node_times.pop(self._check_round, None)
        wave = self._rdzv_round - 1
        self._wave_check_round[wave] = self._check_round
        self._round_members[self._check_round] = new_members
        # keep the wave map bounded (only recent waves are ever echoed)
        for old in [w for w in self._wave_check_round if w < wave - 8]:
            del self._wave_check_round[old]

    def check_fault_node(self) -> Tuple[List[int], str]:
        """Reference :732. A node is faulty if it reported not-normal in the
        latest round; with two rounds of different pairings, both-round
        failures isolate the true fault."""
        with self._lock:
            if not self._node_status:
                return [], "no check results"
            rounds = sorted(self._node_status)
            latest = self._node_status[rounds[-1]]
            expected = set(self._rdzv_nodes) or set(latest)
            if len(rounds) >= 2:
                first = self._node_status[rounds[-2]]
                fault = {
                    n
                    for n in expected
                    if not latest.get(n, True) and not first.get(n, True)
                }
            else:
                fault = {n for n in expected if not latest.get(n, True)}
            self._fault_nodes = fault
            return sorted(fault), ""

    def detect_stragglers(self) -> List[int]:
        """Reference :784-799: elapsed > ratio × median of the round."""
        with self._lock:
            if not self._node_times:
                return []
            latest_round = max(self._node_times)
            times = self._node_times[latest_round]
            if len(times) < 2:
                return []
            med = statistics.median(times.values())
            ratio = get_context().straggler_median_ratio
            if med <= 0:
                return []
            stragglers = [n for n, t in times.items() if t > ratio * med]
            self._stragglers = set(stragglers)
            return sorted(stragglers)

    def network_ready(self, wave: int = -1) -> Tuple[bool, str]:
        """All members of the given wave's check round reported → ready.

        ``wave`` is what the agent was handed by ``get_comm_world``;
        membership is the set recorded when that wave completed (it
        survives the next join wave, so late pollers of a finished round
        are not stranded when a fast peer has already re-joined). Without
        a wave, falls back to the latest reported round.
        """
        with self._lock:
            if not self._node_status:
                return False, "no results yet"
            if wave >= 0 and wave in self._wave_check_round:
                r = self._wave_check_round[wave]
            else:
                r = max(self._node_status)
            status = self._node_status.get(r, {})
            expected = len(self._round_members.get(r, set())) or len(
                self._latest_members
            )
            if expected == 0 or len(status) < expected:
                return False, "results pending"
            return True, ""
