"""Master CLI argument parsing.

Reference: ``dlrover/python/master/args.py:22-110`` — job name, platform,
port, node counts and timeouts. The TPU master keeps the same surface but
speaks host/slice instead of pod/PS.
"""

import argparse

from ..common.constants import DefaultValues, PlatformType


def _pos_int(value: str) -> int:
    res = int(value)
    if res <= 0:
        raise argparse.ArgumentTypeError(f"must be positive: {value}")
    return res


def build_master_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="dlrover-tpu job master")
    parser.add_argument("--job_name", default="local_job", help="job name")
    parser.add_argument(
        "--platform",
        default=PlatformType.LOCAL,
        choices=[
            PlatformType.LOCAL,
            PlatformType.KUBERNETES,
            PlatformType.GKE_TPU,
            PlatformType.RAY,
        ],
        help="scheduling platform backing the job",
    )
    parser.add_argument(
        "--port", type=int, default=0, help="RPC port (0 picks a free one)"
    )
    parser.add_argument(
        "--num_workers",
        type=_pos_int,
        default=1,
        help="number of TPU hosts (JAX processes) in the job",
    )
    parser.add_argument(
        "--max_workers",
        type=int,
        default=0,
        help="auto-scale ceiling (0 = fixed at --num_workers)",
    )
    parser.add_argument(
        "--node_unit",
        type=_pos_int,
        default=1,
        help="world sizes must be multiples of this (hosts per slice)",
    )
    parser.add_argument(
        "--service_type",
        default=DefaultValues.SERVICE_TYPE,
        help="master RPC transport: grpc | http | local",
    )
    parser.add_argument(
        "--pending_timeout",
        type=int,
        default=DefaultValues.SEC_TO_WAIT_PENDING_POD,
        help="seconds a node may stay pending before early stop",
    )
    parser.add_argument(
        "--port_file",
        default="",
        help="if set, write the bound RPC port to this file once serving "
        "(lets a parent process discover a port picked with --port 0)",
    )
    parser.add_argument(
        "--brain_addr",
        default="",
        help="cluster Brain service address host:port (empty = disabled); "
        "enables cross-job history-driven resource optimization",
    )
    return parser


def parse_master_args(args=None) -> argparse.Namespace:
    return build_master_parser().parse_args(args)
