"""Distributed job master: full composition for cluster platforms.

Reference: ``DistributedJobMaster`` (dlrover/python/master/
dist_master.py:98): composes JobManager + TaskManager + rendezvous
managers + DiagnosisMaster + PerfMonitor + servicer (:132-166),
``prepare`` starts server & managers (:194), ``run`` is the 30s
supervision loop checking early-stop / all-exited / hang / completion
(:276-370) with the diagnosis action thread (:223).

Platform wiring:
- ``local-proc``: ProcessScaler/ProcessWatcher — worker "hosts" are
  local agent processes (production standalone + chaos harness).
- ``k8s``/``gke_tpu``: PodScaler/PodWatcher (requires the kubernetes
  client in the image).
"""

import threading
import time
from typing import Dict, List, Optional

from ..common.config import get_context
from ..common.constants import (
    JobExitReason,
    JobStage,
    PlatformType,
    PreCheckStatus,
    RendezvousName,
)
from ..common.events import MasterEvents
from ..common.log import logger
from ..observability.metrics import get_registry, maybe_start_metrics_server
from ..rpc.server import create_master_server
from .diagnosis.action import DiagnosisActionType, JobAbortionAction
from .diagnosis.diagnosis_master import (
    ConnectionPreCheckOperator,
    DiagnosisMaster,
    PreCheckOperator,
    SchedulingPreCheckOperator,
)
from .job_context import JobContext, get_job_context
from .kv_store import KVStoreService
from .monitor.perf_monitor import PerfMonitor
from .node.dist_job_manager import DistributedJobManager
from .node.job_auto_scaler import JobAutoScaler
from .rdzv.manager import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
    RendezvousManager,
)
from .resource.optimizer import (
    FixedResourceOptimizer,
    ThroughputScalingOptimizer,
)
from .scaler.base_scaler import NoopScaler, Scaler
from .servicer import MasterServicer
from .shard.task_manager import TaskManager
from .sync_service import SyncService


def ctx_enables_stats() -> bool:
    """The stats sampler only runs when something consumes it (tuning
    or straggler exclusion) — no 10s sampling thread on idle masters."""
    ctx = get_context()
    return ctx.auto_tuning_enabled or ctx.exclude_stragglers


class DistributedJobMaster:
    def __init__(
        self,
        scaler: Scaler,
        watcher=None,
        port: int = 0,
        num_workers: int = 1,
        max_workers: int = 0,
        node_unit: int = 1,
        service_type: str = "",
        job_name: str = "job",
        pre_check_ops: Optional[List[PreCheckOperator]] = None,
        fresh_context: bool = True,
        quota=None,
    ):
        ctx = get_context()
        if fresh_context:
            JobContext.reset()
        self._job_ctx = get_job_context()
        self._events = MasterEvents()
        self.job_name = job_name
        self.num_workers = num_workers
        self.max_workers = max_workers or num_workers

        self.job_manager = DistributedJobManager(
            num_workers=num_workers,
            scaler=scaler,
            watcher=watcher,
            node_unit=node_unit,
        )
        training_rdzv = ElasticTrainingRendezvousManager()
        training_rdzv.update_rdzv_params(
            min_nodes=min(num_workers, self.max_workers),
            max_nodes=self.max_workers,
            waiting_timeout=ctx.rdzv_timeout_s,
            node_unit=node_unit,
        )
        check_rdzv = NetworkCheckRendezvousManager()
        check_rdzv.update_rdzv_params(
            min_nodes=min(num_workers, self.max_workers),
            max_nodes=self.max_workers,
            waiting_timeout=ctx.node_check_timeout_s,
            node_unit=node_unit,
        )
        self.rdzv_managers: Dict[str, RendezvousManager] = {
            RendezvousName.TRAINING: training_rdzv,
            RendezvousName.NETWORK_CHECK: check_rdzv,
        }
        self.task_manager = TaskManager()
        self.kv_store = KVStoreService()
        self.sync_service = SyncService(default_expected=num_workers)
        self.perf_monitor = PerfMonitor()
        # Real-metrics pipeline: per-node runtime series feeding the
        # strategy generator, straggler exclusion, and the diagnosis
        # device-pressure check (reference master/stats/ +
        # simple_strategy_generator.py:40).
        from .stats import JobStatsCollector

        self.stats_collector = JobStatsCollector(self._job_ctx)
        self.diagnosis_master = DiagnosisMaster(
            operators=pre_check_ops
            if pre_check_ops is not None
            else [
                SchedulingPreCheckOperator(expected_workers=num_workers),
                ConnectionPreCheckOperator(expected_workers=num_workers),
            ],
            stats=self.stats_collector,
        )
        optimizer = (
            ThroughputScalingOptimizer(
                self.perf_monitor,
                max_workers=self.max_workers,
                node_unit=node_unit,
            )
            if self.max_workers > num_workers
            else FixedResourceOptimizer()
        )
        from .hyperparams import SimpleStrategyGenerator

        strategy = (
            SimpleStrategyGenerator(
                self.stats_collector,
                host_memory_mb=ctx.host_memory_mb,
                current_batch_size=ctx.initial_batch_size,
            )
            if ctx.auto_tuning_enabled and ctx.initial_batch_size > 0
            else None
        )

        # Cluster Brain (reference brain_optimizer.py:64): when configured,
        # the running-stage optimizer consults cross-job history first and
        # falls back to the local throughput optimizer; a reporter thread
        # persists this job's record + metric samples into the Brain.
        self.brain_reporter = None
        self.brain_create_advice = None
        if ctx.brain_addr:
            from ..brain.client import BrainClient
            from .resource.brain_optimizer import (
                BrainReporter,
                BrainResourceOptimizer,
            )

            brain_client = BrainClient(ctx.brain_addr)
            # Workload-shape profile (fleet-scale warm start): when the
            # submitter supplies model_params (+ optionally
            # global_batch/seq_len/model_arch) in ctx.extra, the job
            # becomes a warm-start donor/consumer by SHAPE — a new
            # model with no signature history borrows shape-similar
            # jobs' scaling curves (brain.datastore.nearest_profiles).
            profile = None
            try:
                n_params = float(ctx.extra.get("model_params", 0) or 0)
                if n_params > 0:
                    from ..brain.datastore import transformer_profile

                    profile = transformer_profile(
                        "",
                        n_params,
                        int(ctx.extra.get("global_batch", 0) or 0),
                        int(ctx.extra.get("seq_len", 0) or 0),
                        arch=str(ctx.extra.get("model_arch", "") or "gpt"),
                    )
            except (TypeError, ValueError) as e:
                # warm-start metadata is optional — malformed values
                # must not fail job startup
                logger.warning("ignoring malformed profile extra: %r", e)
            self.brain_reporter = BrainReporter(
                brain_client,
                job_name=job_name,
                model_signature=ctx.extra.get("model_signature", job_name),
                worker_num=num_workers,
                node_unit=node_unit,
                perf_monitor=self.perf_monitor,
                stats_collector=self.stats_collector,
                world_size_fn=training_rdzv.world_size,
                interval_s=ctx.brain_report_interval_s,
                profile=profile,
            )
            # Create-stage consultation (reference: the Brain sizes new
            # jobs from history before they start). ADVISORY here: the
            # submitter chose num_workers; the advice is recorded (and
            # logged) so operators/auto-tuning can adopt it, without
            # the master silently overriding the requested size. The
            # fetch runs on a daemon thread — an unreachable Brain
            # (retries + 30s transport timeouts) must not delay master
            # construction for advice that is advisory-only.
            def _fetch_create_advice():
                try:
                    plan = brain_client.get_optimization_plan(
                        "create",
                        model_signature=ctx.extra.get(
                            "model_signature", job_name
                        ),
                        node_unit=node_unit,
                        max_workers=self.max_workers,
                        extra=(
                            {"profile": {
                                "param_count": profile.param_count,
                                "flops_per_step": profile.flops_per_step,
                                "tokens_per_batch": (
                                    profile.tokens_per_batch
                                ),
                                "seq_len": profile.seq_len,
                                "arch": profile.arch,
                            }}
                            if profile is not None
                            else None
                        ),
                    )
                    if plan is not None and plan.worker_num > 0:
                        self.brain_create_advice = plan
                        if plan.worker_num != num_workers:
                            logger.info(
                                "brain create-stage advises %s workers "
                                "(requested %s): %s",
                                plan.worker_num, num_workers, plan.reason,
                            )
                except Exception:  # noqa: BLE001 — advisory only
                    logger.debug(
                        "brain create advice unavailable", exc_info=True
                    )

            threading.Thread(
                target=_fetch_create_advice,
                name="brain-create-advice",
                daemon=True,
            ).start()
            optimizer = BrainResourceOptimizer(
                brain_client,
                job_uuid=self.brain_reporter.job_uuid,
                node_unit=node_unit,
                max_workers=self.max_workers,
                world_size_fn=training_rdzv.world_size,
                fallback=optimizer,
            )

        def _exclude_straggler(node_id: int) -> None:
            self.job_manager.migrate_straggler(node_id)

        self._training_rdzv = training_rdzv
        self._node_unit = node_unit

        def _scale_down(target: int) -> None:
            # Drain path: mark the released nodes intentional (no
            # relaunch-budget burn), kill through the scaler, and drop
            # the rendezvous floor so the survivors re-form a world of
            # `target` hosts at the next wave (re-mesh at lower dp).
            removed = self.job_manager.scale_down(target)
            if not removed:
                return
            training_rdzv.update_rdzv_params(
                min_nodes=target,
                max_nodes=self.max_workers,
                waiting_timeout=ctx.rdzv_timeout_s,
                node_unit=node_unit,
            )
            # Named barriers must also expect the smaller world, or two
            # survivors wait forever on a third that no longer exists.
            self.sync_service.set_default_expected(target)
            # Unlike a relaunch (where the REPLACEMENT's rendezvous join
            # announces the new world), a shrink adds no joiner — the
            # survivors would keep running in the old world with dead
            # members wedging every collective. Actively restart their
            # worker groups; the re-joins form the smaller world.
            from .diagnosis.action import DiagnosisActionType, NodeAction

            from ..common.constants import NodeStatus, NodeType

            for node in self._job_ctx.get_nodes(NodeType.WORKER).values():
                # released covers the just-removed nodes too
                if node.is_released or node.status != NodeStatus.RUNNING:
                    continue
                self._job_ctx.node_actions.add_action(
                    NodeAction(
                        node_id=node.node_id,
                        action_type=DiagnosisActionType.RESTART_WORKER,
                        reason="scale_down_remesh",
                    )
                )

        self.scale_down = _scale_down
        self.auto_scaler = JobAutoScaler(
            optimizer=optimizer,
            scaler=scaler,
            node_unit=node_unit,
            max_workers=self.max_workers,
            world_size_fn=training_rdzv.world_size,
            stats=self.stats_collector,
            strategy_generator=strategy,
            straggler_handler=_exclude_straggler,
            shrink_handler=_scale_down,
            quota=quota,
        )
        # Crash tolerance (master/persistence.py): replay the journaled
        # coordination state into the freshly-built components and stamp
        # the new boot epoch on every RPC response so agents re-attach
        # under the epoch fence instead of dying with the old master.
        from .persistence import MasterPersistence

        self.persistence = MasterPersistence.from_env()
        self.master_epoch = 0
        if self.persistence is not None:
            self.master_epoch = self.persistence.boot(self)
        self.servicer = MasterServicer(
            job_manager=self.job_manager,
            rdzv_managers=self.rdzv_managers,
            task_manager=self.task_manager,
            kv_store=self.kv_store,
            sync_service=self.sync_service,
            perf_monitor=self.perf_monitor,
            epoch=self.master_epoch,
        )
        service_type = service_type or ctx.master_comms()
        self._server, self.port = create_master_server(
            self.servicer, service_type, port
        )
        self._stopped = threading.Event()
        self.exit_reason = ""
        self._metrics_server = None
        self._register_metrics()

    def _register_metrics(self) -> None:
        """Bind the master's live objects into the unified registry as
        render-time callbacks: PerfMonitor (step rate/goodput), the
        rendezvous round counters, and the per-node profiler gauges the
        agents report into JobMetricContext — one /metrics (and one
        ``metrics_snapshot()`` for brain/) covers them all."""
        registry = get_registry()
        pm = self.perf_monitor
        registry.gauge_fn(
            "dlrover_job_steps_per_second", pm.steps_per_second
        )
        registry.gauge_fn(
            "dlrover_job_step_time_s",
            lambda: (
                1.0 / pm.steps_per_second() if pm.steps_per_second() > 0 else 0.0
            ),
        )
        registry.gauge_fn("dlrover_job_goodput", pm.goodput)
        registry.gauge_fn(
            "dlrover_job_training_goodput", pm.training_goodput
        )
        registry.gauge_fn(
            "dlrover_job_last_step", lambda: float(pm.last_step()[0])
        )
        registry.gauge_fn(
            "dlrover_job_seconds_since_last_step",
            lambda: pm.seconds_since_last_step() or 0.0,
        )
        for name, mgr in self.rdzv_managers.items():
            registry.gauge_fn(
                f"dlrover_rendezvous_rounds_{name}",
                lambda m=mgr: float(getattr(m, "_rdzv_round", 0)),
            )

        def _node_gauges() -> Dict[str, float]:
            from .monitor.metric_context import get_metric_context

            flat: Dict[str, float] = {}
            for node_id, gauges in get_metric_context().all_gauges().items():
                for gname, value in gauges.items():
                    # Re-label the agent's flattened scrape keys as
                    # per-node series; keys already carrying labels keep
                    # them nested in the name-safe reported form.
                    safe = gname.replace('"', "'")
                    flat[
                        f'dlrover_node_metric{{node="{node_id}",name="{safe}"}}'
                    ] = value
            return flat

        registry.collector(_node_gauges)

    def metrics_snapshot(self) -> Dict[str, float]:
        """Master-side aggregation of the unified plane — the observed-
        signal source for ``brain/`` (ROADMAP item 3)."""
        return get_registry().snapshot()

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    # -- lifecycle ---------------------------------------------------------

    def prepare(self) -> None:
        """Reference dist_master.py:194 — server, managers, pre-check."""
        self._server.start()
        self.job_manager.start()
        if self.brain_reporter is not None:
            self.brain_reporter.start()
        self._job_ctx.set_stage(JobStage.PRE_CHECK)
        self._events.start(port=self.port)
        # Unified metrics plane: off unless DLROVER_METRICS_PORT is set.
        self._metrics_server = maybe_start_metrics_server(
            "DLROVER_METRICS_PORT"
        )
        if self.persistence is not None:
            # Initial snapshot: a crash before the first WAL compaction
            # must still replay the node table and rdzv params.
            self.persistence.tick(force=True)
        # Pre-check runs in the background so prepare() doesn't block the
        # servicer; agents poll get_pre_check_result.
        threading.Thread(
            target=self._run_pre_check, name="pre-check", daemon=True
        ).start()

    def _run_pre_check(self) -> None:
        passed = self.diagnosis_master.pre_check()
        if passed:
            self._job_ctx.set_stage(JobStage.RUNNING)
            self.diagnosis_master.start()
            if ctx_enables_stats():
                self.stats_collector.start()
            self.auto_scaler.start()
        else:
            self._job_ctx.master_actions.add_action(
                JobAbortionAction(reason=JobExitReason.FATAL_ERROR)
            )

    def run_in_background(self) -> None:
        threading.Thread(target=self.run, name="master-run", daemon=True).start()

    def run(self) -> None:
        """Supervision loop (reference dist_master.py:276-370)."""
        while not self._stopped.is_set():
            time.sleep(1.0)
            try:
                action = self._job_ctx.master_actions.next_action(-1)
                if action.action_type == DiagnosisActionType.JOB_ABORTION:
                    self._exit(
                        action.config.get("reason", JobExitReason.FATAL_ERROR)
                    )
                    return
                if getattr(self.job_manager, "is_suspended", False):
                    continue  # suspended: no workers is not completion
                early = self.job_manager.should_early_stop()
                if early:
                    self._exit(early)
                    return
                if self.job_manager.all_workers_exited():
                    if self.job_manager.all_workers_succeeded():
                        self._exit(JobExitReason.SUCCEEDED)
                    else:
                        self._exit(JobExitReason.FATAL_ERROR)
                    return
                slow = self.task_manager.recover_timeout_tasks()
                if slow:
                    logger.warning("recovered tasks from slow nodes %s", slow)
                # Post-replay shard reconciliation + WAL compaction.
                self.task_manager.reconcile_unconfirmed()
                if self.persistence is not None:
                    self.persistence.tick()
            except Exception:
                logger.exception("master run loop error")

    def _exit(self, reason: str) -> None:
        self.exit_reason = reason
        if self.brain_reporter is not None:
            self.brain_reporter.finish(
                "completed" if reason == JobExitReason.SUCCEEDED else "failed"
            )
        self._job_ctx.set_stage(JobStage.STOPPED, reason)
        self._events.job_stop(reason)
        logger.info("distributed master exiting: %s", reason)
        self._stopped.set()

    def stop(self) -> None:
        self._stopped.set()
        if self.persistence is not None:
            self.persistence.tick(force=True)
        if self.brain_reporter is not None:
            self.brain_reporter.stop()
        self.diagnosis_master.stop()
        self.stats_collector.stop()
        self.auto_scaler.stop()
        self.job_manager.stop()
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        self._server.stop()

    # -- construction ------------------------------------------------------

    @classmethod
    def from_args(cls, namespace) -> "DistributedJobMaster":
        """Build from master CLI args (k8s/GKE platforms)."""
        from .scaler.pod_scaler import PodScaler
        from .watcher.k8s_watcher import PodWatcher
        import os

        job_name = namespace.job_name
        namespace_name = os.environ.get("POD_NAMESPACE", "default")
        master_addr = os.environ.get("DLROVER_MASTER_SERVICE_ADDR", "")
        image = os.environ.get("DLROVER_WORKER_IMAGE", "")
        import shlex

        command = shlex.split(os.environ.get("DLROVER_WORKER_COMMAND", ""))
        scaler = PodScaler(
            job_name=job_name,
            image=image,
            command=command,
            master_addr=master_addr,
            namespace=namespace_name,
            owner_uid=os.environ.get("DLROVER_JOB_UID", ""),
        )
        watcher = PodWatcher(job_name, namespace_name)
        from .cluster import K8sQuotaChecker

        master = cls(
            scaler=scaler,
            watcher=watcher,
            quota=K8sQuotaChecker(namespace=namespace_name),
            port=namespace.port,
            num_workers=namespace.num_workers,
            max_workers=getattr(namespace, "max_workers", 0),
            node_unit=namespace.node_unit,
            service_type=namespace.service_type,
            job_name=job_name,
        )
        # CR-driven control: operator/user-posted ScalePlans and the
        # ElasticJob suspend flag (reference k8s_watcher.py:331,427).
        from .watcher.k8s_watcher import ElasticJobWatcher, ScalePlanWatcher

        master.scaleplan_watcher = ScalePlanWatcher(
            job_name, master.execute_scale_plan, namespace_name
        )
        master.elasticjob_watcher = ElasticJobWatcher(
            job_name, master.job_manager, namespace_name
        )
        master.scaleplan_watcher.start()
        master.elasticjob_watcher.start()
        return master

    def execute_scale_plan(self, plan) -> None:
        """Manual/operator scaling entry (ScalePlan CRs): a shrink must
        take the SAME drain path the auto-scaler uses — a raw
        scaler.scale would kill pods that still read as failures,
        burning relaunch budget and resurrecting the removed nodes.

        ``replicas: 0`` means suspend (tear down without failing, keep
        the job resumable) — releasing EVERY worker through scale_down
        would leave a zombie with no completion path. Plans carrying
        explicit removals/launches keep the operator's node choices and
        go to the scaler directly."""
        if plan.worker_num == 0 and not plan.remove_nodes:
            self.job_manager.suspend()
            return
        current = self._training_rdzv.world_size()
        if (
            0 < plan.worker_num < current
            and not plan.launch_nodes
            and not plan.remove_nodes
        ):
            self.scale_down(plan.worker_num)
            return
        self.job_manager._scaler.scale(plan)

    @classmethod
    def from_ray_args(cls, namespace, ray_module=None) -> "DistributedJobMaster":
        """Build for the Ray platform (reference servicer.py:800
        RayMasterServicer + ray_scaler.py:39): nodes are detached
        AgentActors; the agent command inside each actor is the same
        tpurun entrypoint every other platform runs."""
        import os
        import shlex

        from ..scheduler.ray import RayClient
        from .scaler.ray_scaler import ActorScaler
        from .watcher.ray_watcher import ActorWatcher

        job_name = namespace.job_name
        client = RayClient(
            namespace=os.environ.get("RAY_JOB_NAMESPACE", job_name),
            job_name=job_name,
            ray_module=ray_module,
            address=os.environ.get("RAY_ADDRESS", "auto"),
        )
        command = shlex.split(os.environ.get("DLROVER_WORKER_COMMAND", ""))
        if not command:
            # Unlike k8s (empty command -> image CMD), an actor's argv
            # can never be empty; failing fast beats a relaunch storm of
            # actors dying on Popen([]).
            raise SystemExit(
                "the Ray platform needs DLROVER_WORKER_COMMAND set to "
                "the per-host agent command (e.g. 'tpurun ... train.py')"
            )
        resources = {}
        tpu_per_host = os.environ.get("DLROVER_TPU_PER_HOST", "")
        if tpu_per_host:
            resources["TPU"] = float(tpu_per_host)
        scaler = ActorScaler(
            client,
            command=command,
            master_addr=os.environ.get("DLROVER_MASTER_SERVICE_ADDR", ""),
            job_name=job_name,
            num_workers=namespace.num_workers,
            resources_per_node=resources,
        )
        watcher = ActorWatcher(scaler)
        return cls(
            scaler=scaler,
            watcher=watcher,
            port=namespace.port,
            num_workers=namespace.num_workers,
            max_workers=getattr(namespace, "max_workers", 0),
            node_unit=namespace.node_unit,
            service_type=namespace.service_type,
            job_name=job_name,
        )
