"""Watcher over the ProcessScaler's local node processes.

Local analogue of ``PodWatcher`` (reference k8s_watcher.py:251): the
shared :class:`SnapshotWatcher` polls the process table and emits
DELETED events when a node process dies, so the job manager's event
path (watch → _should_relaunch → ScalePlan) is identical across
platforms.
"""

from ..scaler.process_scaler import ProcessScaler
from .base import SnapshotWatcher


class ProcessWatcher(SnapshotWatcher):
    def __init__(self, scaler: ProcessScaler, poll_interval_s: float = 1.0):
        super().__init__(scaler, poll_interval_s)
