"""Watcher over the ProcessScaler's local node processes.

Local analogue of ``PodWatcher`` (reference k8s_watcher.py:251): polls
the process table and emits DELETED events when a node process dies, so
the job manager's event path (watch → _should_relaunch → ScalePlan) is
identical across platforms.
"""

import threading
import time
from typing import Dict, Iterator, List, Optional

from ...common.constants import NodeEventType, NodeExitReason, NodeStatus, NodeType
from ...common.node import Node, NodeEvent
from ..scaler.process_scaler import ProcessScaler
from .base import NodeWatcher


class ProcessWatcher(NodeWatcher):
    def __init__(self, scaler: ProcessScaler, poll_interval_s: float = 1.0):
        self._scaler = scaler
        self._interval = poll_interval_s
        self._stopped = threading.Event()
        self._known: Dict[int, Optional[int]] = {}

    def watch(self) -> Iterator[NodeEvent]:
        while not self._stopped.is_set():
            snapshot = self._scaler.snapshot()
            for node_id, rc in snapshot.items():
                prev = self._known.get(node_id, "absent")
                if prev == "absent" and rc is None:
                    yield self._event(node_id, NodeEventType.ADDED, rc)
                elif (prev == "absent" or prev is None) and rc is not None:
                    yield self._event(node_id, NodeEventType.DELETED, rc)
                self._known[node_id] = rc
            for gone in set(self._known) - set(snapshot):
                del self._known[gone]
            time.sleep(self._interval)

    def _event(
        self, node_id: int, event_type: str, returncode: Optional[int]
    ) -> NodeEvent:
        if event_type == NodeEventType.DELETED:
            status = NodeStatus.FAILED if returncode else NodeStatus.SUCCEEDED
        else:
            status = NodeStatus.RUNNING
        node = Node(
            node_type=NodeType.WORKER,
            node_id=node_id,
            rank_index=node_id,
            status=status,
        )
        if event_type == NodeEventType.DELETED and returncode:
            node.exit_reason = (
                NodeExitReason.KILLED if returncode < 0 else NodeExitReason.FATAL_ERROR
            )
        return NodeEvent(event_type=event_type, node=node)

    def list(self) -> List[Node]:
        return [
            Node(
                node_type=NodeType.WORKER,
                node_id=nid,
                rank_index=nid,
                status=NodeStatus.RUNNING if rc is None else NodeStatus.FAILED,
            )
            for nid, rc in self._scaler.snapshot().items()
        ]

    def stop(self) -> None:
        self._stopped.set()
