"""Pod watcher: k8s pod events → NodeEvents.

Reference: ``PodWatcher`` (dlrover/python/master/watcher/
k8s_watcher.py:251) — list/watch worker pods of the job, translate pod
phases into node status, feed the job manager's event path.
"""

import threading
from typing import Iterator, List, Optional

from ...common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from ...common.log import logger
from ...common.node import Node, NodeEvent
from ...scheduler.kubernetes import (
    ELASTIC_JOB_LABEL,
    REPLICA_INDEX_LABEL,
    k8sClient,
    pod_labels,
    pod_name,
    pod_phase,
)
from .base import NodeWatcher

_PHASE_TO_STATUS = {
    "Pending": NodeStatus.PENDING,
    "Running": NodeStatus.RUNNING,
    "Succeeded": NodeStatus.SUCCEEDED,
    "Failed": NodeStatus.FAILED,
    "Unknown": NodeStatus.BREAKDOWN,
}


def _pod_to_node(pod) -> Optional[Node]:
    labels = pod_labels(pod)
    name = pod_name(pod)
    try:
        node_id = int(name.rsplit("-", 1)[-1])
    except ValueError:
        return None
    rank = int(labels.get(REPLICA_INDEX_LABEL, node_id))
    node = Node(
        node_type=NodeType.WORKER,
        node_id=node_id,
        rank_index=rank,
        status=_PHASE_TO_STATUS.get(pod_phase(pod), NodeStatus.INITIAL),
        name=name,
    )
    if node.status == NodeStatus.FAILED:
        node.exit_reason = _exit_reason(pod)
    return node


def _container_terminations(pod):
    """Yield terminated-state dicts {reason, exit_code, signal} from
    either pod representation."""
    if isinstance(pod, dict):
        statuses = (pod.get("status") or {}).get("containerStatuses") or []
        for cs in statuses:
            term = (cs.get("state") or {}).get("terminated")
            if term:
                yield {
                    "reason": term.get("reason"),
                    "exit_code": term.get("exitCode") or 0,
                    "signal": term.get("signal") or 0,
                }
        return
    statuses = pod.status.container_statuses or []
    for cs in statuses:
        term = cs.state.terminated if cs.state else None
        if term is not None:
            yield {
                "reason": term.reason,
                "exit_code": term.exit_code or 0,
                "signal": term.signal or 0,
            }


def _exit_reason(pod) -> str:
    for term in _container_terminations(pod):
        if term["reason"] == "OOMKilled":
            return NodeExitReason.OOM
        if term["exit_code"] in (137, 143) or term["signal"] in (9, 15):
            return NodeExitReason.KILLED
        if term["exit_code"]:
            return NodeExitReason.FATAL_ERROR
    return NodeExitReason.UNKNOWN


class PodWatcher(NodeWatcher):
    _EVENT_TYPES = {
        "ADDED": NodeEventType.ADDED,
        "MODIFIED": NodeEventType.MODIFIED,
        "DELETED": NodeEventType.DELETED,
    }

    def __init__(self, job_name: str, namespace: str = "default"):
        self._job_name = job_name
        self._selector = f"{ELASTIC_JOB_LABEL}={job_name}"
        self._client = k8sClient.singleton(namespace)
        self._stopped = threading.Event()

    def watch(self) -> Iterator[NodeEvent]:
        while not self._stopped.is_set():
            try:
                for raw in self._client.watch_pods(self._selector):
                    if self._stopped.is_set():
                        return
                    node = _pod_to_node(raw["object"])
                    if node is None:
                        continue
                    event_type = self._EVENT_TYPES.get(
                        raw["type"], NodeEventType.MODIFIED
                    )
                    yield NodeEvent(event_type=event_type, node=node)
            except Exception as e:
                logger.warning("pod watch stream error (retrying): %s", e)

    def list(self) -> List[Node]:
        nodes = []
        for pod in self._client.list_pods(self._selector):
            node = _pod_to_node(pod)
            if node is not None:
                nodes.append(node)
        return nodes

    def stop(self) -> None:
        self._stopped.set()
