"""Pod watcher: k8s pod events → NodeEvents.

Reference: ``PodWatcher`` (dlrover/python/master/watcher/
k8s_watcher.py:251) — list/watch worker pods of the job, translate pod
phases into node status, feed the job manager's event path.
"""

import threading
from typing import Iterator, List, Optional

from ...common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from ...common.log import logger
from ...common.node import Node, NodeEvent
from ...scheduler.kubernetes import (
    ELASTIC_JOB_LABEL,
    REPLICA_INDEX_LABEL,
    k8sClient,
    pod_labels,
    pod_name,
    pod_phase,
)
from .base import NodeWatcher

_PHASE_TO_STATUS = {
    "Pending": NodeStatus.PENDING,
    "Running": NodeStatus.RUNNING,
    "Succeeded": NodeStatus.SUCCEEDED,
    "Failed": NodeStatus.FAILED,
    "Unknown": NodeStatus.BREAKDOWN,
}


def _pod_to_node(pod) -> Optional[Node]:
    labels = pod_labels(pod)
    name = pod_name(pod)
    try:
        node_id = int(name.rsplit("-", 1)[-1])
    except ValueError:
        return None
    rank = int(labels.get(REPLICA_INDEX_LABEL, node_id))
    node = Node(
        node_type=NodeType.WORKER,
        node_id=node_id,
        rank_index=rank,
        status=_PHASE_TO_STATUS.get(pod_phase(pod), NodeStatus.INITIAL),
        name=name,
    )
    if node.status == NodeStatus.FAILED:
        node.exit_reason = _exit_reason(pod)
    return node


def _container_terminations(pod):
    """Yield terminated-state dicts {reason, exit_code, signal} from
    either pod representation."""
    if isinstance(pod, dict):
        statuses = (pod.get("status") or {}).get("containerStatuses") or []
        for cs in statuses:
            term = (cs.get("state") or {}).get("terminated")
            if term:
                yield {
                    "reason": term.get("reason"),
                    "exit_code": term.get("exitCode") or 0,
                    "signal": term.get("signal") or 0,
                }
        return
    statuses = pod.status.container_statuses or []
    for cs in statuses:
        term = cs.state.terminated if cs.state else None
        if term is not None:
            yield {
                "reason": term.reason,
                "exit_code": term.exit_code or 0,
                "signal": term.signal or 0,
            }


def _exit_reason(pod) -> str:
    for term in _container_terminations(pod):
        if term["reason"] == "OOMKilled":
            return NodeExitReason.OOM
        if term["exit_code"] in (137, 143) or term["signal"] in (9, 15):
            return NodeExitReason.KILLED
        if term["exit_code"]:
            return NodeExitReason.FATAL_ERROR
    return NodeExitReason.UNKNOWN


class ScalePlanWatcher:
    """Watch ScalePlan CRs targeting this job and feed them to a callback
    as :class:`ScalePlan`s (reference ``K8sScalePlanWatcher``,
    dlrover/python/master/watcher/k8s_watcher.py:331 — the manual /
    operator-driven scaling path: users or the Brain post a ScalePlan CR,
    the master executes it)."""

    def __init__(self, job_name: str, on_plan, namespace: str = "default"):
        from ...scheduler.kubernetes import (
            CRD_GROUP,
            CRD_VERSION,
            SCALEPLAN_PLURAL,
        )

        self._job_name = job_name
        self._on_plan = on_plan
        self._selector = f"{ELASTIC_JOB_LABEL}={job_name}"
        self._client = k8sClient.singleton(namespace)
        self._stopped = threading.Event()
        self._coords = (CRD_GROUP, CRD_VERSION, SCALEPLAN_PLURAL)
        self._thread: Optional[threading.Thread] = None
        self._seen: set = set()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="scaleplan-watcher", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        group, version, plural = self._coords
        while not self._stopped.is_set():
            try:
                for raw in self._client.watch_custom_objects(
                    group, version, plural, self._selector
                ):
                    if self._stopped.is_set():
                        return
                    if raw.get("type") not in ("ADDED", "MODIFIED"):
                        continue
                    self._handle(raw.get("object") or {})
            except Exception as e:
                logger.warning("scaleplan watch error (retrying): %s", e)
                self._stopped.wait(2.0)

    def _handle(self, obj) -> None:
        meta = obj.get("metadata", {})
        key = (meta.get("name"), meta.get("resourceVersion"))
        if key in self._seen:
            return
        self._seen.add(key)
        plan = scale_plan_from_cr(obj)
        if plan is None:
            return
        logger.info(
            "executing ScalePlan CR %s: worker_num=%s remove=%s",
            meta.get("name"),
            plan.worker_num,
            plan.remove_nodes,
        )
        try:
            self._on_plan(plan)
        except Exception:
            logger.exception("ScalePlan CR execution failed")
            return
        # A ScalePlan CR is a one-shot command: delete it once executed,
        # or a master restart would replay stale plans against a job
        # that has long since scaled elsewhere (the watch re-lists
        # existing objects as ADDED, and _seen starts empty).
        group, version, plural = self._coords
        if meta.get("name") and not self._client.delete_custom_object(
            group, version, plural, meta["name"]
        ):
            logger.warning(
                "executed ScalePlan CR %s could not be deleted; it may "
                "replay on master restart",
                meta.get("name"),
            )

    def stop(self) -> None:
        self._stopped.set()


def scale_plan_from_cr(obj) -> Optional["ScalePlan"]:
    """Parse a ScalePlan CR into a ScalePlan. Spec shape:

    ``spec.replicaResourceSpecs.worker.replicas`` (target count) and/or
    ``spec.removeNodes`` (explicit evictions) — mirroring the reference
    ScalePlan CRD handled in go/elasticjob/pkg/controllers."""
    from ..scaler.base_scaler import ScalePlan

    spec = obj.get("spec") or {}
    worker = (spec.get("replicaResourceSpecs") or {}).get("worker") or {}
    worker_num = int(worker.get("replicas", -1))
    remove = [int(n) for n in spec.get("removeNodes") or []]
    if worker_num < 0 and not remove:
        return None
    return ScalePlan(worker_num=worker_num, remove_nodes=remove)


class ElasticJobWatcher:
    """Watch this job's ElasticJob CR for ``spec.suspend`` flips and
    drive job_manager.suspend()/resume() (reference
    ``K8sElasticJobWatcher``, k8s_watcher.py:427)."""

    def __init__(self, job_name: str, job_manager, namespace: str = "default"):
        from ...scheduler.kubernetes import (
            CRD_GROUP,
            CRD_VERSION,
            ELASTICJOB_PLURAL,
        )

        self._job_name = job_name
        self._job_manager = job_manager
        self._client = k8sClient.singleton(namespace)
        self._stopped = threading.Event()
        self._coords = (CRD_GROUP, CRD_VERSION, ELASTICJOB_PLURAL)
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="elasticjob-watcher", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        group, version, plural = self._coords
        while not self._stopped.is_set():
            try:
                for raw in self._client.watch_custom_objects(
                    group, version, plural
                ):
                    if self._stopped.is_set():
                        return
                    obj = raw.get("object") or {}
                    if obj.get("metadata", {}).get("name") != self._job_name:
                        continue
                    self._apply(obj)
            except Exception as e:
                logger.warning("elasticjob watch error (retrying): %s", e)
                self._stopped.wait(2.0)

    def _apply(self, obj) -> None:
        suspend = bool((obj.get("spec") or {}).get("suspend", False))
        if suspend and not self._job_manager.is_suspended:
            logger.info("ElasticJob CR suspended; tearing down workers")
            self._job_manager.suspend()
        elif not suspend and self._job_manager.is_suspended:
            logger.info("ElasticJob CR resumed; restoring workers")
            self._job_manager.resume()

    def stop(self) -> None:
        self._stopped.set()


class PodWatcher(NodeWatcher):
    _EVENT_TYPES = {
        "ADDED": NodeEventType.ADDED,
        "MODIFIED": NodeEventType.MODIFIED,
        "DELETED": NodeEventType.DELETED,
    }

    def __init__(self, job_name: str, namespace: str = "default"):
        self._job_name = job_name
        self._selector = f"{ELASTIC_JOB_LABEL}={job_name}"
        self._client = k8sClient.singleton(namespace)
        self._stopped = threading.Event()

    def watch(self) -> Iterator[NodeEvent]:
        while not self._stopped.is_set():
            try:
                for raw in self._client.watch_pods(self._selector):
                    if self._stopped.is_set():
                        return
                    node = _pod_to_node(raw["object"])
                    if node is None:
                        continue
                    event_type = self._EVENT_TYPES.get(
                        raw["type"], NodeEventType.MODIFIED
                    )
                    yield NodeEvent(event_type=event_type, node=node)
            except Exception as e:
                logger.warning("pod watch stream error (retrying): %s", e)

    def list(self) -> List[Node]:
        nodes = []
        for pod in self._client.list_pods(self._selector):
            node = _pod_to_node(pod)
            if node is not None:
                nodes.append(node)
        return nodes

    def stop(self) -> None:
        self._stopped.set()
