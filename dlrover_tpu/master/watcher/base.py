"""NodeWatcher ABC (reference master/watcher/k8s_watcher.py shape)."""

import threading
import time
from abc import ABC, abstractmethod
from typing import Dict, Iterator, List, Optional

from ...common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from ...common.node import Node, NodeEvent


class NodeWatcher(ABC):
    @abstractmethod
    def watch(self) -> Iterator[NodeEvent]:
        """Block, yielding node events as the platform reports them."""

    @abstractmethod
    def list(self) -> List[Node]:
        """Snapshot of the platform's current nodes."""

    def stop(self) -> None:
        pass


class SnapshotWatcher(NodeWatcher):
    """Shared poll-based watcher over any scaler exposing
    ``snapshot() -> {node_id: None | exit_code}`` (ProcessScaler,
    ActorScaler). Emits ADDED when an id appears alive and DELETED when
    it exits, mapping exit codes to node status/exit-reason — so the
    job manager's event path is identical across platforms."""

    def __init__(self, scaler, poll_interval_s: float = 1.0):
        self._scaler = scaler
        self._interval = poll_interval_s
        self._stopped = threading.Event()
        self._known: Dict[int, Optional[int]] = {}

    def watch(self) -> Iterator[NodeEvent]:
        while not self._stopped.is_set():
            snapshot = self._scaler.snapshot()
            for node_id, rc in snapshot.items():
                prev = self._known.get(node_id, "absent")
                if prev == "absent" and rc is None:
                    yield self._event(node_id, NodeEventType.ADDED, rc)
                elif (prev == "absent" or prev is None) and rc is not None:
                    yield self._event(node_id, NodeEventType.DELETED, rc)
                self._known[node_id] = rc
            for gone in set(self._known) - set(snapshot):
                del self._known[gone]
            time.sleep(self._interval)

    def _event(
        self, node_id: int, event_type: str, returncode: Optional[int]
    ) -> NodeEvent:
        if event_type == NodeEventType.DELETED:
            status = NodeStatus.FAILED if returncode else NodeStatus.SUCCEEDED
        else:
            status = NodeStatus.RUNNING
        node = Node(
            node_type=NodeType.WORKER,
            node_id=node_id,
            rank_index=node_id,
            status=status,
        )
        if event_type == NodeEventType.DELETED and returncode:
            node.exit_reason = (
                NodeExitReason.KILLED
                if returncode < 0
                else NodeExitReason.FATAL_ERROR
            )
        return NodeEvent(event_type=event_type, node=node)

    def list(self) -> List[Node]:
        return [
            Node(
                node_type=NodeType.WORKER,
                node_id=nid,
                rank_index=nid,
                status=NodeStatus.RUNNING if rc is None else NodeStatus.FAILED,
            )
            for nid, rc in self._scaler.snapshot().items()
        ]

    def stop(self) -> None:
        self._stopped.set()
