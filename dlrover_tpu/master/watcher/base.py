"""NodeWatcher ABC (reference master/watcher/k8s_watcher.py shape)."""

from abc import ABC, abstractmethod
from typing import Iterator, List

from ...common.node import Node, NodeEvent


class NodeWatcher(ABC):
    @abstractmethod
    def watch(self) -> Iterator[NodeEvent]:
        """Block, yielding node events as the platform reports them."""

    @abstractmethod
    def list(self) -> List[Node]:
        """Snapshot of the platform's current nodes."""

    def stop(self) -> None:
        pass
