"""Watcher over the ActorScaler's Ray actors.

Reference: ``dlrover/python/master/watcher/ray_watcher.py``
(ActorWatcher). The actual state machine is the shared
:class:`SnapshotWatcher` (same contract as the ProcessWatcher), so the
job manager's event path (watch → relaunch decision → ScalePlan) is
identical across the process, k8s, and Ray platforms.
"""

from ..scaler.ray_scaler import ActorScaler
from .base import SnapshotWatcher


class ActorWatcher(SnapshotWatcher):
    def __init__(self, scaler: ActorScaler, poll_interval_s: float = 1.0):
        super().__init__(scaler, poll_interval_s)
