"""Node watchers: platform events → NodeEvents (reference master/watcher/)."""
