"""Hyperparameter strategy suggestions from runtime stats.

Reference: ``SimpleStrategyGenerator``
(dlrover/python/master/hyperparams/simple_strategy_generator.py:40) —
emits DataLoaderConfig/OptimizerConfig suggestions that the agent-side
ParalConfigTuner delivers to trainers. TPU shape: the knobs that matter
per-host are the input-pipeline batch size (HBM- and host-RAM-bound) and
gradient accumulation (keeps the global batch constant when the
per-host batch moves); both ride the existing ParallelConfig push.
"""

from typing import Optional

from ...common.log import logger
from ..resource.optimizer import ResourcePlan
from ..stats.job_stats import JobStatsCollector


class SimpleStrategyGenerator:
    """Heuristic tuner (reference :40, :82 data-loader version rules):

    - host memory nearly exhausted → halve the dataloader batch and
      double grad accumulation (same global batch, half the peak RAM)
    - host memory + CPU both far below capacity while training is
      input-bound → double the dataloader batch (fewer, larger host
      transfers; the MXU prefers bigger batches)
    """

    def __init__(
        self,
        stats: JobStatsCollector,
        host_memory_mb: float,
        current_batch_size: int,
        max_batch_size: int = 0,
        high_mem_frac: float = 0.92,
        low_mem_frac: float = 0.45,
        low_cpu_percent: float = 50.0,
        settle_s: float = 180.0,
    ):
        self._stats = stats
        self._host_mem = host_memory_mb
        self._batch = current_batch_size
        self._max_batch = max_batch_size or current_batch_size * 8
        self._high = high_mem_frac
        self._low = low_mem_frac
        self._low_cpu = low_cpu_percent
        # Settle period: a pushed plan needs time to reach the trainers
        # (config poll) and show up in fresh samples; reacting to
        # pre-push memory readings every round would collapse the batch
        # to 1 in a handful of rounds.
        self._settle_s = settle_s
        self._last_push = 0.0
        self._accum = 1

    def generate_plan(self) -> ResourcePlan:
        import time

        if self._batch <= 0 or self._host_mem <= 0:
            return ResourcePlan()
        if time.time() - self._last_push < self._settle_s:
            return ResourcePlan()
        mem = self._stats.mean_memory_mb()
        cpu = self._stats.mean_cpu_percent()
        if mem <= 0:
            return ResourcePlan()
        frac = mem / self._host_mem
        if frac > self._high and self._batch > 1:
            self._batch = max(1, self._batch // 2)
            self._accum *= 2
            logger.info(
                "memory %.0f%%: halving dataloader batch to %s "
                "(grad accum x%s keeps the global batch)",
                frac * 100,
                self._batch,
                self._accum,
            )
            self._last_push = time.time()
            return ResourcePlan(
                dataloader_batch_size=self._batch,
                grad_accum_steps=self._accum,
            )
        if (
            frac < self._low
            and cpu < self._low_cpu
            and self._batch * 2 <= self._max_batch
        ):
            self._batch *= 2
            self._accum = max(1, self._accum // 2)
            logger.info(
                "memory %.0f%% cpu %.0f%%: doubling dataloader batch to %s",
                frac * 100,
                cpu,
                self._batch,
            )
            self._last_push = time.time()
            return ResourcePlan(
                dataloader_batch_size=self._batch,
                grad_accum_steps=self._accum,
            )
        return ResourcePlan()
