from .strategy_generator import SimpleStrategyGenerator  # noqa: F401
