"""Diagnosis actions: what the master tells agents (and itself) to do.

Reference: ``dlrover/python/diagnosis/common/diagnosis_action.py``
(DiagnosisAction:29, NoAction:131, EventAction:136, NodeAction:199,
JobAbortionAction:252, DiagnosisActionQueue:303). Actions ride back to
agents on heartbeat responses (reference servicer.py:783).
"""

import threading
import time
from typing import Dict, List

from ...common import comm
from ...common.constants import DiagnosisConstants
from ...common.log import logger


class DiagnosisActionType:
    NONE = "no_action"
    EVENT = "event"
    RESTART_WORKER = "restart_worker"  # soft: restart the JAX process
    RELAUNCH_WORKER = "relaunch_worker"  # hard: replace the node
    STACK_DUMP = "stack_dump"  # collect the worker's Python stacks
    JOB_ABORTION = "job_abortion"


class DiagnosisAction:
    action_type = DiagnosisActionType.NONE

    def __init__(
        self,
        instance: int = DiagnosisConstants.ANY_INSTANCE,
        expired_s: float = DiagnosisConstants.ACTION_EXPIRY_S,
        config: Dict[str, str] = None,
    ):
        self.instance = instance
        self.timestamp = time.time()
        self.expired_s = expired_s
        self.config = config or {}

    def is_expired(self) -> bool:
        return time.time() > self.timestamp + self.expired_s

    def is_needed(self) -> bool:
        return not self.is_expired() and self.action_type != DiagnosisActionType.NONE

    def to_msg(self) -> comm.DiagnosisActionMsg:
        return comm.DiagnosisActionMsg(
            action_cls=type(self).__name__,
            instance=self.instance,
            timestamp=self.timestamp,
            expired_s=self.expired_s,
            config={k: str(v) for k, v in self.config.items()},
        )


class NoAction(DiagnosisAction):
    action_type = DiagnosisActionType.NONE


class EventAction(DiagnosisAction):
    action_type = DiagnosisActionType.EVENT

    def __init__(self, event_type: str = "", msg: str = "", **kw):
        super().__init__(**kw)
        self.config.setdefault("event_type", event_type)
        self.config.setdefault("msg", msg)


class NodeAction(DiagnosisAction):
    """Restart or relaunch one node's worker process."""

    def __init__(self, node_id: int, action_type: str, reason: str = "", **kw):
        super().__init__(instance=node_id, **kw)
        self.action_type = action_type
        self.config.setdefault("reason", reason)

    @property
    def node_id(self) -> int:
        return self.instance


class JobAbortionAction(DiagnosisAction):
    action_type = DiagnosisActionType.JOB_ABORTION

    def __init__(self, reason: str = "", **kw):
        super().__init__(instance=DiagnosisConstants.MASTER_INSTANCE, **kw)
        self.config.setdefault("reason", reason)


_MSG_CLASSES = {
    "NoAction": NoAction,
    "EventAction": EventAction,
    "NodeAction": NodeAction,
    "JobAbortionAction": JobAbortionAction,
}


def action_from_msg(msg: comm.DiagnosisActionMsg) -> DiagnosisAction:
    cls = _MSG_CLASSES.get(msg.action_cls, NoAction)
    if cls is NodeAction:
        action = NodeAction(
            node_id=msg.instance,
            action_type=msg.config.get("action_type", DiagnosisActionType.RESTART_WORKER),
        )
    elif cls is JobAbortionAction:
        action = JobAbortionAction(reason=msg.config.get("reason", ""))
    elif cls is EventAction:
        action = EventAction()
    else:
        action = NoAction()
    action.timestamp = msg.timestamp or action.timestamp
    action.expired_s = msg.expired_s
    action.config.update(msg.config)
    if cls is NodeAction:
        action.action_type = msg.config.get("action_type", action.action_type)
    return action


def action_to_msg(action: DiagnosisAction) -> comm.DiagnosisActionMsg:
    msg = action.to_msg()
    msg.config["action_type"] = action.action_type
    return msg


class DiagnosisActionQueue:
    """Per-instance queues of pending actions (reference :303)."""

    def __init__(self):
        self._actions: Dict[int, List[DiagnosisAction]] = {}
        self._lock = threading.Lock()

    def add_action(self, action: DiagnosisAction) -> None:
        if not action.is_needed():
            return
        with self._lock:
            queue = self._actions.setdefault(action.instance, [])
            queue.append(action)
            logger.info(
                "queued diagnosis action %s for instance %s",
                action.action_type,
                action.instance,
            )

    def next_action(self, instance: int) -> DiagnosisAction:
        with self._lock:
            for key in (instance, DiagnosisConstants.ANY_INSTANCE):
                queue = self._actions.get(key, [])
                while queue:
                    action = queue.pop(0)
                    if not action.is_expired():
                        return action
            return NoAction()

    def drain_actions(self, instance: int) -> List[DiagnosisAction]:
        actions = []
        while True:
            action = self.next_action(instance)
            if isinstance(action, NoAction):
                return actions
            actions.append(action)

    def clear(self) -> None:
        with self._lock:
            self._actions.clear()
