"""Master-side diagnosis: pre-check chain, hang detection, action loop.

Reference: ``DiagnosisMaster`` (dlrover/python/master/diagnosis/
diagnosis_master.py:73): ``pre_check`` (:100) running an operator chain
(``precheck_operator.py:63`` — SchedulingPreCheckOperator gang-wait
:91, ConnectionPreCheckOperator :352), metric monitors (:272), hang
check (:359 — "tensor-util zero for hang_downtime AND step events
stalled") and the ``_diagnose`` loop (:465) feeding the action queues.

TPU hang signal: no kernel-level NCCL hooks exist for XLA, so the hang
check watermarks *step events* reported by trainers (ElasticContext.
report_step) — a stalled watermark across all hosts for longer than
``hang_downtime_s`` while workers are RUNNING means the job is wedged
(usually a collective stall after a silent host loss); the action is a
job-level restart of the worker group.
"""

import threading
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import List, Optional

from ...common.config import get_context
from ...common.constants import (
    JobExitReason,
    NodeStatus,
    NodeType,
    PreCheckStatus,
)
from ...common.log import logger
from ..job_context import get_job_context
from .action import (
    DiagnosisActionType,
    EventAction,
    JobAbortionAction,
    NodeAction,
)


@dataclass
class PreCheckResult:
    passed: bool = True
    reason: str = ""
    # nodes to relaunch before retrying the check
    abnormal_nodes: List[int] = field(default_factory=list)


class PreCheckOperator(ABC):
    """Reference precheck_operator.py:63."""

    retry_interval_s: float = 2.0
    max_retries: int = 150

    @abstractmethod
    def check(self) -> PreCheckResult:
        ...

    def recover(self, result: PreCheckResult) -> None:
        """Optional recovery between retries (e.g. relaunch bad nodes)."""


class SchedulingPreCheckOperator(PreCheckOperator):
    """Gang-wait: every expected worker is scheduled (RUNNING) before
    training rendezvous proceeds (reference :91)."""

    def __init__(self, expected_workers: int):
        self._expected = expected_workers
        self._job_ctx = get_job_context()

    def check(self) -> PreCheckResult:
        workers = self._job_ctx.get_nodes(NodeType.WORKER)
        running = [
            n for n in workers.values() if n.status == NodeStatus.RUNNING
        ]
        if len(running) >= self._expected:
            return PreCheckResult(passed=True)
        return PreCheckResult(
            passed=False,
            reason=f"{len(running)}/{self._expected} workers scheduled",
        )


class ConnectionPreCheckOperator(PreCheckOperator):
    """All expected agents have opened a control-plane connection
    (heartbeat seen) — reference :352."""

    def __init__(self, expected_workers: int, window_s: float = 120.0):
        self._expected = expected_workers
        self._window = window_s
        self._job_ctx = get_job_context()

    def check(self) -> PreCheckResult:
        now = time.time()
        workers = self._job_ctx.get_nodes(NodeType.WORKER)
        connected = [
            n
            for n in workers.values()
            if n.heartbeat_time > 0 and now - n.heartbeat_time < self._window
        ]
        if len(connected) >= self._expected:
            return PreCheckResult(passed=True)
        return PreCheckResult(
            passed=False,
            reason=f"{len(connected)}/{self._expected} agents connected",
        )


class DiagnosisMaster:
    def __init__(
        self,
        operators: Optional[List[PreCheckOperator]] = None,
        stats=None,
    ):
        from ...diagnosis.diagnostician import TrainingHangDiagnostician

        self._ctx = get_context()
        self._job_ctx = get_job_context()
        self._operators = operators or []
        self._stats = stats  # JobStatsCollector (device-pressure source)
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._hang_since: Optional[float] = None
        self._hang_reported = False
        self._pressure_reported: dict = {}
        self._hang_diagnostician = TrainingHangDiagnostician(
            self._ctx.hang_downtime_s
        )

    # -- pre-check chain ---------------------------------------------------

    def pre_check(self) -> bool:
        """Run the operator chain; sets job-context pre-check status
        (reference diagnosis_master.py:100). Blocking."""
        if not self._ctx.precheck_enabled or not self._operators:
            self._job_ctx.pre_check_status = PreCheckStatus.PASSED
            return True
        self._job_ctx.pre_check_status = PreCheckStatus.CHECKING
        for op in self._operators:
            attempts = 0
            while True:
                result = op.check()
                if result.passed:
                    break
                attempts += 1
                if attempts > op.max_retries:
                    self._job_ctx.pre_check_status = PreCheckStatus.FAILED
                    self._job_ctx.pre_check_reason = result.reason
                    logger.error(
                        "pre-check %s failed: %s",
                        type(op).__name__,
                        result.reason,
                    )
                    return False
                op.recover(result)
                time.sleep(op.retry_interval_s)
        self._job_ctx.pre_check_status = PreCheckStatus.PASSED
        return True

    # -- periodic diagnosis ------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._diagnose_loop, name="diagnosis-master", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        self._thread = None

    def _diagnose_loop(self) -> None:
        interval = max(1.0, self._ctx.monitor_interval_s)
        while not self._stopped.wait(interval):
            try:
                self.observe_once()
            except Exception:
                logger.exception("diagnosis loop error")

    def observe_once(self) -> None:
        if self._ctx.hang_detection_enabled:
            self._check_hang()
            self._check_profiler_hang()
        self._check_device_pressure()

    def _check_device_pressure(self) -> None:
        """Early warning from DEVICE gauges (VERDICT r2 #5): a host
        whose chip duty-cycle collapsed or whose HBM is saturated gets
        flagged as an EVENT action before its step times diverge —
        operators (and the auto-scaler's straggler path) see the cause,
        not just the eventual symptom."""
        if self._stats is None:
            return
        try:
            pressured = self._stats.detect_device_pressure()
        except Exception:  # noqa: BLE001 — advisory path
            logger.exception("device pressure check failed")
            return
        for node_id, reason in pressured.items():
            # Dedup on the condition KIND (text before ':'), not the
            # full message — the embedded floats drift every tick and
            # would re-queue the same condition forever.
            kind = reason.split(":", 1)[0]
            if self._pressure_reported.get(node_id) == kind:
                continue  # one action per distinct condition
            self._pressure_reported[node_id] = kind
            logger.warning(
                "device pressure on node %s: %s", node_id, reason
            )
            self._job_ctx.node_actions.add_action(
                NodeAction(
                    node_id=node_id,
                    action_type=DiagnosisActionType.EVENT,
                    reason=f"device_pressure: {reason}",
                )
            )
        for node_id in list(self._pressure_reported):
            if node_id not in pressured:
                del self._pressure_reported[node_id]

    def _check_profiler_hang(self) -> None:
        """Second hang signal: the native tpu_timer watchdog on each node
        exports ``tpu_timer_hang`` (scraped by the agent, reference
        xpu_timer doHang → :18889 → collector). A node-local hang fires
        faster than the global step watermark and names the node."""
        from ..monitor.metric_context import get_metric_context

        metric_ctx = get_metric_context()
        hung = metric_ctx.hung_nodes()
        if not hung:
            return
        workers = self._job_ctx.get_nodes(NodeType.WORKER)
        for node_id in hung:
            node = workers.get(node_id)
            if node is None or node.status != NodeStatus.RUNNING:
                continue
            if node.reported_unhealthy:
                continue  # already acted on
            node.reported_unhealthy = True
            self._job_ctx.update_node(node)
            # Launch-vs-completion evidence (PJRT interposer): name the
            # side that stalled so operators (and the RELAUNCH-vs-
            # RESTART policy) see device-wedge vs host-loop-stall
            # instead of one undifferentiated "hang".
            verdict = int(
                metric_ctx.gauge(node_id, "tpu_timer_stall_verdict", 0.0)
            )
            cause = {1: "device_stall", 2: "host_stall"}.get(
                verdict, "unknown"
            )
            logger.error(
                "node %s profiler reports a hang (%s); restarting its "
                "worker",
                node_id,
                cause,
            )
            self._job_ctx.node_actions.add_action(
                NodeAction(
                    node_id=node_id,
                    action_type=DiagnosisActionType.RESTART_WORKER,
                    reason=f"profiler_hang:{cause}",
                )
            )

    def _check_hang(self) -> None:
        """Step-watermark hang detection (reference :359 adapted)."""
        last_step_time = self._job_ctx.last_step_time
        if last_step_time <= 0:
            return  # training has not produced a step yet
        workers = self._job_ctx.get_nodes(NodeType.WORKER)
        running = [
            n for n in workers.values() if n.status == NodeStatus.RUNNING
        ]
        if not running:
            self._hang_since = None
            self._hang_reported = False
            return
        stalled_for = time.time() - last_step_time
        if stalled_for < self._ctx.hang_downtime_s:
            self._hang_since = None
            self._hang_reported = False
            return
        if self._hang_reported:
            return
        self._hang_reported = True
        logger.error(
            "hang detected: no training step for %.0fs (> %.0fs) with %s "
            "running workers; restarting worker group",
            stalled_for,
            self._ctx.hang_downtime_s,
            len(running),
        )
        self._job_ctx.master_actions.add_action(
            EventAction(event_type="hang", msg=f"stalled {stalled_for:.0f}s")
        )
        # Route the symptom through the hang diagnostician (reference
        # inferencechain/check+resolve_training_hang_operator): the
        # resolved actions come back ordered — stack dumps first (the
        # post-mortem a restart would destroy), then the group restart
        # whose re-rendezvous clears wedged collectives.
        from ..monitor.metric_context import get_metric_context

        actions = self._hang_diagnostician.diagnose(
            stalled_for_s=stalled_for,
            profiler_hung_nodes=get_metric_context().hung_nodes(),
        )
        for action_type in actions:
            for node in running:
                self._job_ctx.node_actions.add_action(
                    NodeAction(
                        node_id=node.node_id,
                        action_type=action_type,
                        reason="hang",
                    )
                )
