"""Resource plans & optimizers (reference master/resource/)."""
