"""Brain-backed resource optimizer + the master→Brain reporter.

Reference: ``dlrover/python/master/resource/brain_optimizer.py:64``
(``BrainResoureOptimizer`` querying the Brain gRPC service per stage,
with every call degrading to an empty plan on RPC failure) and the
``JobMetricCollector`` → Brain persistence path (``master/stats/``).
"""

import threading
import uuid
from typing import Optional

from ...brain.client import BrainClient
from ...common.log import logger
from .optimizer import ResourceOptimizer, ResourcePlan


class BrainResourceOptimizer(ResourceOptimizer):
    """Running-stage optimizer consulting the cluster Brain, falling back
    to a local optimizer when Brain has no opinion or is unreachable."""

    def __init__(
        self,
        brain_client: BrainClient,
        job_uuid: str,
        node_unit: int = 1,
        max_workers: int = 0,
        world_size_fn=None,
        fallback: Optional[ResourceOptimizer] = None,
    ):
        self._brain = brain_client
        self._job_uuid = job_uuid
        self._node_unit = node_unit
        self._max_workers = max_workers
        self._world_size_fn = world_size_fn or (lambda: 0)
        self._fallback = fallback
        self._init_checks_left = self.INIT_ADJUST_CHECKS
        self._init_attempts_left = self.INIT_ADJUST_MAX_ATTEMPTS

    # The first few rounds consult the Brain's init-adjust stage: a job
    # running far below its cohort at the same size is misconfigured (a
    # slow host, wrong batch) and should be flagged/corrected NOW, not
    # slow-walked by the running-stage knee search. CHECKS counts
    # conclusive verdicts; MAX_ATTEMPTS bounds total RPCs so a job with
    # no cohort (unique model) stops asking after ~2 reporter periods.
    INIT_ADJUST_CHECKS = 3
    INIT_ADJUST_MAX_ATTEMPTS = 20

    def generate_plan(self) -> ResourcePlan:
        current = self._world_size_fn()
        if self._init_checks_left > 0 and self._init_attempts_left > 0:
            self._init_attempts_left -= 1
            resp = self._brain.get_optimization_plan(
                "init_adjust",
                job_uuid=self._job_uuid,
                node_unit=self._node_unit,
                max_workers=self._max_workers,
            )
            # Only a CONCLUSIVE verdict (a cohort comparison actually
            # ran — cohort_ratio present) consumes the window, in
            # either direction: healthy closes it, anomaly closes it
            # and corrects. Inconclusive rounds (no samples yet — the
            # reporter streams every ~30 s while plans run every ~5 s —
            # no cohort, Brain unreachable) keep the check alive so the
            # anomaly scan happens on REAL data, not on startup air.
            if resp is not None and "cohort_ratio" in resp.extra:
                self._init_checks_left = 0
                if resp.extra.get("anomaly"):
                    logger.warning(
                        "brain init-adjust flags this job: %s", resp.reason
                    )
                    if resp.worker_num > 0:
                        return ResourcePlan(worker_num=resp.worker_num)
        resp = self._brain.get_optimization_plan(
            "running",
            job_uuid=self._job_uuid,
            current_workers=current,
            node_unit=self._node_unit,
            max_workers=self._max_workers,
        )
        if resp is not None and resp.worker_num > 0:
            logger.info(
                "brain plan: %s workers (%s)", resp.worker_num, resp.reason
            )
            return ResourcePlan(worker_num=resp.worker_num)
        if self._fallback is not None:
            return self._fallback.generate_plan()
        return ResourcePlan()

    # Delegate the signals the local fallback needs.
    def record_world_size(self, size: int) -> None:
        if self._fallback is not None and hasattr(
            self._fallback, "record_world_size"
        ):
            self._fallback.record_world_size(size)


class BrainReporter:
    """Periodic job→Brain persistence thread (reference JobMetricCollector
    feeding Brain). Registers the job, then streams metric samples from
    the PerfMonitor + stats collector; marks final status on stop."""

    def __init__(
        self,
        brain_client: BrainClient,
        job_name: str,
        model_signature: str = "",
        workload: str = "jax",
        worker_num: int = 0,
        node_unit: int = 1,
        perf_monitor=None,
        stats_collector=None,
        world_size_fn=None,
        interval_s: float = 30.0,
        job_uuid: str = "",
        profile=None,
    ):
        self.job_uuid = job_uuid or f"{job_name}-{uuid.uuid4().hex[:8]}"
        # Optional JobProfile (brain.datastore): reported once at
        # registration so models with no signature history become
        # warm-start donors/consumers by workload shape.
        self._profile = profile
        self._brain = brain_client
        self._job_name = job_name
        self._signature = model_signature
        self._workload = workload
        self._worker_num = worker_num
        self._node_unit = node_unit
        self._perf = perf_monitor
        self._stats = stats_collector
        self._world_size_fn = world_size_fn or (lambda: worker_num)
        self._interval = interval_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._brain.report_job(
            self.job_uuid,
            job_name=self._job_name,
            model_signature=self._signature,
            workload=self._workload,
            worker_num=self._worker_num,
            node_unit=self._node_unit,
            status="running",
        )
        if self._profile is not None:
            self._brain.report_profile(
                self.job_uuid,
                param_count=self._profile.param_count,
                flops_per_step=self._profile.flops_per_step,
                tokens_per_batch=self._profile.tokens_per_batch,
                seq_len=self._profile.seq_len,
                arch=self._profile.arch,
            )
        self._thread = threading.Thread(
            target=self._loop, name="brain-reporter", daemon=True
        )
        self._thread.start()

    def sample_once(self) -> None:
        steps_per_s = (
            self._perf.steps_per_second() if self._perf is not None else 0.0
        )
        peak_mem = cpu = 0.0
        if self._stats is not None:
            peak_mem = self._stats.mean_memory_mb()
            cpu = self._stats.mean_cpu_percent()
        self._brain.report_metrics(
            self.job_uuid,
            world_size=self._world_size_fn(),
            steps_per_second=steps_per_s,
            peak_memory_mb=peak_mem,
            cpu_percent=cpu,
        )

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001
                logger.debug("brain reporting failed", exc_info=True)

    def stop(self) -> None:
        """Stop sampling without recording a final status (master torn
        down externally, e.g. tests); ``finish`` records the outcome."""
        self._stopped.set()
        self._thread = None

    def finish(self, status: str) -> None:
        self._stopped.set()
        try:
            self._brain.report_job(
                self.job_uuid,
                job_name=self._job_name,
                model_signature=self._signature,
                workload=self._workload,
                worker_num=self._world_size_fn() or self._worker_num,
                node_unit=self._node_unit,
                status=status,
            )
        except Exception as e:  # noqa: BLE001 — final report, brain may be gone
            logger.warning("final brain report failed: %r", e)
        self._thread = None
