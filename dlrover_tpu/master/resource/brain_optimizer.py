"""Brain-backed resource optimizer + the master→Brain reporter.

Reference: ``dlrover/python/master/resource/brain_optimizer.py:64``
(``BrainResoureOptimizer`` querying the Brain gRPC service per stage,
with every call degrading to an empty plan on RPC failure) and the
``JobMetricCollector`` → Brain persistence path (``master/stats/``).
"""

import threading
import uuid
from typing import Optional

from ...brain.client import BrainClient
from ...common.log import logger
from .optimizer import ResourceOptimizer, ResourcePlan


class BrainResourceOptimizer(ResourceOptimizer):
    """Running-stage optimizer consulting the cluster Brain, falling back
    to a local optimizer when Brain has no opinion or is unreachable."""

    def __init__(
        self,
        brain_client: BrainClient,
        job_uuid: str,
        node_unit: int = 1,
        max_workers: int = 0,
        world_size_fn=None,
        fallback: Optional[ResourceOptimizer] = None,
    ):
        self._brain = brain_client
        self._job_uuid = job_uuid
        self._node_unit = node_unit
        self._max_workers = max_workers
        self._world_size_fn = world_size_fn or (lambda: 0)
        self._fallback = fallback

    def generate_plan(self) -> ResourcePlan:
        current = self._world_size_fn()
        resp = self._brain.get_optimization_plan(
            "running",
            job_uuid=self._job_uuid,
            current_workers=current,
            node_unit=self._node_unit,
            max_workers=self._max_workers,
        )
        if resp is not None and resp.worker_num > 0:
            logger.info(
                "brain plan: %s workers (%s)", resp.worker_num, resp.reason
            )
            return ResourcePlan(worker_num=resp.worker_num)
        if self._fallback is not None:
            return self._fallback.generate_plan()
        return ResourcePlan()

    # Delegate the signals the local fallback needs.
    def record_world_size(self, size: int) -> None:
        if self._fallback is not None and hasattr(
            self._fallback, "record_world_size"
        ):
            self._fallback.record_world_size(size)


class BrainReporter:
    """Periodic job→Brain persistence thread (reference JobMetricCollector
    feeding Brain). Registers the job, then streams metric samples from
    the PerfMonitor + stats collector; marks final status on stop."""

    def __init__(
        self,
        brain_client: BrainClient,
        job_name: str,
        model_signature: str = "",
        workload: str = "jax",
        worker_num: int = 0,
        node_unit: int = 1,
        perf_monitor=None,
        stats_collector=None,
        world_size_fn=None,
        interval_s: float = 30.0,
        job_uuid: str = "",
    ):
        self.job_uuid = job_uuid or f"{job_name}-{uuid.uuid4().hex[:8]}"
        self._brain = brain_client
        self._job_name = job_name
        self._signature = model_signature
        self._workload = workload
        self._worker_num = worker_num
        self._node_unit = node_unit
        self._perf = perf_monitor
        self._stats = stats_collector
        self._world_size_fn = world_size_fn or (lambda: worker_num)
        self._interval = interval_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._brain.report_job(
            self.job_uuid,
            job_name=self._job_name,
            model_signature=self._signature,
            workload=self._workload,
            worker_num=self._worker_num,
            node_unit=self._node_unit,
            status="running",
        )
        self._thread = threading.Thread(
            target=self._loop, name="brain-reporter", daemon=True
        )
        self._thread.start()

    def sample_once(self) -> None:
        steps_per_s = (
            self._perf.steps_per_second() if self._perf is not None else 0.0
        )
        peak_mem = cpu = 0.0
        if self._stats is not None:
            peak_mem = self._stats.mean_memory_mb()
            cpu = self._stats.mean_cpu_percent()
        self._brain.report_metrics(
            self.job_uuid,
            world_size=self._world_size_fn(),
            steps_per_second=steps_per_s,
            peak_memory_mb=peak_mem,
            cpu_percent=cpu,
        )

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001
                logger.debug("brain reporting failed", exc_info=True)

    def stop(self) -> None:
        """Stop sampling without recording a final status (master torn
        down externally, e.g. tests); ``finish`` records the outcome."""
        self._stopped.set()
        self._thread = None

    def finish(self, status: str) -> None:
        self._stopped.set()
        try:
            self._brain.report_job(
                self.job_uuid,
                job_name=self._job_name,
                model_signature=self._signature,
                workload=self._workload,
                worker_num=self._world_size_fn() or self._worker_num,
                node_unit=self._node_unit,
                status=status,
            )
        except Exception:  # noqa: BLE001
            pass
        self._thread = None
