"""Resource plans and optimizers.

Reference: ``ResourcePlan``/``ResourceOptimizer`` (dlrover/python/
master/resource/optimizer.py:48,134) + the stats-driven single-job
``PSLocalOptimizer`` (local_optimizer.py:66). The PS-specific parts
(hot-PS migration) don't exist on TPU; what carries over is the split:
an optimizer produces a platform-neutral plan from observed stats, the
auto-scaler executes it.

TPU specifics: the scaling unit is a slice (node_unit hosts); valid
worker counts are multiples of it. Throughput modelling is per-host
step speed from the PerfMonitor.
"""

from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, Optional

from ...common.log import logger
from ...common.node import NodeResource


@dataclass
class ResourcePlan:
    """A desired adjustment (reference optimizer.py:48)."""

    # target worker (host) count; 0 = no opinion
    worker_num: int = 0
    node_resources: Dict[str, NodeResource] = field(default_factory=dict)
    # tuning suggestions delivered to trainers via ParallelConfig
    dataloader_batch_size: int = 0
    grad_accum_steps: int = 0

    def empty(self) -> bool:
        return (
            self.worker_num <= 0
            and not self.node_resources
            and self.dataloader_batch_size <= 0
            and self.grad_accum_steps <= 0
        )


class ResourceOptimizer(ABC):
    @abstractmethod
    def generate_plan(self) -> ResourcePlan:
        ...


class FixedResourceOptimizer(ResourceOptimizer):
    """No-op optimizer for fixed-size jobs."""

    def generate_plan(self) -> ResourcePlan:
        return ResourcePlan()


class ThroughputScalingOptimizer(ResourceOptimizer):
    """Grow the job while throughput scales, shrink past saturation.

    The allreduce-path analogue of the reference's stats-driven local
    optimizer (reference handles both directions,
    job_auto_scaler.py:276-345): track steps/s at each world size;
    propose +node_unit hosts while marginal speedup per host stays
    above ``min_gain``. When a grow turns out NOT to pay, propose
    shrinking back to the last efficient size and remember the
    saturation frontier so the job doesn't oscillate grow/shrink
    around it — hosts past the knee cost quota while barely moving
    throughput.
    """

    def __init__(
        self,
        perf_monitor,
        max_workers: int,
        node_unit: int = 1,
        min_gain_per_host: float = 0.4,
    ):
        self._perf = perf_monitor
        self._max = max_workers
        self._unit = max(1, node_unit)
        self._min_gain = min_gain_per_host
        self._speed_at_size: Dict[int, float] = {}
        self._current_size = 0
        # Largest size observed to still scale efficiently; sizes above
        # it are known-saturated. None until a saturation is seen.
        self._efficient_frontier: Optional[int] = None
        # Speed measured at the frontier when it was set, and how many
        # plans have been pinned at it — both feed invalidation below.
        self._frontier_speed = 0.0
        self._plans_at_frontier = 0

    # A saturation verdict is evidence about conditions at the time it
    # was taken (a straggler since excluded, transient network
    # degradation), not a permanent property of the job. Re-probe past
    # the knee when the measured speed at the frontier size drifts
    # materially, or after enough pinned plans go by.
    FRONTIER_DRIFT = 0.15
    FRONTIER_REPROBE_PLANS = 30

    def invalidate_frontier(self, reason: str = "") -> None:
        """Forget the saturation knee (e.g. after straggler exclusion
        or node migration changed the fleet's character)."""
        if self._efficient_frontier is not None:
            logger.info(
                "re-opening scaling frontier (was %s hosts)%s",
                self._efficient_frontier,
                f": {reason}" if reason else "",
            )
        self._efficient_frontier = None
        self._frontier_speed = 0.0
        self._plans_at_frontier = 0
        # Stale per-size speeds above the old knee would immediately
        # re-trigger saturation against fresh measurements.
        self._speed_at_size.clear()

    def _maybe_invalidate(self, size: int, speed: float) -> None:
        if self._efficient_frontier is None:
            return
        if size == self._efficient_frontier and self._frontier_speed > 0:
            drift = abs(speed - self._frontier_speed) / self._frontier_speed
            if drift > self.FRONTIER_DRIFT:
                self.invalidate_frontier(
                    f"speed at {size} hosts moved {drift:.0%}"
                )
                return
        self._plans_at_frontier += 1
        if self._plans_at_frontier >= self.FRONTIER_REPROBE_PLANS:
            self.invalidate_frontier("periodic re-probe window elapsed")

    def record_world_size(self, size: int) -> None:
        self._current_size = size

    def generate_plan(self) -> ResourcePlan:
        speed = self._perf.steps_per_second()
        size = self._current_size
        if size <= 0 or speed <= 0:
            return ResourcePlan()
        self._maybe_invalidate(size, speed)
        self._speed_at_size[size] = speed
        prev_sizes = [s for s in self._speed_at_size if s < size]
        if prev_sizes:
            prev = max(prev_sizes)
            gained = self._speed_at_size[size] - self._speed_at_size[prev]
            per_host = gained / max(1, size - prev)
            expected_per_host = self._speed_at_size[prev] / prev
            if per_host < self._min_gain * expected_per_host:
                self._efficient_frontier = prev
                self._frontier_speed = self._speed_at_size[prev]
                self._plans_at_frontier = 0
                logger.info(
                    "scaling saturated: +%.3f steps/s per host < %.0f%% of "
                    "linear; releasing back to %s hosts",
                    per_host,
                    self._min_gain * 100,
                    prev,
                )
                return ResourcePlan(worker_num=prev)
        if (
            self._efficient_frontier is not None
            and size > self._efficient_frontier
        ):
            # Still above the known knee (e.g. the earlier shrink plan
            # was not executed): keep asking for the efficient size.
            return ResourcePlan(worker_num=self._efficient_frontier)
        target = size + self._unit
        if target > self._max:
            return ResourcePlan()
        if (
            self._efficient_frontier is not None
            and target > self._efficient_frontier
        ):
            return ResourcePlan()  # growing past the knee is known waste
        return ResourcePlan(worker_num=target)
