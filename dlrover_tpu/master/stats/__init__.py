from .job_stats import JobStatsCollector, NodeSample  # noqa: F401
