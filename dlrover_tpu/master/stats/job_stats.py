"""Master-side job runtime statistics.

Reference: ``dlrover/python/master/stats/`` (job stats collectors feeding
the local optimizer, ``local_optimizer.py:66``) and the runtime metric
path ``xpu_timer_metric_collector.py:28`` → ``JobMetricContext`` →
diagnosis. The TPU shape: agents report (a) resource usage and (b)
profiler gauges (tpu_timer Prometheus names); this collector samples both
into bounded per-node time series that the auto-scaling optimizer, the
straggler policy, and the hyperparameter strategy generator consume —
the "real metrics pipeline" behind scaling decisions.
"""

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from ...common.constants import NodeType
from ...common.log import logger
from ..monitor.metric_context import get_metric_context

# tpu_timer gauge names (native/tpu_timer MetricsText). win_avg is the
# recent-window average — the run-lifetime avg would take hours to
# reflect a degradation and is useless for straggler detection.
STEP_AVG_US = 'tpu_timer_latency_us{kind="step",agg="win_avg"}'
MATMUL_TFLOPS = 'tpu_timer_tflops{kind="hlo_flops"}'


@dataclass
class NodeSample:
    """One sampling instant of one node's runtime signals."""

    timestamp: float
    cpu_percent: float = 0.0
    memory_mb: float = 0.0
    step_time_us: float = 0.0
    matmul_tflops: float = 0.0
    # Device-side signals (trainer-reported, see trainer/device_monitor):
    # mean duty-cycle across the host's local devices (-1 = no signal)
    # and the worst HBM occupancy fraction (used/limit; 0 = unknown).
    device_util: float = -1.0
    device_mem_frac: float = 0.0


@dataclass
class NodeSeries:
    node_id: int
    samples: Deque[NodeSample] = field(default_factory=lambda: deque(maxlen=128))

    def latest(self) -> Optional[NodeSample]:
        return self.samples[-1] if self.samples else None

    def mean_step_time_us(self, last_n: int = 8) -> float:
        vals = [
            s.step_time_us
            for s in list(self.samples)[-last_n:]
            if s.step_time_us > 0
        ]
        return sum(vals) / len(vals) if vals else 0.0


class JobStatsCollector:
    """Samples per-node signals into series; answers optimizer queries.

    Sources (both already flow through the master RPC surface):
    - ``ResourceUsageReport`` → node.used_resource (job context)
    - ``NodeMetricsReport`` → JobMetricContext gauges (tpu_timer scrape)
    """

    def __init__(self, job_context, interval_s: float = 10.0):
        self._job_ctx = job_context
        self._interval = interval_s
        self._series: Dict[int, NodeSeries] = {}
        self._mu = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    # -- sampling ----------------------------------------------------------

    def sample_once(self) -> None:
        now = time.time()
        metric_ctx = get_metric_context()
        # Gauges older than this are re-reports of one stale scrape, not
        # new observations — recording them would let a single metric
        # report satisfy min_samples.
        max_age = 3 * self._interval
        with self._mu:
            nodes = self._job_ctx.get_nodes(NodeType.WORKER)
            # Evict series of exited/removed nodes: frozen samples of
            # dead nodes must not feed straggler medians or memory means.
            for node_id in list(self._series):
                node = nodes.get(node_id)
                if node is None or node.exited():
                    del self._series[node_id]
            for node in nodes.values():
                if node.exited():
                    continue
                series = self._series.setdefault(
                    node.node_id, NodeSeries(node.node_id)
                )
                used = node.used_resource
                # Freshness gate (same rationale as fresh_gauge above):
                # a dead reporter's last device gauges must not be
                # replayed into new samples — they would prop up or drag
                # the peer median in detect_device_pressure forever.
                device_fresh = (
                    used.device_reported_at > 0
                    and now - used.device_reported_at <= max_age
                )
                utils = (
                    [u for u in used.device_util.values() if u >= 0]
                    if device_fresh
                    else []
                )
                mem_fracs = (
                    [
                        used.device_mem_mb.get(i, 0.0) / limit
                        for i, limit in used.device_mem_limit_mb.items()
                        if limit > 0
                    ]
                    if device_fresh
                    else []
                )
                series.samples.append(
                    NodeSample(
                        timestamp=now,
                        cpu_percent=used.cpu,
                        memory_mb=used.memory_mb,
                        step_time_us=metric_ctx.fresh_gauge(
                            node.node_id, STEP_AVG_US, max_age
                        ),
                        matmul_tflops=metric_ctx.fresh_gauge(
                            node.node_id, MATMUL_TFLOPS, max_age
                        ),
                        device_util=(
                            sum(utils) / len(utils) if utils else -1.0
                        ),
                        device_mem_frac=max(mem_fracs, default=0.0),
                    )
                )

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name="job-stats", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval):
            try:
                self.sample_once()
            except Exception:
                logger.exception("job stats sampling error")

    def stop(self) -> None:
        self._stopped.set()
        self._thread = None

    # -- queries -----------------------------------------------------------

    def evict(self, node_id: int) -> None:
        """Drop a node's series (e.g. straggler migrated: the old
        incarnation's samples must not skew the peer median)."""
        with self._mu:
            self._series.pop(node_id, None)

    def series(self, node_id: int) -> Optional[NodeSeries]:
        with self._mu:
            return self._series.get(node_id)

    def detect_stragglers(
        self,
        factor: Optional[float] = None,
        min_nodes: int = 3,
        min_samples: int = 4,
    ) -> List[int]:
        """Nodes whose mean step time exceeds ``factor`` x the median of
        peers (reference straggler rule, rdzv_manager.py:784 — applied
        here to *runtime* profiler data rather than the one-shot network
        check).

        Requires ``min_nodes`` reporting nodes (a median of one or two is
        meaningless) and ``min_samples`` samples per accused node so a
        single slow step (GC pause, ckpt stall) can't evict a host.
        ``factor`` defaults to the configured straggler_median_ratio so
        runtime exclusion and the rendezvous check share one knob.
        """
        if factor is None:
            from ...common.config import get_context

            factor = get_context().straggler_median_ratio
        with self._mu:
            means = {}
            for nid, series in self._series.items():
                count = sum(1 for s in series.samples if s.step_time_us > 0)
                if count >= min_samples:
                    means[nid] = series.mean_step_time_us()
        if len(means) < min_nodes:
            return []
        import statistics

        median = statistics.median(means.values())
        if median <= 0:
            return []
        return sorted(n for n, v in means.items() if v > factor * median)

    def detect_device_pressure(
        self,
        util_floor_ratio: float = 0.6,
        mem_frac_ceiling: float = 0.92,
        min_nodes: int = 3,
        min_samples: int = 3,
    ) -> Dict[int, str]:
        """Hosts whose DEVICE metrics degraded — before step times
        diverge (reference GpuMetricMonitor feeds the same early-warning
        role, common/metric/monitor.py:351). Two signals:

        - duty-cycle collapse: a node's mean device utilization below
          ``util_floor_ratio`` x the peer median while peers are busy —
          its chip is starving (input stall, desharded collective)
          though its step reports may still look on-pace;
        - HBM saturation: worst device memory above ``mem_frac_ceiling``
          of its limit — the next rematerialization spike OOMs it.

        Returns {node_id: reason}. Median gating mirrors
        detect_stragglers: no verdicts from tiny worlds or thin series.
        """
        with self._mu:
            utils: Dict[int, float] = {}
            mem_fracs: Dict[int, float] = {}
            for nid, series in self._series.items():
                samples = [
                    s for s in list(series.samples)[-8:] if s.device_util >= 0
                ]
                if len(samples) >= min_samples:
                    utils[nid] = sum(s.device_util for s in samples) / len(
                        samples
                    )
                mems = [
                    s.device_mem_frac
                    for s in list(series.samples)[-min_samples:]
                    if s.device_mem_frac > 0
                ]
                if len(mems) >= min_samples:
                    mem_fracs[nid] = min(mems)  # sustained, not a spike
        out: Dict[int, str] = {}
        if len(utils) >= min_nodes:
            import statistics

            median = statistics.median(utils.values())
            if median > 0.05:  # peers genuinely busy
                for nid, u in utils.items():
                    if u < util_floor_ratio * median:
                        # "<kind>: <detail>" — consumers dedup on kind
                        out[nid] = (
                            f"duty-cycle: {u:.2f} vs peer median "
                            f"{median:.2f}"
                        )
        for nid, frac in mem_fracs.items():
            if frac > mem_frac_ceiling and nid not in out:
                out[nid] = f"hbm: {frac:.0%} of limit"
        return out

    def mean_cpu_percent(self) -> float:
        with self._mu:
            vals = [
                s.latest().cpu_percent
                for s in self._series.values()
                if s.latest() is not None
            ]
        return sum(vals) / len(vals) if vals else 0.0

    def mean_memory_mb(self) -> float:
        with self._mu:
            vals = [
                s.latest().memory_mb
                for s in self._series.values()
                if s.latest() is not None
            ]
        return sum(vals) / len(vals) if vals else 0.0
