"""Dataset splitting into elastic shards.

Reference: ``master/shard/dataset_splitter.py`` (Shard:26, DatasetSplitter:92,
TableDatasetSplitter:146, TextDatasetSplitter:259,
StreamingDatasetSplitter:361). A shard is a [start, end) sample-index range,
optionally with shuffled per-sample indices; workers pull shards as tasks so
data delivery stays correct under worker churn.
"""

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Shard:
    name: str = ""
    start: int = 0
    end: int = 0
    record_indices: List[int] = field(default_factory=list)

    @property
    def size(self) -> int:
        return self.end - self.start


class DatasetSplitter:
    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
    ):
        self.dataset_name = dataset_name
        self.dataset_size = dataset_size
        self.shard_size = max(1, shard_size)
        self.num_epochs = max(1, num_epochs)
        self.epoch = 0

    def create_shards(self) -> List[Shard]:
        raise NotImplementedError

    def epoch_finished(self) -> bool:
        return self.epoch >= self.num_epochs

    # -- persistence (master crash-tolerance journal) ----------------------

    def export_state(self) -> Dict:
        """Everything create_shards depends on beyond the constructor
        params: the epoch cursor and — for the shuffling splitters —
        the RNG stream position. Without the RNG state, a refill
        replayed over a snapshot would draw a DIFFERENT permutation
        than the shards agents already hold (samples dropped and
        duplicated at index granularity)."""
        state: Dict = {"epoch": self.epoch}
        rng = getattr(self, "_rng", None)
        if rng is not None:
            version, internal, gauss = rng.getstate()
            state["rng"] = [version, list(internal), gauss]
        return state

    def import_state(self, state: Dict) -> None:
        self.epoch = int(state.get("epoch", self.epoch))
        rng = getattr(self, "_rng", None)
        if rng is not None and state.get("rng"):
            version, internal, gauss = state["rng"]
            rng.setstate((version, tuple(internal), gauss))


class TableDatasetSplitter(DatasetSplitter):
    """Contiguous range shards over an indexable dataset (reference :146)."""

    def __init__(self, *args, shuffle: bool = False, seed: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self.shuffle = shuffle
        self._rng = random.Random(seed)

    def create_shards(self) -> List[Shard]:
        self.epoch += 1
        shards = []
        starts = list(range(0, self.dataset_size, self.shard_size))
        if self.shuffle:
            self._rng.shuffle(starts)
        for i, start in enumerate(starts):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(
                Shard(name=f"{self.dataset_name}_e{self.epoch}_s{i}", start=start, end=end)
            )
        return shards


class TextDatasetSplitter(DatasetSplitter):
    """Shards with explicit per-sample indices, supporting intra-shard
    shuffling (reference :259)."""

    def __init__(self, *args, shuffle: bool = False, seed: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self.shuffle = shuffle
        self._rng = random.Random(seed)

    def create_shards(self) -> List[Shard]:
        self.epoch += 1
        indices = list(range(self.dataset_size))
        if self.shuffle:
            self._rng.shuffle(indices)
        shards = []
        for i, start in enumerate(range(0, self.dataset_size, self.shard_size)):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(
                Shard(
                    name=f"{self.dataset_name}_e{self.epoch}_s{i}",
                    start=start,
                    end=end,
                    record_indices=indices[start:end],
                )
            )
        return shards


class StreamingDatasetSplitter(DatasetSplitter):
    """Open-ended stream: shards are emitted as offsets advance
    (reference :361)."""

    def __init__(self, dataset_name: str, shard_size: int, start_offset: int = 0):
        super().__init__(dataset_name, dataset_size=-1, shard_size=shard_size)
        self._offset = start_offset
        self._shard_idx = 0

    def create_shards(self, count: int = 16) -> List[Shard]:
        shards = []
        for _ in range(count):
            shards.append(
                Shard(
                    name=f"{self.dataset_name}_s{self._shard_idx}",
                    start=self._offset,
                    end=self._offset + self.shard_size,
                )
            )
            self._offset += self.shard_size
            self._shard_idx += 1
        return shards

    def epoch_finished(self) -> bool:
        return False

    def export_state(self) -> Dict:
        state = super().export_state()
        state["offset"] = self._offset
        state["shard_idx"] = self._shard_idx
        return state

    def import_state(self, state: Dict) -> None:
        super().import_state(state)
        self._offset = int(state.get("offset", self._offset))
        self._shard_idx = int(state.get("shard_idx", self._shard_idx))


def new_dataset_splitter(
    splitter_type: str,
    dataset_name: str,
    dataset_size: int,
    shard_size: int,
    num_epochs: int = 1,
    shuffle: bool = False,
    seed: int = 0,
) -> DatasetSplitter:
    if splitter_type == "text":
        return TextDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle=shuffle, seed=seed
        )
    if splitter_type == "streaming":
        return StreamingDatasetSplitter(dataset_name, shard_size)
    return TableDatasetSplitter(
        dataset_name, dataset_size, shard_size, num_epochs, shuffle=shuffle, seed=seed
    )
