"""Master-side task queues for dynamic data sharding.

Reference: ``master/shard/base_dataset_manager.py`` (Task:22, DoingTask:43,
DatasetShardCheckpoint:60), ``batch_dataset_manager.py:29`` and
``task_manager.py:35``: todo/doing queues with at-least-once redelivery —
shards of dead or timed-out workers are re-queued, which is what makes
worker-count elasticity safe for data order.
"""

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ...common import comm
from ...common.log import logger
from .dataset_splitter import DatasetSplitter, Shard


@dataclass
class Task:
    task_id: int = -1
    task_type: str = "training"
    shard: Shard = field(default_factory=Shard)

    @classmethod
    def create_invalid_task(cls) -> "Task":
        return cls(task_id=-1)


@dataclass
class DoingTask:
    task: Task
    node_id: int
    start_time: float


class DatasetManager:
    """Per-dataset todo/doing bookkeeping (reference batch_dataset_manager)."""

    def __init__(self, dataset_name: str, splitter: DatasetSplitter, task_type: str = "training"):
        self.dataset_name = dataset_name
        self._splitter = splitter
        self._task_type = task_type
        self.todo: List[Task] = []
        self.doing: Dict[int, DoingTask] = {}
        self._task_id = 0
        self._completed = 0
        self._lock = threading.Lock()

    def _refill(self) -> None:
        if self.todo or self._splitter.epoch_finished():
            return
        for shard in self._splitter.create_shards():
            self.todo.append(
                Task(task_id=self._task_id, task_type=self._task_type, shard=shard)
            )
            self._task_id += 1

    def get_task(self, node_id: int) -> Task:
        with self._lock:
            self._refill()
            if not self.todo:
                return Task.create_invalid_task()
            task = self.todo.pop(0)
            self.doing[task.task_id] = DoingTask(task, node_id, time.time())
            return task

    def report_task_status(self, task_id: int, success: bool) -> Optional[Task]:
        with self._lock:
            doing = self.doing.pop(task_id, None)
            if doing is None:
                return None
            if success:
                self._completed += 1
                return doing.task
            self.todo.insert(0, doing.task)
            return None

    def recover_tasks_of_node(self, node_id: int) -> int:
        """Requeue uncompleted shards of a dead worker (reference
        task_manager recovery)."""
        with self._lock:
            recovered = [t for t in self.doing.values() if t.node_id == node_id]
            for doing in recovered:
                del self.doing[doing.task.task_id]
                self.todo.insert(0, doing.task)
            if recovered:
                logger.info(
                    "requeued %s tasks of dead node %s on dataset %s",
                    len(recovered),
                    node_id,
                    self.dataset_name,
                )
            return len(recovered)

    def recover_timeout_tasks(self, timeout_s: float) -> List[int]:
        now = time.time()
        with self._lock:
            timed_out = [
                tid
                for tid, doing in self.doing.items()
                if now - doing.start_time > timeout_s
            ]
            nodes = []
            for tid in timed_out:
                doing = self.doing.pop(tid)
                self.todo.insert(0, doing.task)
                nodes.append(doing.node_id)
            return nodes

    def completed(self) -> bool:
        with self._lock:
            return (
                not self.todo
                and not self.doing
                and self._splitter.epoch_finished()
            )

    # -- shard checkpoint (data resume) -----------------------------------

    def checkpoint(self) -> str:
        """Serialize undelivered + in-flight shards (reference
        DatasetShardCheckpoint base_dataset_manager.py:60)."""
        with self._lock:
            payload = {
                "dataset_name": self.dataset_name,
                "todo": [
                    [t.shard.start, t.shard.end, t.shard.record_indices]
                    for t in self.todo
                ],
                "doing": [
                    [d.task.shard.start, d.task.shard.end, d.task.shard.record_indices]
                    for d in self.doing.values()
                ],
                "epoch": self._splitter.epoch,
            }
            return json.dumps(payload)

    def restore_checkpoint(self, content: str) -> None:
        data = json.loads(content)
        with self._lock:
            self.todo = []
            self.doing = {}
            self._splitter.epoch = data.get("epoch", self._splitter.epoch)
            for start, end, indices in data.get("doing", []) + data.get("todo", []):
                shard = Shard(
                    name=f"{self.dataset_name}_restored_{self._task_id}",
                    start=start,
                    end=end,
                    record_indices=indices or [],
                )
                self.todo.append(
                    Task(task_id=self._task_id, task_type=self._task_type, shard=shard)
                )
                self._task_id += 1


class TaskManager:
    """All datasets of the job (reference task_manager.py:35)."""

    def __init__(self, task_timeout_s: float = 1800.0):
        self._datasets: Dict[str, DatasetManager] = {}
        self._lock = threading.Lock()
        self._task_timeout_s = task_timeout_s
        self._worker_restart_callbacks = []

    def new_dataset(self, params: comm.DatasetShardParams) -> None:
        from .dataset_splitter import new_dataset_splitter

        with self._lock:
            if params.dataset_name in self._datasets:
                return
            shard_size = max(
                1, params.batch_size * params.num_minibatches_per_shard
            )
            splitter = new_dataset_splitter(
                params.storage_type or "table",
                params.dataset_name,
                params.dataset_size,
                shard_size,
                num_epochs=params.num_epochs,
                shuffle=params.shuffle,
            )
            self._datasets[params.dataset_name] = DatasetManager(
                params.dataset_name, splitter, params.task_type
            )
            logger.info("created dataset manager %s", params.dataset_name)

    def get_dataset(self, name: str) -> Optional[DatasetManager]:
        with self._lock:
            return self._datasets.get(name)

    def get_task(self, node_id: int, dataset_name: str) -> Task:
        ds = self.get_dataset(dataset_name)
        if ds is None:
            return Task.create_invalid_task()
        return ds.get_task(node_id)

    def report_task_result(self, dataset_name: str, task_id: int, success: bool) -> None:
        ds = self.get_dataset(dataset_name)
        if ds is not None:
            ds.report_task_status(task_id, success)

    def recover_tasks(self, node_id: int) -> None:
        with self._lock:
            datasets = list(self._datasets.values())
        for ds in datasets:
            ds.recover_tasks_of_node(node_id)

    def recover_timeout_tasks(self) -> List[int]:
        slow_nodes: List[int] = []
        with self._lock:
            datasets = list(self._datasets.values())
        for ds in datasets:
            slow_nodes.extend(ds.recover_timeout_tasks(self._task_timeout_s))
        return slow_nodes

    def finished(self) -> bool:
        with self._lock:
            return bool(self._datasets) and all(
                ds.completed() for ds in self._datasets.values()
            )

    def checkpoint(self, dataset_name: str) -> str:
        ds = self.get_dataset(dataset_name)
        return ds.checkpoint() if ds else ""

    def restore_checkpoint(self, dataset_name: str, content: str) -> None:
        ds = self.get_dataset(dataset_name)
        if ds is not None:
            ds.restore_checkpoint(content)
