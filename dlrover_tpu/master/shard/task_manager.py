"""Master-side task queues for dynamic data sharding.

Reference: ``master/shard/base_dataset_manager.py`` (Task:22, DoingTask:43,
DatasetShardCheckpoint:60), ``batch_dataset_manager.py:29`` and
``task_manager.py:35``: todo/doing queues with at-least-once redelivery —
shards of dead or timed-out workers are re-queued, which is what makes
worker-count elasticity safe for data order.

Crash tolerance (master journal): dataset creation and every task
issue/completion are WAL'd — the issue record is appended *before* the
task is handed to the agent, so a task the agent holds is always in the
replayed ``doing`` set. After a replay the doing entries start
*unconfirmed*; agents re-report the task ids they actually hold
(``confirm_tasks``), which confirms real in-flight shards exactly once
and immediately re-queues anything the reporting node does not hold
(finished-but-unacked or never-received). Nodes that never re-report
within the re-attach grace have their tasks re-queued by
``reconcile_unconfirmed`` — no sample is dropped, none double-issued.
"""

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ...common import comm
from ...common.log import logger
from .dataset_splitter import DatasetSplitter, Shard


@dataclass
class Task:
    task_id: int = -1
    task_type: str = "training"
    shard: Shard = field(default_factory=Shard)

    @classmethod
    def create_invalid_task(cls) -> "Task":
        return cls(task_id=-1)


@dataclass
class DoingTask:
    task: Task
    node_id: int
    start_time: float
    # False only on replayed entries awaiting the owner's re-report.
    confirmed: bool = True


def _shard_dict(shard: Shard) -> Dict:
    return {
        "name": shard.name,
        "start": shard.start,
        "end": shard.end,
        "indices": list(shard.record_indices or []),
    }


def _shard_from(data: Dict) -> Shard:
    return Shard(
        name=data.get("name", ""),
        start=int(data.get("start", 0)),
        end=int(data.get("end", 0)),
        record_indices=list(data.get("indices") or []),
    )


class DatasetManager:
    """Per-dataset todo/doing bookkeeping (reference batch_dataset_manager)."""

    def __init__(self, dataset_name: str, splitter: DatasetSplitter, task_type: str = "training"):
        self.dataset_name = dataset_name
        self._splitter = splitter
        self._task_type = task_type
        self.todo: List[Task] = []
        self.doing: Dict[int, DoingTask] = {}
        self._task_id = 0
        self._completed = 0
        self._done_ids: List[int] = []  # recent, for replay idempotence
        self._lock = threading.Lock()
        self.journal = None  # threaded down from TaskManager

    def _record(self, kind: str, payload: Dict) -> None:
        if self.journal is not None:
            payload = dict(payload, dataset=self.dataset_name)
            self.journal(kind, payload)

    def _refill(self) -> None:
        if self.todo or self._splitter.epoch_finished():
            return
        for shard in self._splitter.create_shards():
            self.todo.append(
                Task(task_id=self._task_id, task_type=self._task_type, shard=shard)
            )
            self._task_id += 1
        # Journaled by the post-refill task-id watermark, not by shard
        # list: splitters are seeded and sequential, so replaying the
        # same create_shards sequence reproduces the exact shards and
        # task ids (see apply_journal) — without this a replayed dataset
        # whose snapshot predates the refill would re-create
        # already-issued shards (duplicate samples).
        self._record("task.refill", {"next_task_id": self._task_id})

    def get_task(self, node_id: int) -> Task:
        with self._lock:
            self._refill()
            if not self.todo:
                return Task.create_invalid_task()
            task = self.todo.pop(0)
            self.doing[task.task_id] = DoingTask(task, node_id, time.time())
            # WAL BEFORE the task leaves this call: a master crash after
            # the agent received the task but before the record landed
            # would otherwise lose the doing entry — with this ordering
            # a held task is always replayable (exactly-once re-issue).
            self._record(
                "task.issue",
                {
                    "task_id": task.task_id,
                    "node_id": node_id,
                    "task_type": task.task_type,
                    "shard": _shard_dict(task.shard),
                },
            )
            return task

    def report_task_status(self, task_id: int, success: bool) -> Optional[Task]:
        with self._lock:
            doing = self.doing.pop(task_id, None)
            if doing is None:
                return None
            self._record("task.done", {"task_id": task_id, "success": success})
            if success:
                self._complete_id(task_id)
                return doing.task
            self.todo.insert(0, doing.task)
            return None

    def _complete_id(self, task_id: int) -> None:
        self._completed += 1
        self._done_ids.append(task_id)
        if len(self._done_ids) > 4096:
            del self._done_ids[:-2048]

    def recover_tasks_of_node(self, node_id: int) -> int:
        """Requeue uncompleted shards of a dead worker (reference
        task_manager recovery)."""
        with self._lock:
            recovered = [t for t in self.doing.values() if t.node_id == node_id]
            for doing in recovered:
                del self.doing[doing.task.task_id]
                self.todo.insert(0, doing.task)
                self._record(
                    "task.done",
                    {"task_id": doing.task.task_id, "success": False},
                )
            if recovered:
                logger.info(
                    "requeued %s tasks of dead node %s on dataset %s",
                    len(recovered),
                    node_id,
                    self.dataset_name,
                )
            return len(recovered)

    def recover_timeout_tasks(self, timeout_s: float) -> List[int]:
        now = time.time()
        with self._lock:
            timed_out = [
                tid
                for tid, doing in self.doing.items()
                if now - doing.start_time > timeout_s
            ]
            nodes = []
            for tid in timed_out:
                doing = self.doing.pop(tid)
                self.todo.insert(0, doing.task)
                self._record("task.done", {"task_id": tid, "success": False})
                nodes.append(doing.node_id)
            return nodes

    def completed(self) -> bool:
        with self._lock:
            return (
                not self.todo
                and not self.doing
                and self._splitter.epoch_finished()
            )

    # -- shard checkpoint (data resume) -----------------------------------

    def checkpoint(self) -> str:
        """Serialize undelivered + in-flight shards (reference
        DatasetShardCheckpoint base_dataset_manager.py:60)."""
        with self._lock:
            payload = {
                "dataset_name": self.dataset_name,
                "todo": [
                    [t.shard.start, t.shard.end, t.shard.record_indices]
                    for t in self.todo
                ],
                "doing": [
                    [d.task.shard.start, d.task.shard.end, d.task.shard.record_indices]
                    for d in self.doing.values()
                ],
                "epoch": self._splitter.epoch,
            }
            return json.dumps(payload)

    def restore_checkpoint(self, content: str) -> None:
        data = json.loads(content)
        with self._lock:
            self.todo = []
            self.doing = {}
            self._splitter.epoch = data.get("epoch", self._splitter.epoch)
            for start, end, indices in data.get("doing", []) + data.get("todo", []):
                shard = Shard(
                    name=f"{self.dataset_name}_restored_{self._task_id}",
                    start=start,
                    end=end,
                    record_indices=indices or [],
                )
                self.todo.append(
                    Task(task_id=self._task_id, task_type=self._task_type, shard=shard)
                )
                self._task_id += 1

    # -- persistence (snapshot / replay / re-attach) -----------------------

    def export_state(self) -> Dict:
        """Exact-id export for the master journal — unlike the
        agent-facing ``checkpoint`` above, task ids must survive so
        replayed doing entries match agent re-reports byte-for-byte."""
        with self._lock:
            return {
                "task_type": self._task_type,
                "next_task_id": self._task_id,
                "completed": self._completed,
                "done_ids": list(self._done_ids[-2048:]),
                # Full splitter cursor (epoch + streaming offset + RNG
                # stream position): a post-restart refill must continue
                # the dead master's shard sequence, not restart it.
                "splitter": self._splitter.export_state(),
                "todo": [
                    {
                        "task_id": t.task_id,
                        "task_type": t.task_type,
                        "shard": _shard_dict(t.shard),
                    }
                    for t in self.todo
                ],
                "doing": [
                    {
                        "task_id": d.task.task_id,
                        "node_id": d.node_id,
                        "task_type": d.task.task_type,
                        "shard": _shard_dict(d.task.shard),
                    }
                    for d in self.doing.values()
                ],
            }

    def import_state(self, state: Dict) -> None:
        with self._lock:
            self._task_id = int(state.get("next_task_id", 0))
            self._completed = int(state.get("completed", 0))
            self._done_ids = list(state.get("done_ids") or [])
            self._splitter.import_state(state.get("splitter") or {})
            self.todo = [
                Task(
                    task_id=int(t["task_id"]),
                    task_type=t.get("task_type", self._task_type),
                    shard=_shard_from(t.get("shard") or {}),
                )
                for t in state.get("todo") or []
            ]
            self.doing = {}
            for d in state.get("doing") or []:
                task = Task(
                    task_id=int(d["task_id"]),
                    task_type=d.get("task_type", self._task_type),
                    shard=_shard_from(d.get("shard") or {}),
                )
                # unconfirmed until the owner re-reports (or the grace
                # deadline re-queues it)
                self.doing[task.task_id] = DoingTask(
                    task, int(d.get("node_id", -1)), time.time(),
                    confirmed=False,
                )

    def apply_journal(self, kind: str, data: Dict) -> None:
        """Replay one WAL record. Idempotent against the snapshot."""
        with self._lock:
            task_id = int(data.get("task_id", -1))
            if kind == "task.refill":
                # Re-run the seeded splitter up to the journaled task-id
                # watermark: identical shards, identical sequential ids
                # (works for epoch splitters AND the streaming one,
                # whose cursor lives outside `epoch`).
                target = int(data.get("next_task_id", 0))
                while (
                    self._task_id < target
                    and not self._splitter.epoch_finished()
                ):
                    made = self._splitter.create_shards()
                    if not made:
                        break  # exhausted splitter can't reach the mark
                    for shard in made:
                        self.todo.append(
                            Task(
                                task_id=self._task_id,
                                task_type=self._task_type,
                                shard=shard,
                            )
                        )
                        self._task_id += 1
            elif kind == "task.issue":
                if task_id in self.doing or task_id in self._done_ids:
                    return
                match = next(
                    (t for t in self.todo if t.task_id == task_id), None
                )
                if match is not None:
                    self.todo.remove(match)
                    task = match
                else:
                    task = Task(
                        task_id=task_id,
                        task_type=data.get("task_type", self._task_type),
                        shard=_shard_from(data.get("shard") or {}),
                    )
                    self._task_id = max(self._task_id, task_id + 1)
                self.doing[task_id] = DoingTask(
                    task, int(data.get("node_id", -1)), time.time(),
                    confirmed=False,
                )
            elif kind == "task.done":
                doing = self.doing.pop(task_id, None)
                if bool(data.get("success")):
                    if task_id not in self._done_ids:
                        self._complete_id(task_id)
                elif doing is not None:
                    self.todo.insert(0, doing.task)

    def confirm_tasks(self, node_id: int, task_ids: List[int]) -> int:
        """An agent re-asserted the shards it holds after a master
        restart: confirm those, and immediately requeue any other
        replayed doing entry of the SAME node — the worker does not
        hold it (finished-but-unacked or never received), so waiting
        for the grace deadline would only stall redelivery. Returns the
        number of confirmed tasks."""
        claimed = set(task_ids)
        confirmed = 0
        with self._lock:
            for tid in list(self.doing):
                doing = self.doing[tid]
                if doing.node_id != node_id:
                    continue
                if tid in claimed:
                    if not doing.confirmed:
                        doing.confirmed = True
                        confirmed += 1
                elif not doing.confirmed:
                    del self.doing[tid]
                    self.todo.insert(0, doing.task)
                    self._record(
                        "task.done", {"task_id": tid, "success": False}
                    )
                    logger.info(
                        "requeued unclaimed task %s of node %s on %s "
                        "after master restart",
                        tid, node_id, self.dataset_name,
                    )
        return confirmed

    def reconcile_unconfirmed(self) -> int:
        """Grace expired: requeue every still-unconfirmed doing entry
        (its node never re-attached). Returns how many were requeued."""
        with self._lock:
            stale = [
                tid for tid, d in self.doing.items() if not d.confirmed
            ]
            for tid in stale:
                doing = self.doing.pop(tid)
                self.todo.insert(0, doing.task)
                self._record("task.done", {"task_id": tid, "success": False})
            if stale:
                logger.warning(
                    "requeued %s unconfirmed tasks on %s after the "
                    "re-attach grace expired",
                    len(stale), self.dataset_name,
                )
            return len(stale)


class TaskManager:
    """All datasets of the job (reference task_manager.py:35)."""

    def __init__(self, task_timeout_s: float = 1800.0):
        self._datasets: Dict[str, DatasetManager] = {}
        self._dataset_params: Dict[str, Dict] = {}
        self._lock = threading.Lock()
        self._task_timeout_s = task_timeout_s
        self._worker_restart_callbacks = []
        self._journal = None
        self._reattach_deadline = 0.0

    def set_journal(self, journal) -> None:
        with self._lock:
            self._journal = journal
            for ds in self._datasets.values():
                ds.journal = journal

    def new_dataset(self, params: comm.DatasetShardParams) -> None:
        self._new_dataset_dict(
            {
                "dataset_name": params.dataset_name,
                "batch_size": params.batch_size,
                "num_epochs": params.num_epochs,
                "dataset_size": params.dataset_size,
                "shuffle": bool(params.shuffle),
                "num_minibatches_per_shard": params.num_minibatches_per_shard,
                "storage_type": params.storage_type,
                "task_type": params.task_type,
            }
        )

    def _new_dataset_dict(self, params: Dict, journal: bool = True) -> None:
        from .dataset_splitter import new_dataset_splitter

        with self._lock:
            name = params["dataset_name"]
            if name in self._datasets:
                return
            shard_size = max(
                1,
                int(params.get("batch_size", 0))
                * int(params.get("num_minibatches_per_shard", 2)),
            )
            splitter = new_dataset_splitter(
                params.get("storage_type") or "table",
                name,
                int(params.get("dataset_size", 0)),
                shard_size,
                num_epochs=int(params.get("num_epochs", 1)),
                shuffle=bool(params.get("shuffle", False)),
            )
            ds = DatasetManager(
                name, splitter, params.get("task_type", "training")
            )
            ds.journal = self._journal
            self._datasets[name] = ds
            self._dataset_params[name] = dict(params)
            if journal and self._journal is not None:
                self._journal("task.dataset", dict(params))
            logger.info("created dataset manager %s", name)

    def get_dataset(self, name: str) -> Optional[DatasetManager]:
        with self._lock:
            return self._datasets.get(name)

    def get_task(self, node_id: int, dataset_name: str) -> Task:
        ds = self.get_dataset(dataset_name)
        if ds is None:
            return Task.create_invalid_task()
        return ds.get_task(node_id)

    def report_task_result(self, dataset_name: str, task_id: int, success: bool) -> None:
        ds = self.get_dataset(dataset_name)
        if ds is not None:
            ds.report_task_status(task_id, success)

    def recover_tasks(self, node_id: int) -> None:
        with self._lock:
            datasets = list(self._datasets.values())
        for ds in datasets:
            ds.recover_tasks_of_node(node_id)

    def recover_timeout_tasks(self) -> List[int]:
        slow_nodes: List[int] = []
        with self._lock:
            datasets = list(self._datasets.values())
        for ds in datasets:
            slow_nodes.extend(ds.recover_timeout_tasks(self._task_timeout_s))
        return slow_nodes

    def finished(self) -> bool:
        with self._lock:
            return bool(self._datasets) and all(
                ds.completed() for ds in self._datasets.values()
            )

    def checkpoint(self, dataset_name: str) -> str:
        ds = self.get_dataset(dataset_name)
        return ds.checkpoint() if ds else ""

    def restore_checkpoint(self, dataset_name: str, content: str) -> None:
        ds = self.get_dataset(dataset_name)
        if ds is not None:
            ds.restore_checkpoint(content)

    # -- persistence (snapshot / replay / re-attach) -----------------------

    def export_state(self) -> Dict:
        with self._lock:
            datasets = dict(self._datasets)
            params = {k: dict(v) for k, v in self._dataset_params.items()}
        return {
            "params": params,
            "datasets": {
                name: ds.export_state() for name, ds in datasets.items()
            },
        }

    def import_state(self, state: Dict) -> None:
        for name, params in (state.get("params") or {}).items():
            self._new_dataset_dict(dict(params), journal=False)
        for name, ds_state in (state.get("datasets") or {}).items():
            ds = self.get_dataset(name)
            if ds is not None:
                ds.import_state(ds_state)

    def apply_journal(self, kind: str, data: Dict) -> None:
        if kind == "task.dataset":
            self._new_dataset_dict(dict(data), journal=False)
            return
        ds = self.get_dataset(data.get("dataset", ""))
        if ds is not None:
            ds.apply_journal(kind, data)

    def begin_reattach(self, grace_s: float) -> None:
        """Arm the post-replay reconfirmation window."""
        with self._lock:
            self._reattach_deadline = time.time() + max(0.0, grace_s)

    def confirm_tasks(
        self, node_id: int, dataset_name: str, task_ids: List[int]
    ) -> int:
        ds = self.get_dataset(dataset_name)
        if ds is None:
            return 0
        return ds.confirm_tasks(node_id, task_ids)

    def reconcile_unconfirmed(self) -> int:
        """Called from the master run loop: once the re-attach grace has
        expired, requeue in-flight shards whose owners never re-reported."""
        with self._lock:
            if not self._reattach_deadline:
                return 0
            if time.time() < self._reattach_deadline:
                return 0
            self._reattach_deadline = 0.0
            datasets = list(self._datasets.values())
        return sum(ds.reconcile_unconfirmed() for ds in datasets)
