"""Job/node management: node tables, heartbeats, relaunch decisions.

Reference shape: ``master/node/job_manager.py`` + ``local_job_manager.py`` +
the event-processing half of ``dist_job_manager.py`` (:459-1046). The
platform-scheduler half (creating pods/VMs) lives behind
:mod:`dlrover_tpu.scheduler`; in local/standalone mode relaunch decisions
are delivered to agents as diagnosis actions instead.
"""

import threading
import time
from typing import Dict, List, Optional

from ...common.config import get_context
from ...common.constants import (
    JobExitReason,
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from ...common.log import logger
from ...common.node import Node, NodeEvent
from ..diagnosis.action import (
    DiagnosisActionType,
    JobAbortionAction,
    NodeAction,
)
from ..job_context import get_job_context


class JobManager:
    def __init__(self, num_workers: int = 1):
        self._ctx = get_context()
        self._job_ctx = get_job_context()
        self.num_workers = num_workers
        self._stopped = False
        self._monitor_thread: Optional[threading.Thread] = None
        self._next_node_id = num_workers

    def start(self) -> None:
        for node_id in range(self.num_workers):
            if self._job_ctx.get_node(NodeType.WORKER, node_id) is None:
                self._job_ctx.update_node(
                    Node(
                        node_type=NodeType.WORKER,
                        node_id=node_id,
                        rank_index=node_id,
                        max_relaunch_count=self._ctx.max_relaunch_count,
                    )
                )
        self._monitor_thread = threading.Thread(
            target=self._monitor_heartbeats, name="heartbeat-monitor", daemon=True
        )
        self._monitor_thread.start()

    def stop(self) -> None:
        self._stopped = True

    # -- status reports from agents ---------------------------------------

    def update_node_status(
        self, node_id: int, node_type: str, status: str, exit_reason: str = ""
    ) -> None:
        node = self._job_ctx.get_node(node_type, node_id)
        if node is None:
            node = Node(
                node_type=node_type,
                node_id=node_id,
                rank_index=node_id,
                max_relaunch_count=self._ctx.max_relaunch_count,
            )
        changed = node.update_status(status)
        if exit_reason:
            node.exit_reason = exit_reason
        self._job_ctx.update_node(node)
        if changed and status == NodeStatus.FAILED:
            self._handle_node_failure(node)

    def process_event(self, event: NodeEvent) -> None:
        """Platform watcher events (pod added/modified/deleted)."""
        node = event.node
        if node is None:
            return
        if event.event_type == NodeEventType.DELETED:
            node.is_released = True
            if not node.exited():
                node.update_status(NodeStatus.DELETED)
            self._job_ctx.update_node(node)
            self._handle_node_failure(node, deleted=True)
        else:
            self._job_ctx.update_node(node)

    def record_heartbeat(self, node_id: int, timestamp: float) -> None:
        node = self._job_ctx.get_node(NodeType.WORKER, node_id)
        if node is not None:
            node.heartbeat_time = timestamp
            self._job_ctx.update_node(node)

    def handle_failure_report(
        self, node_id: int, error_data: str, restart_count: int
    ) -> None:
        node = self._job_ctx.get_node(NodeType.WORKER, node_id)
        if node is None:
            return
        node.relaunch_count = max(node.relaunch_count, restart_count)
        self._job_ctx.update_node(node)
        logger.warning("node %s reported failure: %s", node_id, error_data[:500])

    # -- relaunch policy ---------------------------------------------------

    def _handle_node_failure(self, node: Node, deleted: bool = False) -> None:
        """Decide relaunch vs abort (reference dist_job_manager.py:922-1046)."""
        if self._relaunchable(node):
            node.inc_relaunch_count()
            self._job_ctx.update_node(node)
            logger.info(
                "relaunching node %s (count %s/%s, reason=%s)",
                node.node_id,
                node.relaunch_count,
                node.max_relaunch_count,
                node.exit_reason,
            )
            self._job_ctx.node_actions.add_action(
                NodeAction(
                    node_id=node.node_id,
                    action_type=DiagnosisActionType.RELAUNCH_WORKER,
                    reason=node.exit_reason or ("deleted" if deleted else "failed"),
                )
            )
        elif not self._fault_tolerance_left():
            self._job_ctx.master_actions.add_action(
                JobAbortionAction(reason=JobExitReason.MAX_RELAUNCH)
            )

    def _relaunchable(self, node: Node) -> bool:
        if self._ctx.relaunch_always:
            return True
        return node.should_relaunch()

    def _fault_tolerance_left(self) -> bool:
        workers = self._job_ctx.get_nodes(NodeType.WORKER)
        return any(n.should_relaunch() for n in workers.values() if not n.exited())

    # -- heartbeat monitor -------------------------------------------------

    def _monitor_heartbeats(self) -> None:
        interval = max(1.0, self._ctx.heartbeat_interval_s)
        while not self._stopped and not self._job_ctx.is_stopped():
            time.sleep(interval)
            try:
                self._check_dead_nodes()
            except Exception:
                logger.exception("heartbeat monitor error")

    def _check_dead_nodes(self) -> None:
        """No heartbeat within the deadline → treat the node as dead
        (reference dist_job_manager.py:475-532, 600s window)."""
        deadline = self._ctx.heartbeat_deadline_s
        now = time.time()
        for node in self._job_ctx.get_nodes(NodeType.WORKER).values():
            if node.exited() or node.heartbeat_time <= 0:
                continue
            if now - node.heartbeat_time > deadline:
                logger.warning(
                    "node %s heartbeat lost for %.0fs; marking failed",
                    node.node_id,
                    now - node.heartbeat_time,
                )
                node.exit_reason = NodeExitReason.KILLED
                self.update_node_status(
                    node.node_id, node.node_type, NodeStatus.FAILED, NodeExitReason.KILLED
                )

    # -- queries -----------------------------------------------------------

    @staticmethod
    def _scaled_out(n) -> bool:
        # Intentionally removed by scale_down (is_released +
        # relaunchable=False set BEFORE the kill): its FAILED/KILLED end
        # state is the shrink working, not an error, so completion
        # accounting skips it. Ordinary deletions only set is_released
        # (relaunchable stays True) and still count.
        return n.is_released and not n.relaunchable

    def all_workers_exited(self) -> bool:
        workers = [
            n
            for n in self._job_ctx.get_nodes(NodeType.WORKER).values()
            if not self._scaled_out(n)
        ]
        return bool(workers) and all(n.exited() for n in workers)

    def all_workers_succeeded(self) -> bool:
        workers = [
            n
            for n in self._job_ctx.get_nodes(NodeType.WORKER).values()
            if not self._scaled_out(n)
        ]
        return bool(workers) and all(
            n.status == NodeStatus.SUCCEEDED for n in workers
        )

    def alive_workers(self) -> List[Node]:
        return [
            n
            for n in self._job_ctx.get_nodes(NodeType.WORKER).values()
            if n.status == NodeStatus.RUNNING
        ]
