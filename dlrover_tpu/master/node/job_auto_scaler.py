"""Job auto-scaler: execute resource plans as scale operations.

Reference: ``JobAutoScaler``/``AllreduceTrainingAutoScaler``
(dlrover/python/master/node/job_auto_scaler.py:71,276) — the allreduce
path periodically grows workers toward the max (:315); plan execution
flows optimizer → ResourcePlan → ScalePlan → Scaler.

TPU constraint: world sizes move in node_unit (slice) steps, and a grown
world only takes effect at the next rendezvous wave — the rendezvous
manager admits the new hosts and the agents restart the worker group
(num_nodes_waiting ≥ node_unit), rebuilding the mesh.
"""

import threading
from typing import Optional

from ...common import comm
from ...common.config import get_context
from ...common.log import logger
from ..job_context import get_job_context
from ..scaler.base_scaler import ScalePlan, Scaler
from ..resource.optimizer import ResourceOptimizer, ResourcePlan


class JobAutoScaler:
    def __init__(
        self,
        optimizer: ResourceOptimizer,
        scaler: Scaler,
        node_unit: int = 1,
        max_workers: int = 1,
        world_size_fn=None,
        stats=None,
        strategy_generator=None,
        straggler_handler=None,
        shrink_handler=None,
        quota=None,
    ):
        self._ctx = get_context()
        self._job_ctx = get_job_context()
        self._optimizer = optimizer
        self._scaler = scaler
        self._unit = max(1, node_unit)
        self._max = max_workers
        # Supplies the current rendezvous world size to size-aware
        # optimizers (ThroughputScalingOptimizer.record_world_size).
        self._world_size_fn = world_size_fn
        # Real-metrics pipeline (reference master/stats/): collector of
        # per-node runtime series, the hyperparam strategy generator fed
        # by it, and the straggler exclusion callback (node_id -> None).
        self._stats = stats
        self._strategy = strategy_generator
        self._straggler_handler = straggler_handler
        # Executes a shrink (target_workers -> None) with drain
        # semantics: released nodes must be marked intentional before
        # the kill, and the rendezvous bounds must drop, so the shrink
        # routes through the job manager instead of the raw scaler.
        self._shrink_handler = shrink_handler
        # Cluster quota (reference master/cluster/quota.py): grow plans
        # are capped at what the cluster can actually schedule, so the
        # job never parks pending pods into the pending-timeout abort.
        self._quota = quota
        self._excluded_stragglers: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    def execute_job_optimization_plan(self, plan: ResourcePlan) -> None:
        """Reference job_auto_scaler.py:71 — plan → scale + tuning push."""
        if plan.empty():
            return
        if plan.dataloader_batch_size > 0 or plan.grad_accum_steps > 0:
            prev = self._job_ctx.paral_config
            version = (prev.version if prev else 0) + 1
            self._job_ctx.paral_config = comm.ParallelConfig(
                dataloader_batch_size=plan.dataloader_batch_size,
                grad_accum_steps=plan.grad_accum_steps,
                version=version,
            )
            logger.info(
                "pushed tuning config v%s (batch=%s accum=%s)",
                version,
                plan.dataloader_batch_size,
                plan.grad_accum_steps,
            )
        if plan.worker_num > 0:
            target = (plan.worker_num // self._unit) * self._unit
            target = min(target, self._max)
            if target <= 0:
                return
            current = (
                self._world_size_fn() if self._world_size_fn else 0
            )
            if 0 < target < current and self._shrink_handler is not None:
                # Shrink (optimizer saturation / Brain running-stage
                # advice): drain path, not a bare kill.
                logger.info(
                    "auto-scale DOWN %s -> %s workers", current, target
                )
                self._shrink_handler(target)
                return
            if target > current > 0 and self._quota is not None:
                free = self._quota.get_free_node_num()
                capped = current + (free // self._unit) * self._unit
                if capped < target:
                    logger.info(
                        "quota caps grow %s -> %s (free hosts: %s)",
                        target,
                        capped,
                        free,
                    )
                    target = capped
                if target <= current:
                    return
            logger.info("auto-scale to %s workers", target)
            self._scaler.scale(ScalePlan(worker_num=target))

    # -- periodic loop (allreduce auto-scale, reference :315) --------------

    def start(self) -> None:
        enabled = (
            self._ctx.auto_tuning_enabled or self._ctx.exclude_stragglers
        )
        if self._thread is not None or not enabled:
            return
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name="auto-scaler", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        interval = max(5.0, self._ctx.auto_scaling_interval_s)
        while not self._stopped.wait(interval):
            try:
                self.run_once()
            except Exception:
                logger.exception("auto-scaler loop error")

    def run_once(self) -> None:
        """One supervision round: scale decision from throughput, then
        hyperparam suggestions, then straggler exclusion — each gated on
        its own opt-in (a user enabling only straggler exclusion must
        not get auto scale-ups)."""
        if self._ctx.auto_tuning_enabled:
            if self._world_size_fn is not None and hasattr(
                self._optimizer, "record_world_size"
            ):
                self._optimizer.record_world_size(self._world_size_fn())
            self.execute_job_optimization_plan(
                self._optimizer.generate_plan()
            )
            if self._strategy is not None:
                self.execute_job_optimization_plan(
                    self._strategy.generate_plan()
                )
        self._check_stragglers()

    def _check_stragglers(self) -> None:
        """Runtime straggler exclusion (reference job_auto_scaler.py:241
        PS migration + the rdzv median rule applied to live step times):
        a consistently slow host drags every ICI collective, so it is
        handed to the straggler handler (relaunch/exclude) once."""
        if self._stats is None or self._straggler_handler is None:
            return
        if not self._ctx.exclude_stragglers:
            return  # destructive exclusion is its own opt-in flag
        from ...common.constants import NodeType

        for node_id in self._stats.detect_stragglers():
            node = self._job_ctx.get_node(NodeType.WORKER, node_id)
            # Key by incarnation: migration reuses the node id, and the
            # replacement (higher relaunch_count) must stay detectable.
            key = (node_id, node.relaunch_count if node else 0)
            if key in self._excluded_stragglers:
                continue
            self._excluded_stragglers.add(key)
            self._stats.evict(node_id)  # old samples must not skew peers
            # A straggler was dragging every collective; any saturation
            # knee measured while it ran is evidence about the old
            # fleet, not the post-exclusion one.
            if hasattr(self._optimizer, "invalidate_frontier"):
                self._optimizer.invalidate_frontier(
                    f"straggler {node_id} excluded"
                )
            logger.warning(
                "straggler node %s (step time > %.1fx median); excluding",
                node_id,
                self._ctx.straggler_median_ratio,
            )
            try:
                self._straggler_handler(node_id)
            except Exception:
                logger.exception("straggler handler failed for %s", node_id)

    def stop(self) -> None:
        self._stopped.set()
        self._thread = None
