"""Distributed job manager: watcher-driven node lifecycle + relaunch.

Reference: ``DistributedJobManager`` (dlrover/python/master/node/
dist_job_manager.py:102): node watcher thread (:459), heartbeat monitor
(:475), event processing through the status flow (:733), relaunch policy
(:922) issuing ScalePlans (:1010), group relaunch (:1046) and early-stop
conditions (:257).

TPU shape: a node is a TPU host; group relaunch moves in slice
granularity (node_unit hosts at a time) because a slice with a dead
host cannot run its ICI collectives at all.
"""

import threading
import time
from typing import List, Optional

from ...common.config import get_context
from ...common.constants import (
    JobExitReason,
    NodeEventType,
    NodeStatus,
    NodeType,
)
from ...common.log import logger
from ...common.node import Node, NodeEvent
from ..diagnosis.action import JobAbortionAction
from ..scaler.base_scaler import ScalePlan, Scaler
from ..watcher.base import NodeWatcher
from .job_manager import JobManager


class DistributedJobManager(JobManager):
    # How long a slice-relaunch replacement shields itself from its
    # predecessor's in-flight DELETED event (see process_event). The
    # watcher polls at ~0.5-1 s, so stale events land within a couple
    # of polls; a replacement that genuinely dies inside this window
    # while still INITIAL is caught by the pending/heartbeat monitors.
    STALE_DELETE_GRACE_S = 5.0

    def __init__(
        self,
        num_workers: int,
        scaler: Scaler,
        watcher: Optional[NodeWatcher] = None,
        node_unit: int = 1,
    ):
        super().__init__(num_workers=num_workers)
        self._scaler = scaler
        self._watcher = watcher
        self._node_unit = max(1, node_unit)
        self._watch_thread: Optional[threading.Thread] = None
        self._pending_since: Optional[float] = None
        self._suspended = False
        # Observability for chaos harnesses: how many times the
        # slice-granular recovery path actually ran.
        self.slice_relaunches = 0

    def start(self) -> None:
        super().start()
        if self._node_unit > 1:
            # Ranks are slice-contiguous (node_unit hosts per slice) —
            # the same mapping the agents report at rendezvous join.
            for node in self._job_ctx.get_nodes(NodeType.WORKER).values():
                node.slice_id = max(0, node.rank_index) // self._node_unit
                self._job_ctx.update_node(node)
        self._scaler.start()
        # Materialize the initial world.
        self._scaler.scale(ScalePlan(worker_num=self.num_workers))
        if self._watcher is not None:
            self._watch_thread = threading.Thread(
                target=self._watch_nodes, name="node-watcher", daemon=True
            )
            self._watch_thread.start()

    def stop(self) -> None:
        super().stop()
        if self._watcher is not None:
            self._watcher.stop()
        self._scaler.stop()

    # -- platform event loop ----------------------------------------------

    def _watch_nodes(self) -> None:
        """Reference dist_job_manager.py:459 — consume watcher events."""
        while not self._stopped:
            try:
                for event in self._watcher.watch():
                    if self._stopped:
                        return
                    self.process_event(event)
            except Exception:
                logger.exception("node watcher error; retrying")
                time.sleep(1)

    def _slice_of(self, node: Node) -> int:
        """Slice membership derived from the rank (ranks are assigned
        slice-contiguously, node_unit hosts per slice). Derived, not
        read from node.slice_id: watcher-built event nodes carry the
        default 0, and a stale 0 here would group-relaunch the WRONG
        slice."""
        if self._node_unit <= 1:
            return 0
        return max(0, node.rank_index) // self._node_unit

    def process_event(self, event: NodeEvent) -> None:
        node = event.node
        if node is None:
            return
        if self._node_unit > 1 and node.node_type == NodeType.WORKER:
            # Watcher-built event nodes default slice_id to 0; stamp the
            # derived membership so the adoption paths below never
            # insert a mis-sliced record into the job context.
            node.slice_id = self._slice_of(node)
        if self._suspended and event.event_type == NodeEventType.DELETED:
            # Suspension removes the pods on purpose; their deletions are
            # not failures and must not consume the relaunch budget.
            current = self._job_ctx.get_node(node.node_type, node.node_id)
            if current is not None:
                current.is_released = True
                self._job_ctx.update_node(current)
            return
        if event.event_type == NodeEventType.DELETED:
            current = self._job_ctx.get_node(node.node_type, node.node_id)
            if (
                current is not None
                and current.status == NodeStatus.INITIAL
                and current.stale_delete_until > time.time()
            ):
                # A slice relaunch registered this replacement while its
                # predecessor's death was still in the watcher pipeline:
                # this deletion is the predecessor's, already handled by
                # the group relaunch — consuming it as the REPLACEMENT's
                # failure would burn budget and kill the fresh node.
                current.stale_delete_until = 0.0
                self._job_ctx.update_node(current)
                logger.info(
                    "ignoring stale deletion for relaunched node %s",
                    node.node_id,
                )
                return
            if current is not None:
                # The agent's own status report (RPC, arrives first) knows
                # WHY it exited — e.g. RELAUNCH_REQUESTED. The watcher only
                # guesses from the return code (any rc>0 reads FATAL_ERROR),
                # so its guess must never clobber a reported reason: that
                # clobber turned every agent-requested relaunch into a
                # never-relaunch verdict and stranded the node.
                current.exit_reason = current.exit_reason or node.exit_reason
                if not current.exited():
                    current.update_status(
                        NodeStatus.FAILED
                        if node.status == NodeStatus.FAILED
                        else node.status
                    )
                node = current
            # Decide relaunch BEFORE marking released: a released node is
            # never relaunchable, but this deletion IS the failure we are
            # reacting to.
            relaunch = (
                node.status == NodeStatus.FAILED and node.should_relaunch()
            )
            node.is_released = True
            self._job_ctx.update_node(node)
            if node.status == NodeStatus.FAILED:
                self._relaunch_node(node, allowed=relaunch)
        else:
            current = self._job_ctx.get_node(node.node_type, node.node_id)
            if (
                current is not None
                and self._scaled_out(current)
                and current.exited()
            ):
                # A released id re-materialized (grow after a shrink):
                # the stale terminal record would make the fresh worker
                # a ghost (excluded from completion, never relaunched).
                # Adopt the event node as a brand-new incarnation.
                self._job_ctx.update_node(node)
            elif current is not None:
                current.update_status(node.status)
                self._job_ctx.update_node(current)
            else:
                self._job_ctx.update_node(node)

    # -- relaunch (platform path) -----------------------------------------

    def _relaunch_node(self, node: Node, allowed: Optional[bool] = None) -> None:
        """Replace a dead node via the scaler (reference :1010)."""
        if self._suspended:
            return
        if allowed is None:
            allowed = node.should_relaunch()
        if not allowed:
            if self._scaled_out(node):
                return  # intentional shrink removal: never abort-worthy
            if not self._fault_tolerance_left():
                self._job_ctx.master_actions.add_action(
                    JobAbortionAction(reason=JobExitReason.MAX_RELAUNCH)
                )
            return
        if self._node_unit > 1:
            # TPU shape: one dead host means the slice's ICI domain
            # cannot run collectives at all — surviving members would
            # only rejoin as a short slice the rendezvous must truncate
            # away. Replace the whole slice as a unit instead.
            self.relaunch_slice(self._slice_of(node))
            return
        replacement = self._consume_budget(node)
        logger.info(
            "relaunching node %s via scaler (count %s/%s)",
            node.node_id,
            node.relaunch_count,
            node.max_relaunch_count,
        )
        self._scaler.scale(ScalePlan(launch_nodes=[replacement]))

    def _consume_budget(self, node: Node) -> Node:
        """Burn one relaunch and register the replacement node (shared
        by the dead-node and straggler-migration paths)."""
        node.inc_relaunch_count()
        self._job_ctx.update_node(node)
        replacement = node.get_relaunch_node(node.node_id)
        replacement.relaunch_count = node.relaunch_count
        self._job_ctx.update_node(replacement)
        return replacement

    def migrate_straggler(self, node_id: int) -> None:
        """Replace a live-but-slow node: remove its pod AND launch a
        replacement in one plan (the dead-node path only launches, which
        against a still-running pod is a 409 no-op). Budget rules apply —
        a straggler that exhausted its relaunch count stays."""
        node = self._job_ctx.get_node(NodeType.WORKER, node_id)
        if node is None or node.exited() or node.is_released:
            return
        if not node.should_relaunch():
            logger.warning(
                "straggler node %s has no relaunch budget left; keeping it",
                node_id,
            )
            return
        node.is_released = True
        replacement = self._consume_budget(node)
        logger.info("migrating straggler node %s", node_id)
        self._scaler.scale(
            ScalePlan(remove_nodes=[node_id], launch_nodes=[replacement])
        )

    def relaunch_slice(self, slice_id: int) -> None:
        """Group relaunch (reference :1046): replace every host of a
        slice together — a slice is the unit of ICI connectivity.

        The replacements (same node ids: a relaunched "pod" lands on
        the same simulated host, reattaching its staged shm checkpoint)
        are registered in the job context NOW, so the fleet's view never
        holds terminal records for ids that are about to come back —
        and each carries a short stale-delete shield because members
        killed by the same fault may still have DELETED events in the
        watcher pipeline when this runs."""
        workers = self._job_ctx.get_nodes(NodeType.WORKER)
        members = [
            n
            for n in workers.values()
            if self._slice_of(n) == slice_id and not self._scaled_out(n)
        ]
        if not members:
            return
        self.slice_relaunches += 1
        logger.info(
            "slice %s group relaunch: nodes %s",
            slice_id,
            sorted(n.node_id for n in members),
        )
        shield_until = time.time() + self.STALE_DELETE_GRACE_S
        replacements = []
        for node in members:
            replacement = self._consume_budget(node)
            replacement.stale_delete_until = shield_until
            self._job_ctx.update_node(replacement)
            replacements.append(replacement)
        self._scaler.scale(
            ScalePlan(
                remove_nodes=[n.node_id for n in members],
                launch_nodes=replacements,
            )
        )

    # -- scale down (reference job_auto_scaler.py:276-345 shrink path) -----

    def scale_down(self, target: int):
        """Release the highest-ranked workers so the job continues at
        ``target`` hosts. The released nodes are marked BEFORE the
        scaler kills them: their DELETED events must read as intentional
        removals, not failures — otherwise the relaunch budget would
        resurrect every host the optimizer just released. Returns the
        removed node ids.
        """
        target = max(0, int(target))
        workers = self._job_ctx.get_nodes(NodeType.WORKER)
        active = sorted(
            (n for n in workers.values() if not n.exited() and not n.is_released),
            key=lambda n: n.rank_index,
        )
        if target >= len(active):
            return []
        # Multislice jobs shrink by WHOLE slices: a slice missing some
        # hosts is dead weight (its ICI domain can't form the per-slice
        # mesh), so round the target DOWN to a slice boundary. Ranks are
        # slice-grouped by the rendezvous TopologySorter, so a boundary
        # in rank order is a boundary between slices.
        if len({n.slice_id for n in active}) > 1:
            boundaries = [
                i
                for i in range(1, len(active))
                if active[i].slice_id != active[i - 1].slice_id
            ]
            below = [b for b in boundaries if b <= target]
            if below:
                aligned = below[-1]
            else:
                # A nonzero target below the first boundary rounds UP to
                # one whole slice: a shrink request must never be
                # silently escalated into killing the entire job.
                aligned = boundaries[0] if target > 0 else 0
            if aligned != target:
                logger.info(
                    "scale_down target %s not slice-aligned; using slice "
                    "boundary %s", target, aligned
                )
                target = aligned
            if target >= len(active):
                return []
        removed = active[target:]  # keep the lowest ranks: dp shrinks
        ids = []
        for node in removed:
            node.is_released = True
            node.relaunchable = False
            # Terminal NOW: the process/Ray scalers drop the handle
            # synchronously, so no DELETED event ever arrives for these
            # — a record stuck in RUNNING would defeat the
            # grow-after-shrink adoption (which requires exited()).
            node.update_status(NodeStatus.DELETED)
            self._job_ctx.update_node(node)
            ids.append(node.node_id)
        self.num_workers = target
        logger.info(
            "scaling down to %s workers: releasing nodes %s", target, ids
        )
        self._scaler.scale(ScalePlan(worker_num=target, remove_nodes=ids))
        return ids

    # -- suspend / resume (reference K8sElasticJobWatcher, k8s_watcher.py:427)

    @property
    def is_suspended(self) -> bool:
        return self._suspended

    def suspend(self) -> None:
        """Tear the worker pods down without failing the job (ElasticJob
        ``spec.suspend`` — reference elasticjob_types.go:29-130)."""
        if self._suspended:
            return
        self._suspended = True
        workers = self._job_ctx.get_nodes(NodeType.WORKER)
        ids = []
        for node in workers.values():
            if not node.exited():
                ids.append(node.node_id)
            node.is_released = True
            self._job_ctx.update_node(node)
        logger.info("suspending job: removing workers %s", sorted(ids))
        self._scaler.scale(ScalePlan(worker_num=0, remove_nodes=ids))

    def resume(self) -> None:
        if not self._suspended:
            return
        self._suspended = False
        # Reset node bookkeeping: suspension marked every node released,
        # and a released node is never relaunchable — without this, a
        # post-resume crash would leave the job permanently short.
        # Scale-down casualties keep their marker: resume must not turn
        # an intentional removal back into an abort-worthy FAILED.
        for node in self._job_ctx.get_nodes(NodeType.WORKER).values():
            if self._scaled_out(node):
                continue
            node.is_released = False
            node.update_status(NodeStatus.PENDING)
            self._job_ctx.update_node(node)
        logger.info("resuming job: scaling back to %s workers", self.num_workers)
        self._scaler.scale(ScalePlan(worker_num=self.num_workers))

    # -- early stop (reference should_early_stop :257) ---------------------

    def should_early_stop(self) -> Optional[str]:
        workers = self._job_ctx.get_nodes(NodeType.WORKER)
        if not workers:
            return None
        pending = [
            n
            for n in workers.values()
            if n.status in (NodeStatus.INITIAL, NodeStatus.PENDING)
            and not n.is_released
        ]
        if pending and len(pending) == len(workers):
            if self._pending_since is None:
                self._pending_since = time.time()
            elif (
                time.time() - self._pending_since
                > self._ctx.seconds_to_wait_pending_pod
            ):
                return JobExitReason.PENDING_TIMEOUT
        else:
            self._pending_since = None
        if not self._fault_tolerance_left() and any(
            n.status == NodeStatus.FAILED and not self._scaled_out(n)
            for n in workers.values()
        ):
            # scaled-out nodes end FAILED (killed on purpose) and stay in
            # the context; counting them would abort a healthy shrunken
            # job once the survivors' budgets are spent.
            return JobExitReason.MAX_RELAUNCH
        return None
