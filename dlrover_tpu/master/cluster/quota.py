"""Cluster quota awareness (reference ``master/cluster/quota.py:18``).

Scale-ups must not ask for hosts the cluster cannot give: a grow plan
beyond the free quota leaves pending pods that trip the
pending-timeout abort. The checker answers "how many MORE hosts can
this job get right now"; the auto-scaler caps grow targets with it.
"""

from abc import ABC, abstractmethod

from ...common.log import logger


class QuotaChecker(ABC):
    @abstractmethod
    def get_free_node_num(self) -> int:
        """Hosts the cluster could schedule for this job right now."""


class UnlimitedQuotaChecker(QuotaChecker):
    """Default: the platform will make room (autoscaling node pools)."""

    def get_free_node_num(self) -> int:
        return 1 << 30


class StaticQuotaChecker(QuotaChecker):
    """Fixed reservation (on-prem slice pools, test rigs)."""

    def __init__(self, free_nodes: int):
        self._free = max(0, int(free_nodes))

    def set_free_node_num(self, free_nodes: int) -> None:
        self._free = max(0, int(free_nodes))

    def get_free_node_num(self) -> int:
        return self._free


class K8sQuotaChecker(QuotaChecker):
    """Free TPU hosts = schedulable nodes carrying the TPU resource
    minus nodes already running a TPU-requesting pod. Coarse (node
    granularity — TPU hosts are not fractionally shared), which matches
    how slices schedule."""

    TPU_RESOURCE = "google.com/tpu"

    def __init__(self, client=None, namespace: str = "default"):
        if client is None:
            from ...scheduler.kubernetes import k8sClient

            client = k8sClient.singleton(namespace)
        self._client = client

    def get_free_node_num(self) -> int:
        try:
            nodes = self._client.list_nodes()
            pods = self._client.list_all_pods()
        except Exception:  # noqa: BLE001 — degrade to "don't block"
            logger.exception("quota query failed; assuming unlimited")
            return 1 << 30
        tpu_nodes = set()
        for node in nodes or []:
            alloc = (
                getattr(node.status, "allocatable", None) or {}
                if hasattr(node, "status")
                else node.get("status", {}).get("allocatable", {})
            )
            name = (
                node.metadata.name
                if hasattr(node, "metadata")
                else node.get("metadata", {}).get("name", "")
            )
            unschedulable = (
                getattr(node.spec, "unschedulable", False)
                if hasattr(node, "spec")
                else node.get("spec", {}).get("unschedulable", False)
            )
            if not unschedulable and self.TPU_RESOURCE in (alloc or {}):
                tpu_nodes.add(name)
        busy = set()
        for pod in pods or []:
            phase = (
                getattr(getattr(pod, "status", None), "phase", "")
                if hasattr(pod, "status")
                else pod.get("status", {}).get("phase", "")
            )
            if phase in ("Succeeded", "Failed"):
                continue  # terminated pods no longer hold the device
            spec = (
                pod.spec if hasattr(pod, "spec") else pod.get("spec", {})
            )
            node_name = (
                getattr(spec, "node_name", "")
                if hasattr(pod, "spec")
                else spec.get("nodeName", "")
            )
            containers = (
                getattr(spec, "containers", [])
                if hasattr(pod, "spec")
                else spec.get("containers", [])
            )
            for c in containers or []:
                limits = (
                    (getattr(c, "resources", None) and c.resources.limits)
                    if hasattr(c, "resources")
                    else c.get("resources", {}).get("limits", {})
                ) or {}
                if self.TPU_RESOURCE in limits and node_name:
                    busy.add(node_name)
                    break
        return max(0, len(tpu_nodes - busy))
