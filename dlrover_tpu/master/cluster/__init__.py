from .quota import (  # noqa: F401
    K8sQuotaChecker,
    QuotaChecker,
    StaticQuotaChecker,
    UnlimitedQuotaChecker,
)
