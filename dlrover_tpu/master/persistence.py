"""Master crash tolerance: durable state journal + epoch-fenced reboot.

The master is the coordination plane's last single point of failure:
node tables, rendezvous rounds, shard doing/done sets, kv-store and
sync-service contents all lived purely in memory, so a SIGKILLed master
cost the whole job even though every worker was healthy. This module
makes the master restartable by its orchestrator with the job riding
through:

- :class:`MasterStateStore` — a durable journal under
  ``DLROVER_MASTER_STATE_DIR`` (Context ``master_state_dir``): an atomic
  snapshot (tmp + rename) plus an O_APPEND JSONL WAL, the same idiom as
  the chip-pool decision journal. Every record carries a monotonic
  ``seq``; the snapshot stamps the last seq it covers, so a crash
  between snapshot-rename and WAL-truncate replays each record exactly
  once.
- a **master epoch** — an integer bumped once per boot from the same
  state dir and stamped on every RPC response. Agents and the rpc
  client detect a restarted master by the bump, fence stale in-flight
  responses from the dead incarnation, and re-attach (re-register +
  verify the recovered world) instead of dying on it.
- :class:`MasterPersistence` — the façade a master wires in: ``boot``
  bumps the epoch and replays snapshot + WAL into the freshly-built
  components (``master.boot.replay`` injection point), ``attach`` hangs
  the journal hooks off the kv store / sync service / task manager /
  rendezvous managers, and ``tick`` (called from the master run loop,
  never from inside a component lock) compacts the WAL into a new
  snapshot.

Shard state is the one thing replay alone cannot make exact: a task
issued between the last WAL write and the crash window is closed by
WAL-before-respond ordering (the issue record lands before the agent
ever sees the task), and the replayed ``doing`` set starts *unconfirmed*
— agents re-report the task ids they actually hold
(``TaskInFlightReport``), confirmed entries stay in flight, everything
else is re-queued exactly once (per-node immediately on its report,
stragglers at the ``master_reattach_grace_s`` deadline).
"""

import base64
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..chaos import faults
from ..common.config import get_context
from ..common.log import logger

SNAPSHOT_NAME = "snapshot.json"
WAL_NAME = "wal.jsonl"
EPOCH_NAME = "epoch"


def b64e(value: bytes) -> str:
    return base64.b64encode(value or b"").decode("ascii")


def b64d(value: str) -> bytes:
    return base64.b64decode(value or "")


class MasterStateStore:
    """Snapshot + WAL + epoch files under one state directory.

    Single-writer by contract (one master process owns a state dir at a
    time — the orchestrator restarts the master, it never runs two).
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._mu = threading.Lock()
        self._seq = self._scan_last_seq()

    # -- epoch -------------------------------------------------------------

    def _epoch_path(self) -> str:
        return os.path.join(self.root, EPOCH_NAME)

    def read_epoch(self) -> int:
        try:
            return int(open(self._epoch_path()).read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def bump_epoch(self) -> int:
        """Increment the boot epoch atomically; first boot yields 1."""
        epoch = self.read_epoch() + 1
        self._atomic_write(self._epoch_path(), str(epoch))
        return epoch

    # -- WAL ---------------------------------------------------------------

    def _wal_path(self) -> str:
        return os.path.join(self.root, WAL_NAME)

    def _scan_last_seq(self) -> int:
        last = 0
        snap = self._read_json(os.path.join(self.root, SNAPSHOT_NAME))
        if snap:
            last = int(snap.get("wal_seq", 0))
        for rec in self._read_wal():
            last = max(last, int(rec.get("seq", 0)))
        return last

    def last_seq(self) -> int:
        with self._mu:
            return self._seq

    def append(self, kind: str, payload: Dict[str, Any]) -> int:
        """One O_APPEND write per record; the write stays under the
        store lock so a concurrent compaction (WAL rewrite) can never
        interleave with it. Never raises — a full disk must degrade
        durability, not take the control plane down."""
        with self._mu:
            self._seq += 1
            seq = self._seq
            entry = {"seq": seq, "ts": round(time.time(), 3), "kind": kind,
                     "data": payload}
            try:
                line = (json.dumps(entry) + "\n").encode()
                fd = os.open(
                    self._wal_path(),
                    os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                    0o644,
                )
                try:
                    os.write(fd, line)
                finally:
                    os.close(fd)
            except (OSError, TypeError, ValueError):
                logger.warning("master WAL append failed for %s", kind)
        return seq

    def _read_wal(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        try:
            with open(self._wal_path()) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        # a torn tail write (crash mid-append) ends the
                        # replayable prefix; later records cannot exist
                        break
        except OSError:
            pass
        return out

    # -- snapshot ----------------------------------------------------------

    def write_snapshot(
        self, state: Dict[str, Any], floor: Optional[int] = None
    ) -> None:
        """Atomic snapshot, then WAL compaction.

        ``floor`` is the seq the caller observed BEFORE capturing
        ``state`` — records at or below it are covered by the snapshot;
        records above it may or may not be (a mutation journaled while
        capture was reading other components), so compaction REWRITES
        the WAL keeping them instead of truncating — replay applies
        them idempotently. Crash windows: before the snapshot rename
        the old pair still replays; between rename and rewrite the
        old WAL's covered records are filtered by seq on load."""
        with self._mu:
            if floor is None:
                floor = self._seq
            state = dict(state, wal_seq=floor)
            path = os.path.join(self.root, SNAPSHOT_NAME)
            try:
                self._atomic_write(path, json.dumps(state))
                keep = [
                    json.dumps(r)
                    for r in self._read_wal()
                    if int(r.get("seq", 0)) > floor
                ]
                self._atomic_write(
                    self._wal_path(),
                    "".join(line + "\n" for line in keep),
                )
            except (OSError, TypeError, ValueError):
                logger.warning("master snapshot write failed")

    def load(self) -> Tuple[Optional[Dict[str, Any]], List[Dict[str, Any]]]:
        """(snapshot or None, WAL records newer than the snapshot)."""
        snap = self._read_json(os.path.join(self.root, SNAPSHOT_NAME))
        floor = int(snap.get("wal_seq", 0)) if snap else 0
        wal = [r for r in self._read_wal() if int(r.get("seq", 0)) > floor]
        return snap, wal

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _atomic_write(path: str, text: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.rename(tmp, path)

    @staticmethod
    def _read_json(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


# ---------------------------------------------------------------------------
# capture / restore
# ---------------------------------------------------------------------------


def capture_master_state(master) -> Dict[str, Any]:
    """Full coordination-plane state: node tables + job stage, kv store,
    sync barriers, shard task queues, completed rendezvous worlds. Each
    component exports under its own lock; no lock spans components."""
    return {
        "job": master._job_ctx.export_state(),
        "kv": master.kv_store.export_state(),
        "sync": master.sync_service.export_state(),
        "tasks": master.task_manager.export_state(),
        "rdzv": {
            name: mgr.export_state()
            for name, mgr in master.rdzv_managers.items()
        },
    }


def restore_master_state(master, state: Dict[str, Any]) -> None:
    master._job_ctx.import_state(state.get("job") or {})
    master.kv_store.import_state(state.get("kv") or {})
    master.sync_service.import_state(state.get("sync") or {})
    master.task_manager.import_state(state.get("tasks") or {})
    for name, mgr_state in (state.get("rdzv") or {}).items():
        mgr = master.rdzv_managers.get(name)
        if mgr is not None:
            mgr.import_state(mgr_state)


def apply_wal_record(master, record: Dict[str, Any]) -> None:
    """Replay one WAL record onto restored components. Records are
    idempotent against the snapshot (a snapshot taken after the record
    already contains its effect; seq filtering makes that the rare
    crash-window case, but replay must still never double-apply)."""
    kind = record.get("kind", "")
    data = record.get("data") or {}
    if kind == "kv.set":
        master.kv_store.import_pairs({data["key"]: b64d(data["v"])})
    elif kind == "kv.multi":
        master.kv_store.import_pairs(
            {k: b64d(v) for k, v in (data.get("kvs") or {}).items()}
        )
    elif kind == "kv.del":
        master.kv_store.import_delete(data["key"])
    elif kind == "kv.clear":
        master.kv_store.import_clear()
    elif kind == "sync.join":
        master.sync_service.join(data["name"], int(data["node"]))
    elif kind == "sync.finish":
        master.sync_service.finish(data["name"])
    elif kind == "sync.expected":
        master.sync_service.set_expected(data["name"], int(data["count"]))
    elif kind == "sync.default":
        master.sync_service.set_default_expected(int(data["count"]))
    elif kind == "rdzv.complete":
        mgr = master.rdzv_managers.get(data.get("rdzv", ""))
        if mgr is not None:
            mgr.import_completed_world(
                int(data["round"]), data.get("world") or []
            )
    elif kind in ("task.dataset", "task.refill", "task.issue", "task.done"):
        master.task_manager.apply_journal(kind, data)
    else:
        logger.warning("unknown master WAL record kind %r", kind)


class MasterPersistence:
    """The façade a master composes: journal hooks in, replay on boot,
    periodic WAL compaction from the supervision loop."""

    def __init__(
        self,
        store: MasterStateStore,
        snapshot_every: int = 64,
    ):
        self.store = store
        self.snapshot_every = max(1, snapshot_every)
        self.epoch = 0
        self.replayed = False
        self.replay_s = 0.0
        self._records_since_snapshot = 0
        self._capture = None  # set by attach()

    @classmethod
    def from_env(cls) -> Optional["MasterPersistence"]:
        ctx = get_context()
        if not ctx.master_state_dir:
            return None
        return cls(
            MasterStateStore(ctx.master_state_dir),
            snapshot_every=ctx.master_snapshot_every,
        )

    # -- boot --------------------------------------------------------------

    def boot(self, master) -> int:
        """Bump the epoch, replay any prior state into the freshly-built
        components, attach the journal hooks. Returns the new epoch.
        Replay failures degrade to a fresh boot — an unreadable journal
        must never brick the master."""
        self.epoch = self.store.bump_epoch()
        t0 = time.monotonic()
        wal_count = 0
        try:
            # Chaos hook: a delay here stretches master MTTR (the drill
            # measures it); an error simulates a poisoned journal — the
            # master must boot fresh, not crash-loop.
            faults.inject("master.boot.replay", epoch=self.epoch)
            snapshot, wal = self.store.load()
            if snapshot is not None:
                restore_master_state(master, snapshot)
            for record in wal:
                apply_wal_record(master, record)
            wal_count = len(wal)
            self.replayed = snapshot is not None or wal_count > 0
        except Exception:  # noqa: BLE001 — degrade to a fresh boot
            logger.exception(
                "master state replay failed; booting with empty state"
            )
            self.replayed = False
        self.replay_s = round(time.monotonic() - t0, 3)
        if self.replayed:
            grace = get_context().master_reattach_grace_s
            master.task_manager.begin_reattach(grace)
            master._job_ctx.mark_replayed()
            logger.info(
                "master epoch %s: replayed journal in %.3fs (%s WAL records)",
                self.epoch,
                self.replay_s,
                wal_count,
            )
        self.attach(master)
        # MTTR attribution: the master's own phase of a master-kill
        # recovery (aggregated as master_replay_s; no-op without
        # DLROVER_RECOVERY_DIR).
        from ..attribution.recovery import record_phase_file

        record_phase_file(
            "master",
            {
                "replay_s": self.replay_s,
                "epoch": self.epoch,
                "replayed": self.replayed,
                "wal_records": wal_count,
            },
        )
        return self.epoch

    def attach(self, master) -> None:
        """Hang journal hooks off every stateful component. Hooks are
        invoked with the component's lock held, so they only append to
        the WAL (persistence never calls back into a component)."""
        self._capture = lambda: capture_master_state(master)
        master.kv_store.journal = self.record
        master.sync_service.journal = self.record
        master.task_manager.set_journal(self.record)
        for mgr in master.rdzv_managers.values():
            mgr.journal = self.record

    # -- journal -----------------------------------------------------------

    def record(self, kind: str, payload: Dict[str, Any]) -> None:
        self.store.append(kind, payload)
        self._records_since_snapshot += 1

    def tick(self, force: bool = False) -> bool:
        """Compact the WAL into a snapshot when it has grown past the
        threshold. Called from the master run loop (or stop) only —
        capture takes every component lock, so it must never run from
        inside a journal hook."""
        if self._capture is None:
            return False
        if not force and self._records_since_snapshot < self.snapshot_every:
            return False
        # Floor BEFORE capture: a mutation journaled while capture reads
        # the components may be missing from the snapshot — keeping its
        # WAL record (idempotent replay) is what makes that window safe.
        floor = self.store.last_seq()
        self.store.write_snapshot(self._capture(), floor=floor)
        self._records_since_snapshot = 0
        return True
