"""Process scaler: "nodes" are local agent processes.

The local analogue of the reference's ``PodScaler`` (pod_scaler.py:84) —
and the production standalone/chaos-test backend: each worker node is a
``tpurun``-agent subprocess with the proper ``NodeEnv`` contract. Multi-
host elasticity (kill a node → master relaunches it; scale up → new
nodes join the rendezvous) runs for real on one machine, which is also
how the reference validates fault tolerance without a cluster
(SURVEY §4, trick #1).
"""

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ...common.constants import NodeEnv, NodeStatus
from ...common.log import logger
from ...common.node import Node
from .base_scaler import ScalePlan, Scaler


@dataclass
class ProcessNodeSpec:
    """How to start one worker-node process."""

    command: List[str] = field(default_factory=list)  # argv of the agent
    env: Dict[str, str] = field(default_factory=dict)
    cwd: Optional[str] = None


class ProcessHandle:
    def __init__(self, node_id: int, proc: subprocess.Popen):
        self.node_id = node_id
        self.proc = proc
        self.started_at = time.time()

    def alive(self) -> bool:
        return self.proc.poll() is None

    def returncode(self) -> Optional[int]:
        return self.proc.poll()

    def kill(self) -> None:
        from ...common.proc import kill_process_group

        kill_process_group(self.proc, grace_s=10)


class ProcessScaler(Scaler):
    def __init__(
        self,
        spec: ProcessNodeSpec,
        master_addr: str,
        job_name: str = "job",
        num_workers: int = 1,
    ):
        super().__init__(job_name)
        self._spec = spec
        self._master_addr = master_addr
        self._target = num_workers
        self._procs: Dict[int, ProcessHandle] = {}
        self._next_node_id = num_workers

    # -- plan execution ----------------------------------------------------

    def scale(self, plan: ScalePlan) -> None:
        with self._lock:
            if plan.worker_num >= 0:
                self._target = plan.worker_num
            for node_id in plan.remove_nodes:
                self._kill_node(node_id)
            for node in plan.launch_nodes:
                self._launch_node(node.node_id, node.rank_index)
            self._reconcile()

    def _reconcile(self) -> None:
        """Launch missing node ids / trim beyond-target ones (caller holds
        the lock). Dead entries are deliberately NOT resurrected here: the
        watcher must report their DELETED and the job manager decide the
        relaunch (budget accounting) — reconcile only materializes nodes
        that have never existed (initial world, scale-up)."""
        known = set(self._procs)
        for rank in range(self._target):
            if rank not in known:
                self._launch_node(rank, rank)
        alive = sorted(
            nid for nid, h in self._procs.items() if h.alive()
        )
        for node_id in [n for n in alive if n >= self._target]:
            self._kill_node(node_id)

    def _launch_node(
        self, node_id: int, node_rank: int
    ) -> Optional[ProcessHandle]:
        old = self._procs.get(node_id)
        if old is not None and old.alive():
            old.kill()
        env = dict(os.environ)
        env.update(self._spec.env)
        env[NodeEnv.MASTER_ADDR] = self._master_addr
        env[NodeEnv.JOB_NAME] = self._job_name
        env[NodeEnv.NODE_ID] = str(node_id)
        env[NodeEnv.NODE_RANK] = str(node_rank)
        # Each simulated host gets its own machine-local IPC namespace
        # (keyed by node id, which relaunch preserves — so a replacement
        # agent reattaches the dead incarnation's staged shm checkpoint,
        # like a pod rescheduled onto the same host).
        env["DLROVER_IPC_NAMESPACE"] = f"{self._job_name}_n{node_id}"
        try:
            proc = subprocess.Popen(
                self._spec.command,
                env=env,
                cwd=self._spec.cwd,
                start_new_session=True,
            )
        except OSError as e:
            logger.error("failed to launch node %s: %s", node_id, e)
            return None
        handle = ProcessHandle(node_id, proc)
        self._procs[node_id] = handle
        logger.info("launched node %s pid=%s", node_id, proc.pid)
        return handle

    def _kill_node(self, node_id: int) -> None:
        handle = self._procs.pop(node_id, None)
        if handle is not None:
            logger.info("killing node %s pid=%s", node_id, handle.proc.pid)
            handle.kill()
        # A "node" death takes the whole pod: the agent's worker runs in
        # its own session, so killing the agent's group misses it.
        from ...agent.worker import kill_worker_by_pidfile

        kill_worker_by_pidfile(f"{self._job_name}_n{node_id}")

    # -- introspection (used by the local watcher) -------------------------

    def snapshot(self) -> Dict[int, Optional[int]]:
        """node_id → returncode (None while running)."""
        with self._lock:
            return {nid: h.returncode() for nid, h in self._procs.items()}

    def node_pid(self, node_id: int) -> Optional[int]:
        """PID of a live node's agent process (None when absent/exited).
        Public contract for fault injection (chaos harnesses SIGKILL the
        process group) — callers must not reach into ``_procs``."""
        with self._lock:
            handle = self._procs.get(node_id)
            if handle is None or handle.proc.poll() is not None:
                return None
            return handle.proc.pid

    def stop(self) -> None:
        with self._lock:
            for node_id in list(self._procs):
                self._kill_node(node_id)
