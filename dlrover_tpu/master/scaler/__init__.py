"""Scalers turn ScalePlans into platform actions (reference master/scaler/)."""
