"""Pod scaler: ScalePlan → k8s pod create/delete.

Reference: ``PodScaler`` (dlrover/python/master/scaler/pod_scaler.py:84)
— the master creates/deletes worker pods directly (the Go operator only
launches the master pod). TPU shape: a pod per host; slice granularity
is enforced upstream by the plan builder (node_unit truncation).
"""

import threading
from typing import Dict, List, Optional, Set

from ...common.log import logger
from ...common.node import Node
from ...scheduler.kubernetes import (
    ELASTIC_JOB_LABEL,
    build_worker_pod,
    k8sClient,
    pod_name,
    pod_terminating,
)
from .base_scaler import ScalePlan, Scaler


class PodScaler(Scaler):
    def __init__(
        self,
        job_name: str,
        image: str,
        command: List[str],
        master_addr: str,
        namespace: str = "default",
        tpu_chips_per_host: int = 0,
        tpu_topology: str = "",
        hosts_per_slice: int = 1,
        env: Optional[Dict[str, str]] = None,
        reconcile_interval: float = 15.0,
        owner_uid: str = "",
    ):
        super().__init__(job_name)
        self._client = k8sClient.singleton(namespace)
        self._image = image
        self._command = command
        self._master_addr = master_addr
        self._namespace = namespace
        self._tpu_chips = tpu_chips_per_host
        self._tpu_topology = tpu_topology
        self._hosts_per_slice = max(1, hosts_per_slice)
        self._env = env or {}
        self._owner_uid = owner_uid
        self._target = 0
        # Ids deleted by a plan and not re-launched since: _reconcile must
        # not resurrect them (a remove-only plan keeps worker_num
        # unchanged, so the bare target count would immediately recreate
        # the pod we just deleted).
        self._removed: Set[int] = set()
        # (node_id, rank) creates that failed (e.g. 409 against a
        # still-Terminating pod) — retried by the periodic reconcile loop.
        self._retry: Dict[int, int] = {}
        # Planned rank per node id (from launch_nodes): the bare target
        # loop must not silently reset a replacement's rank to its id.
        self._ranks: Dict[int, int] = {}
        self._reconcile_interval = reconcile_interval
        self._reconcile_thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    def start(self) -> None:
        """Start the periodic reconcile loop (retry failed creates and
        converge the pod set to the target)."""
        if self._reconcile_thread is not None:
            return
        self._reconcile_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="pod-reconcile"
        )
        self._reconcile_thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def scale(self, plan: ScalePlan) -> None:
        # Bookkeeping under the lock, k8s API calls OUTSIDE it
        # (tpurun-lint blocking-under-lock, the PR 3 wedge class): a
        # hung apiserver call held under _lock would block the
        # reconcile loop — and any other scale() caller — for the whole
        # API timeout.
        with self._lock:
            if plan.worker_num >= 0:
                self._target = plan.worker_num
            for node_id in plan.remove_nodes:
                self._removed.add(node_id)
                self._retry.pop(node_id, None)
            for node in plan.launch_nodes:
                self._removed.discard(node.node_id)
                self._ranks[node.node_id] = node.rank_index
        for node_id in plan.remove_nodes:
            self._client.delete_pod(f"{self._job_name}-worker-{node_id}")
        for node in plan.launch_nodes:
            self._create_worker(node.node_id, node.rank_index)
        self._reconcile()

    def _reconcile(self) -> None:
        """Converge the pod set to the bookkeeping state. Snapshots the
        state under the lock, then talks to the API lock-free — a
        concurrent scale() can interleave, so convergence runs BOTH
        directions: missing pods are created, and a pod resurrected by
        a create that raced a remove-plan delete is torn down on the
        next pass instead of living forever."""
        with self._lock:
            target = self._target
            removed = set(self._removed)
            retry = dict(self._retry)
            ranks = dict(self._ranks)
        pods = self._client.list_pods(f"{ELASTIC_JOB_LABEL}={self._job_name}")
        # A Terminating pod still occupies its name (creates 409) but is
        # going away — treat it as absent so its replacement stays queued.
        existing = {pod_name(p) for p in pods if not pod_terminating(p)}
        for node_id in range(target):
            name = f"{self._job_name}-worker-{node_id}"
            if (
                name not in existing
                and node_id not in removed
                and node_id not in retry
            ):
                self._create_worker(node_id, ranks.get(node_id, node_id))
        for node_id, rank in retry.items():
            if f"{self._job_name}-worker-{node_id}" in existing:
                with self._lock:
                    self._retry.pop(node_id, None)
            else:
                self._create_worker(node_id, rank)
        for node_id in removed:
            name = f"{self._job_name}-worker-{node_id}"
            if name in existing:
                # Re-check under the lock right before the delete: a
                # concurrent scale() may have relaunched this node
                # (discarding it from _removed and creating the pod)
                # since the snapshot — tearing down the fresh pod here
                # would burn a worker boot for nothing.
                with self._lock:
                    if node_id not in self._removed:
                        continue
                self._client.delete_pod(name)

    def _reconcile_loop(self) -> None:
        while not self._stopped.wait(self._reconcile_interval):
            try:
                self._reconcile()
            except Exception:
                logger.exception("pod reconcile failed")

    def _create_worker(self, node_id: int, node_rank: int) -> None:
        pod = build_worker_pod(
            job_name=self._job_name,
            node_id=node_id,
            node_rank=node_rank,
            image=self._image,
            command=self._command,
            master_addr=self._master_addr,
            namespace=self._namespace,
            tpu_chips=self._tpu_chips,
            tpu_topology=self._tpu_topology,
            slice_index=node_rank // self._hosts_per_slice,
            env=self._env,
            owner_uid=self._owner_uid,
        )
        # The API call stays outside the lock; only the retry-queue
        # update takes it.
        if self._client.create_pod(pod):
            logger.info("created worker pod %s", pod_name(pod))
            with self._lock:
                self._retry.pop(node_id, None)
        else:
            # Likely a 409 against a still-Terminating pod — leave it for
            # the periodic reconcile to retry.
            logger.warning(
                "create of %s failed; queued for retry", pod_name(pod)
            )
            with self._lock:
                self._retry[node_id] = node_rank
