"""Pod scaler: ScalePlan → k8s pod create/delete.

Reference: ``PodScaler`` (dlrover/python/master/scaler/pod_scaler.py:84)
— the master creates/deletes worker pods directly (the Go operator only
launches the master pod). TPU shape: a pod per host; slice granularity
is enforced upstream by the plan builder (node_unit truncation).
"""

from typing import Dict, List, Optional

from ...common.log import logger
from ...common.node import Node
from ...scheduler.kubernetes import (
    ELASTIC_JOB_LABEL,
    build_worker_pod,
    k8sClient,
)
from .base_scaler import ScalePlan, Scaler


class PodScaler(Scaler):
    def __init__(
        self,
        job_name: str,
        image: str,
        command: List[str],
        master_addr: str,
        namespace: str = "default",
        tpu_chips_per_host: int = 0,
        tpu_topology: str = "",
        hosts_per_slice: int = 1,
        env: Optional[Dict[str, str]] = None,
    ):
        super().__init__(job_name)
        self._client = k8sClient.singleton(namespace)
        self._image = image
        self._command = command
        self._master_addr = master_addr
        self._namespace = namespace
        self._tpu_chips = tpu_chips_per_host
        self._tpu_topology = tpu_topology
        self._hosts_per_slice = max(1, hosts_per_slice)
        self._env = env or {}
        self._target = 0

    def scale(self, plan: ScalePlan) -> None:
        with self._lock:
            if plan.worker_num >= 0:
                self._target = plan.worker_num
            for node_id in plan.remove_nodes:
                self._client.delete_pod(f"{self._job_name}-worker-{node_id}")
            for node in plan.launch_nodes:
                self._create_worker(node.node_id, node.rank_index)
            self._reconcile()

    def _reconcile(self) -> None:
        pods = self._client.list_pods(f"{ELASTIC_JOB_LABEL}={self._job_name}")
        existing = {p.metadata.name for p in pods}
        for node_id in range(self._target):
            name = f"{self._job_name}-worker-{node_id}"
            if name not in existing:
                self._create_worker(node_id, node_id)

    def _create_worker(self, node_id: int, node_rank: int) -> None:
        pod = build_worker_pod(
            job_name=self._job_name,
            node_id=node_id,
            node_rank=node_rank,
            image=self._image,
            command=self._command,
            master_addr=self._master_addr,
            namespace=self._namespace,
            tpu_chips=self._tpu_chips,
            tpu_topology=self._tpu_topology,
            slice_index=node_rank // self._hosts_per_slice,
            env=self._env,
        )
        if self._client.create_pod(pod):
            logger.info("created worker pod %s", pod.metadata.name)
