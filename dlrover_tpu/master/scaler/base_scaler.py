"""ScalePlan + Scaler ABC.

Reference: ``ScalePlan`` (dlrover/python/master/scaler/base_scaler.py:21)
and the scaler split: the plan is platform-neutral (how many hosts of
which resource, which nodes to remove/relaunch); the scaler executes it
against the platform (pods, processes, TPU slice VMs).
"""

import threading
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List

from ...common.log import logger
from ...common.node import Node, NodeResource


@dataclass
class ScalePlan:
    # target worker count (−1 = unchanged)
    worker_num: int = -1
    # nodes to remove (ids)
    remove_nodes: List[int] = field(default_factory=list)
    # failed nodes to replace: old node → replacement node object
    launch_nodes: List[Node] = field(default_factory=list)
    # resource change for new nodes
    node_resource: NodeResource = field(default_factory=NodeResource)
    created_at: float = field(default_factory=time.time)

    def empty(self) -> bool:
        return (
            self.worker_num < 0
            and not self.remove_nodes
            and not self.launch_nodes
        )


class Scaler(ABC):
    """Executes ScalePlans; one per job (reference base_scaler.py)."""

    def __init__(self, job_name: str = "job"):
        self._job_name = job_name
        self._lock = threading.Lock()

    @abstractmethod
    def scale(self, plan: ScalePlan) -> None:
        ...

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


class NoopScaler(Scaler):
    """Local/standalone: agents self-restart; nothing to scale."""

    def scale(self, plan: ScalePlan) -> None:
        if not plan.empty():
            logger.info(
                "noop scaler ignoring plan: worker_num=%s remove=%s launch=%s",
                plan.worker_num,
                plan.remove_nodes,
                [n.node_id for n in plan.launch_nodes],
            )
