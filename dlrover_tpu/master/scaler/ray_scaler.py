"""ActorScaler: execute ScalePlans as Ray actor create/kill.

Reference: ``dlrover/python/master/scaler/ray_scaler.py:39``
(ActorScaler). Same reconcile discipline as the ProcessScaler — the
plan's ``worker_num`` is the target, ``remove_nodes`` kill by id,
``launch_nodes`` materialize replacements; dead actors are NOT
resurrected here (the watcher reports DELETED and the job manager
decides the relaunch, keeping budget accounting in one place).
"""

from typing import Dict, List, Optional

from ...common.constants import NodeEnv
from ...common.log import logger
from ...scheduler.ray import RayClient, RayElasticJob
from .base_scaler import ScalePlan, Scaler


class ActorScaler(Scaler):
    def __init__(
        self,
        client: RayClient,
        command: List[str],
        env: Optional[Dict[str, str]] = None,
        master_addr: str = "",
        job_name: str = "job",
        num_workers: int = 1,
        num_cpus_per_node: float = 1.0,
        resources_per_node: Optional[Dict[str, float]] = None,
    ):
        super().__init__(job_name)
        self._client = client
        self._job = RayElasticJob(job_name)
        self._command = list(command)
        self._env = dict(env or {})
        self._master_addr = master_addr
        self._target = num_workers
        self._num_cpus = num_cpus_per_node
        self._resources = dict(resources_per_node or {})
        # node_id -> actor name for every node this scaler materialized
        self._actors: Dict[int, str] = {}

    def actor_name(self, node_id: int) -> str:
        return self._job.get_node_name("worker", node_id)

    def scale(self, plan: ScalePlan) -> None:
        with self._lock:
            if plan.worker_num >= 0:
                self._target = plan.worker_num
            for node_id in plan.remove_nodes:
                self._kill_node(node_id)
            for node in plan.launch_nodes:
                self._launch_node(node.node_id, node.rank_index)
            self._reconcile()

    def _reconcile(self) -> None:
        for rank in range(self._target):
            if rank not in self._actors:
                self._launch_node(rank, rank)
        for node_id in [n for n in sorted(self._actors) if n >= self._target]:
            self._kill_node(node_id)

    def _launch_node(self, node_id: int, node_rank: int) -> None:
        name = self.actor_name(node_id)
        if self._client.get_actor(name) is not None:
            # replacement of a live/stale incarnation: clear it first
            self._client.kill_actor(name)
        env = dict(self._env)
        env[NodeEnv.MASTER_ADDR] = self._master_addr
        env[NodeEnv.JOB_NAME] = self._job_name
        env[NodeEnv.NODE_ID] = str(node_id)
        env[NodeEnv.NODE_RANK] = str(node_rank)
        try:
            self._client.create_actor(
                name,
                self._command,
                env,
                num_cpus=self._num_cpus,
                resources=self._resources or None,
            )
            self._actors[node_id] = name
        except Exception:  # noqa: BLE001 — surfaced via watcher absence
            logger.exception("failed to create ray actor %s", name)

    def _kill_node(self, node_id: int) -> None:
        name = self._actors.pop(node_id, None) or self.actor_name(node_id)
        self._client.kill_actor(name)

    def snapshot(self) -> Dict[int, Optional[int]]:
        """{node_id: None while alive, exit code after} — the watcher's
        poll source (absent actors report rc -1)."""
        with self._lock:
            items = dict(self._actors)
        out: Dict[int, Optional[int]] = {}
        for node_id, name in items.items():
            state, rc = self._client.actor_poll(name)
            if state == "alive":
                out[node_id] = None
            elif state == "exited":
                out[node_id] = int(rc)
            else:  # absent: the actor died or was externally removed
                out[node_id] = -1
        return out

    def stop(self) -> None:
        with self._lock:
            for node_id in list(self._actors):
                self._kill_node(node_id)
