"""Master RPC servicer: typed-message dispatch for ``get``/``report``.

Reference: ``dlrover/python/master/servicer.py`` (MasterServicer:84, get:147,
report:412). Every agent RPC lands here; the servicer routes by message type
to the owning component (kv store, rendezvous managers, task manager, job
manager, diagnosis queues).
"""

import time
from typing import Dict

from ..chaos import faults
from ..common import comm
from ..common.constants import JobStage, RendezvousName
from ..common.log import logger
from ..common.serialize import dumps, loads
from ..observability import trace
from .diagnosis.action import action_to_msg
from .job_context import get_job_context
from .kv_store import KVStoreService
from .node.job_manager import JobManager
from .rdzv.manager import RendezvousManager
from .shard.task_manager import TaskManager
from .sync_service import SyncService


class MasterServicer:
    def __init__(
        self,
        job_manager: JobManager,
        rdzv_managers: Dict[str, RendezvousManager],
        task_manager: TaskManager,
        kv_store: KVStoreService = None,
        sync_service: SyncService = None,
        perf_monitor=None,
        epoch: int = 0,
    ):
        self._job_manager = job_manager
        self._rdzv_managers = rdzv_managers
        self._task_manager = task_manager
        self._kv_store = kv_store or KVStoreService()
        self._sync_service = sync_service or SyncService()
        self._perf_monitor = perf_monitor
        self._job_ctx = get_job_context()
        self._start_time = time.time()
        # Master boot epoch, stamped on EVERY response (0 = journal-less
        # master, no fencing). Clients detect a restarted master by the
        # bump and re-attach; stale in-flight responses are fenced.
        self._epoch = epoch

    def _respond(self, **kwargs) -> bytes:
        # server_ts feeds the clients' clock-offset estimators; trace_id
        # echoes the adopted request context (empty outside a trace).
        trace_id, _ = trace.current_ids()
        return dumps(
            comm.BaseResponse(
                master_epoch=self._epoch,
                trace_id=trace_id,
                server_ts=time.time(),
                **kwargs,
            )
        )

    # -- transport entry points (bytes in/out) -----------------------------

    def get(self, request_bytes: bytes) -> bytes:
        # Chaos hook: error propagates to the transport (the client sees
        # a failed RPC and retries); "drop" answers with a rejection.
        if faults.inject("master.servicer.get") == "drop":
            return self._respond(success=False, reason="fault-injected drop")
        req = loads(request_bytes)
        message = loads(req.data) if isinstance(req, comm.BaseRequest) else req
        # Scoped adoption: master events emitted while handling this
        # request join the caller's incident trace.
        token = trace.adopt_request(req)
        try:
            handler = self._GET_HANDLERS.get(type(message))
            if handler is None:
                logger.warning("no get handler for %s", type(message).__name__)
                return self._respond(success=False, reason="unknown message")
            try:
                result = handler(self, message)
            except Exception as e:  # noqa: BLE001 — reported, not retried
                logger.exception(
                    "get handler failed for %s", type(message).__name__
                )
                return self._respond(success=False, reason=repr(e))
            return self._respond(success=True, data=dumps(result))
        finally:
            trace.release(token)

    def report(self, request_bytes: bytes) -> bytes:
        if faults.inject("master.servicer.report") == "drop":
            return self._respond(success=False, reason="fault-injected drop")
        req = loads(request_bytes)
        message = loads(req.data) if isinstance(req, comm.BaseRequest) else req
        token = trace.adopt_request(req)
        try:
            handler = self._REPORT_HANDLERS.get(type(message))
            if handler is None:
                logger.warning(
                    "no report handler for %s", type(message).__name__
                )
                return self._respond(success=False, reason="unknown message")
            try:
                handler(self, message)
                return self._respond(success=True)
            except Exception as e:  # noqa: BLE001
                logger.exception("report handler failed")
                return self._respond(success=False, reason=repr(e))
        finally:
            trace.release(token)

    # -- kv store ----------------------------------------------------------

    def _kv_get(self, msg: comm.KeyValueQuery) -> comm.KeyValuePair:
        return comm.KeyValuePair(key=msg.key, value=self._kv_store.get(msg.key))

    def _kv_add(self, msg: comm.KeyValueAdd) -> comm.KeyValuePair:
        value = self._kv_store.add(msg.key, msg.amount)
        return comm.KeyValuePair(key=msg.key, value=str(value).encode())

    def _kv_multi_get(self, msg: comm.KeyValueMultiGet) -> comm.KeyValueMultiPair:
        return comm.KeyValueMultiPair(kvs=self._kv_store.multi_get(msg.keys))

    def _kv_set(self, msg: comm.KeyValuePair) -> None:
        self._kv_store.set(msg.key, msg.value)

    def _kv_multi_set(self, msg: comm.KeyValueMultiPair) -> None:
        self._kv_store.multi_set(msg.kvs)

    # -- rendezvous --------------------------------------------------------

    def _join_rdzv(self, msg: comm.JoinRendezvousRequest) -> comm.JoinRendezvousResponse:
        manager = self._rdzv_managers[msg.rdzv_name or RendezvousName.TRAINING]
        meta = comm.NodeMeta(
            node_id=msg.node_id,
            node_rank=msg.node_rank if msg.node_rank >= 0 else msg.node_id,
            process_unit=msg.local_world_size,
            addr=msg.node_ip,
            slice_id=msg.slice_id,
        )
        round_ = manager.join_rendezvous(meta)
        return comm.JoinRendezvousResponse(round=round_)

    def _get_comm_world(self, msg: comm.CommWorldRequest) -> comm.CommWorldResponse:
        manager = self._rdzv_managers[msg.rdzv_name or RendezvousName.TRAINING]
        rank = msg.node_rank if msg.node_rank >= 0 else msg.node_id
        round_, group, world = manager.get_comm_world(rank)
        return comm.CommWorldResponse(
            rdzv_name=manager.name, round=round_, group=group, world=world
        )

    def _num_waiting(self, msg: comm.WaitingNodeNumRequest) -> comm.WaitingNodeNumResponse:
        manager = self._rdzv_managers[msg.rdzv_name or RendezvousName.TRAINING]
        return comm.WaitingNodeNumResponse(waiting_num=manager.num_nodes_waiting())

    def _network_ready(self, msg: comm.NetworkReadyRequest) -> comm.NetworkReadyResponse:
        manager = self._rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if manager is None:
            return comm.NetworkReadyResponse(ready=True)
        ready, reason = manager.network_ready(wave=msg.round)
        return comm.NetworkReadyResponse(ready=ready, reason=reason)

    def _report_network_check(self, msg: comm.NetworkCheckResult) -> None:
        manager = self._rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if manager is not None:
            rank = msg.node_rank if msg.node_rank >= 0 else msg.node_id
            manager.report_network_check_result(
                rank, msg.normal, msg.elapsed_time, round_idx=msg.round
            )

    def _fault_nodes(self, msg: comm.FaultNodesRequest) -> comm.FaultNodesResponse:
        manager = self._rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if manager is None:
            return comm.FaultNodesResponse()
        nodes, reason = manager.check_fault_node()
        return comm.FaultNodesResponse(fault_nodes=nodes, reason=reason)

    def _stragglers(self, msg: comm.StragglersRequest) -> comm.StragglersResponse:
        manager = self._rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if manager is None:
            return comm.StragglersResponse()
        return comm.StragglersResponse(stragglers=manager.detect_stragglers())

    # -- node lifecycle ----------------------------------------------------

    def _node_state(self, msg: comm.NodeStateRequest) -> None:
        self._job_manager.update_node_status(
            msg.node_id, msg.node_type or "worker", msg.status, msg.exit_reason
        )
        if msg.status in ("failed", "succeeded", "deleted"):
            # Rendezvous structures are keyed by node_rank; a relaunched
            # host keeps its rank even when the platform gives it a new id.
            node = self._job_ctx.get_node(msg.node_type or "worker", msg.node_id)
            rank = node.rank_index if node is not None and node.rank_index >= 0 else msg.node_id
            for manager in self._rdzv_managers.values():
                manager.remove_alive_node(rank)
            self._task_manager.recover_tasks(msg.node_id)

    def _node_failure(self, msg: comm.NodeFailureReport) -> None:
        self._job_manager.handle_failure_report(
            msg.node_id, msg.error_data, msg.restart_count
        )

    def _heartbeat(self, msg: comm.HeartbeatRequest) -> comm.HeartbeatResponse:
        self._job_manager.record_heartbeat(msg.node_id, msg.timestamp)
        actions = self._job_ctx.node_actions.drain_actions(msg.node_id)
        return comm.HeartbeatResponse(actions=[action_to_msg(a) for a in actions])

    def _node_metrics(self, msg: comm.NodeMetricsReport) -> None:
        from .monitor.metric_context import get_metric_context

        get_metric_context().report(msg.node_id, msg.gauges)

    def _resource_usage(self, msg: comm.ResourceUsageReport) -> None:
        node = self._job_ctx.get_node(msg.node_type or "worker", msg.node_id)
        if node is not None:
            # Two reporters share this node: the agent's ResourceMonitor
            # (host cpu/mem) and the trainer's DeviceMonitor (device
            # gauges, host fields None). None = "not reported", so a
            # device-only report can't clobber host gauges and a genuine
            # 0.0 host gauge still lands.
            if msg.cpu_percent is not None:
                node.used_resource.cpu = msg.cpu_percent
            if msg.memory_mb is not None:
                node.used_resource.memory_mb = msg.memory_mb
            if msg.device_util:
                node.used_resource.device_util = dict(msg.device_util)
            if msg.device_mem_mb:
                node.used_resource.device_mem_mb = dict(msg.device_mem_mb)
            if msg.device_mem_limit_mb:
                node.used_resource.device_mem_limit_mb = dict(
                    msg.device_mem_limit_mb
                )
            if msg.device_util or msg.device_mem_mb:
                import time as _time

                node.used_resource.device_reported_at = _time.time()
            self._job_ctx.update_node(node)

    def _training_step(self, msg: comm.TrainingStepReport) -> None:
        self._job_ctx.report_step(msg.step, msg.timestamp)
        if self._perf_monitor is not None:
            self._perf_monitor.collect_global_step(msg.step, msg.timestamp)

    # -- data shards -------------------------------------------------------

    def _dataset_params(self, msg: comm.DatasetShardParams) -> None:
        self._task_manager.new_dataset(msg)

    def _get_task(self, msg: comm.TaskRequest) -> comm.TaskMsg:
        task = self._task_manager.get_task(msg.node_id, msg.dataset_name)
        shard = comm.ShardMsg(
            name=task.shard.name,
            start=task.shard.start,
            end=task.shard.end,
            indices=task.shard.record_indices,
        )
        return comm.TaskMsg(task_id=task.task_id, task_type=task.task_type, shard=shard)

    def _task_result(self, msg: comm.TaskResult) -> None:
        self._task_manager.report_task_result(msg.dataset_name, msg.task_id, msg.success)

    def _task_inflight(self, msg: comm.TaskInFlightReport) -> None:
        self._task_manager.confirm_tasks(
            msg.node_id, msg.dataset_name, list(msg.task_ids)
        )

    def _shard_ckpt_get(self, msg: comm.ShardCheckpointRequest) -> comm.ShardCheckpointMsg:
        return comm.ShardCheckpointMsg(
            dataset_name=msg.dataset_name,
            content=self._task_manager.checkpoint(msg.dataset_name),
        )

    def _shard_ckpt_restore(self, msg: comm.ShardCheckpointMsg) -> None:
        self._task_manager.restore_checkpoint(msg.dataset_name, msg.content)

    # -- checkpoint sync ---------------------------------------------------

    def _ckpt_sync(self, msg: comm.CheckpointStepSync) -> comm.CheckpointStepSyncResponse:
        manager = self._rdzv_managers.get(RendezvousName.TRAINING)
        success = manager.sync_ckpt_nodes(msg.node_id, msg.step) if manager else True
        return comm.CheckpointStepSyncResponse(success=success)

    # -- pre-check / status / config ---------------------------------------

    def _pre_check(self, msg: comm.PreCheckRequest) -> comm.PreCheckResponse:
        return comm.PreCheckResponse(
            status=self._job_ctx.pre_check_status,
            reason=self._job_ctx.pre_check_reason,
        )

    def _cluster_metrics(
        self, msg: comm.ClusterMetricsRequest
    ) -> comm.ClusterMetricsResponse:
        from .monitor.metric_context import get_metric_context

        return comm.ClusterMetricsResponse(
            node_gauges=get_metric_context().all_gauges()
        )

    def _cluster_dump(
        self, msg: comm.ClusterDumpRequest
    ) -> comm.ClusterDumpResponse:
        """Cluster-wide stack dumps (reference hosting service dump
        coordination): one STACK_DUMP action per running worker; the
        agents signal their trainers and report the tracebacks back."""
        from ..common.constants import NodeStatus, NodeType
        from .diagnosis.action import DiagnosisActionType, NodeAction

        dumped = []
        for node in self._job_ctx.get_nodes(NodeType.WORKER).values():
            if node.status != NodeStatus.RUNNING:
                continue
            self._job_ctx.node_actions.add_action(
                NodeAction(
                    node_id=node.node_id,
                    action_type=DiagnosisActionType.STACK_DUMP,
                    reason="cluster_dump",
                )
            )
            dumped.append(node.node_id)
        return comm.ClusterDumpResponse(node_ids=sorted(dumped))

    def _job_status(self, msg: comm.JobStatusRequest) -> comm.JobStatusResponse:
        goodput = training_goodput = sps = 0.0
        last_step = 0
        if self._perf_monitor is not None:
            goodput = self._perf_monitor.goodput()
            training_goodput = self._perf_monitor.training_goodput()
            sps = self._perf_monitor.steps_per_second()
            last_step, _ = self._perf_monitor.last_step()
        return comm.JobStatusResponse(
            stage=self._job_ctx.job_stage,
            exit_reason=self._job_ctx.job_exit_reason,
            goodput=goodput,
            training_goodput=training_goodput,
            steps_per_second=sps,
            last_step=last_step,
        )

    def _paral_config(self, msg: comm.ParallelConfigRequest) -> comm.ParallelConfig:
        return self._job_ctx.paral_config or comm.ParallelConfig()

    def _run_config(self, msg: comm.ElasticRunConfigRequest) -> comm.ElasticRunConfigResponse:
        return comm.ElasticRunConfigResponse(
            configs=dict(self._job_ctx.elastic_run_config)
        )

    def _event_report(self, msg: comm.EventReport) -> None:
        logger.info(
            "[event] type=%s instance=%s action=%s msg=%s",
            msg.event_type,
            msg.instance,
            msg.action,
            msg.msg,
        )

    # -- sync barriers -----------------------------------------------------

    def _sync_join(self, msg: comm.SyncJoin) -> comm.SyncQueryResponse:
        return comm.SyncQueryResponse(
            success=self._sync_service.join(msg.sync_name, msg.node_id)
        )

    def _sync_query(self, msg: comm.SyncQuery) -> comm.SyncQueryResponse:
        return comm.SyncQueryResponse(
            success=self._sync_service.is_finished(msg.sync_name)
        )

    def _sync_finish(self, msg: comm.SyncFinish) -> comm.SyncQueryResponse:
        self._sync_service.finish(msg.sync_name)
        return comm.SyncQueryResponse(success=True)

    _GET_HANDLERS = {
        comm.KeyValueQuery: _kv_get,
        comm.KeyValueAdd: _kv_add,
        comm.KeyValueMultiGet: _kv_multi_get,
        comm.JoinRendezvousRequest: _join_rdzv,
        comm.CommWorldRequest: _get_comm_world,
        comm.WaitingNodeNumRequest: _num_waiting,
        comm.NetworkReadyRequest: _network_ready,
        comm.FaultNodesRequest: _fault_nodes,
        comm.StragglersRequest: _stragglers,
        comm.HeartbeatRequest: _heartbeat,
        comm.TaskRequest: _get_task,
        comm.ShardCheckpointRequest: _shard_ckpt_get,
        comm.CheckpointStepSync: _ckpt_sync,
        comm.PreCheckRequest: _pre_check,
        comm.JobStatusRequest: _job_status,
        comm.ClusterMetricsRequest: _cluster_metrics,
        comm.ClusterDumpRequest: _cluster_dump,
        comm.ParallelConfigRequest: _paral_config,
        comm.ElasticRunConfigRequest: _run_config,
        comm.SyncJoin: _sync_join,
        comm.SyncQuery: _sync_query,
        comm.SyncFinish: _sync_finish,
    }

    _REPORT_HANDLERS = {
        comm.KeyValuePair: _kv_set,
        comm.KeyValueMultiPair: _kv_multi_set,
        comm.NetworkCheckResult: _report_network_check,
        comm.NodeStateRequest: _node_state,
        comm.NodeFailureReport: _node_failure,
        comm.NodeMetricsReport: _node_metrics,
        comm.ResourceUsageReport: _resource_usage,
        comm.TrainingStepReport: _training_step,
        comm.DatasetShardParams: _dataset_params,
        comm.TaskResult: _task_result,
        comm.TaskInFlightReport: _task_inflight,
        comm.ShardCheckpointMsg: _shard_ckpt_restore,
        comm.EventReport: _event_report,
    }
