"""Master-side KV store service.

Reference: ``master/elastic_training/kv_store_service.py:18``. Backs the
agents' :class:`~dlrover_tpu.agent.master_kv_store.MasterKVStore` (barriers,
rendezvous state) and the ``jax.distributed`` bootstrap hand-off.
"""

import threading
from typing import Dict, List


class KVStoreService:
    def __init__(self):
        self._store: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._store[key] = value

    def get(self, key: str) -> bytes:
        with self._lock:
            return self._store.get(key, b"")

    def add(self, key: str, amount: int) -> int:
        """Atomic counter add; value stored as decimal string bytes."""
        with self._lock:
            current = int(self._store.get(key, b"0") or b"0")
            current += amount
            self._store[key] = str(current).encode()
            return current

    def multi_get(self, keys: List[str]) -> Dict[str, bytes]:
        with self._lock:
            return {k: self._store[k] for k in keys if k in self._store}

    def multi_set(self, kvs: Dict[str, bytes]) -> None:
        with self._lock:
            self._store.update(kvs)

    def delete(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._store.clear()
