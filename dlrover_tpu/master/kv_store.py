"""Master-side KV store service.

Reference: ``master/elastic_training/kv_store_service.py:18``. Backs the
agents' :class:`~dlrover_tpu.agent.master_kv_store.MasterKVStore` (barriers,
rendezvous state) and the ``jax.distributed`` bootstrap hand-off.

Crash tolerance: when the master journal is attached (``journal`` set by
:mod:`dlrover_tpu.master.persistence`), every mutation appends one WAL
record and the full store rides the snapshot — the coordinator-address
keys and barrier counters survive a master restart, so re-attaching
agents read the same world they were trained against. The ``import_*``
entry points apply replayed mutations without re-journaling them.

The durable checkpoint tier's commit barrier
(``checkpoint/durable/commit.MasterKVBarrier``) rides the journaled
``add`` counters — key ``ckpt/durable/<lineage>/<step>/done`` — so a
master restart mid-commit replays the shard-done count instead of
wedging rank 0's phase-2 wait.
"""

import base64
import threading
from typing import Dict, List


class KVStoreService:
    def __init__(self):
        self._store: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        self.journal = None  # set by MasterPersistence.attach

    def _record(self, kind: str, payload: Dict) -> None:
        if self.journal is not None:
            self.journal(kind, payload)

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._store[key] = value
            self._record(
                "kv.set",
                {"key": key, "v": base64.b64encode(value or b"").decode()},
            )

    def get(self, key: str) -> bytes:
        with self._lock:
            return self._store.get(key, b"")

    def add(self, key: str, amount: int) -> int:
        """Atomic counter add; value stored as decimal string bytes."""
        with self._lock:
            existed = key in self._store
            current = int(self._store.get(key, b"0") or b"0")
            current += amount
            value = str(current).encode()
            self._store[key] = value
            # Journaled as the RESULT, not the delta (replaying a delta
            # on a snapshot that already contains it would double-count)
            # — and only when something changed: add(key, 0) is the
            # agents' barrier POLL idiom, and journaling each poll would
            # flood the WAL into back-to-back snapshot compactions.
            if amount or not existed:
                self._record(
                    "kv.set",
                    {"key": key, "v": base64.b64encode(value).decode()},
                )
            return current

    def multi_get(self, keys: List[str]) -> Dict[str, bytes]:
        with self._lock:
            return {k: self._store[k] for k in keys if k in self._store}

    def multi_set(self, kvs: Dict[str, bytes]) -> None:
        with self._lock:
            self._store.update(kvs)
            self._record(
                "kv.multi",
                {
                    "kvs": {
                        k: base64.b64encode(v or b"").decode()
                        for k, v in kvs.items()
                    }
                },
            )

    def delete(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)
            self._record("kv.del", {"key": key})

    def clear(self) -> None:
        with self._lock:
            self._store.clear()
            self._record("kv.clear", {})

    # -- persistence (snapshot / replay) -----------------------------------

    def export_state(self) -> Dict[str, str]:
        with self._lock:
            return {
                k: base64.b64encode(v or b"").decode()
                for k, v in self._store.items()
            }

    def import_state(self, state: Dict[str, str]) -> None:
        with self._lock:
            self._store = {
                k: base64.b64decode(v or "") for k, v in state.items()
            }

    def import_pairs(self, kvs: Dict[str, bytes]) -> None:
        """Replay entry: apply without journaling."""
        with self._lock:
            self._store.update(kvs)

    def import_delete(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)

    def import_clear(self) -> None:
        with self._lock:
            self._store.clear()
