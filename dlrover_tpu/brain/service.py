"""Brain service: the RPC surface over datastore + algorithms.

Reference: ``dlrover/go/brain/pkg/server/`` (gRPC optimize/persist
service).  Rides the same 2-verb msgpack transport as the master
(:mod:`dlrover_tpu.rpc.server`), so one server stack serves both roles:
``report`` persists job/metric/event writes, ``get`` answers optimize
and history queries.
"""

from typing import Optional, Tuple

from ..common import comm
from ..common.log import logger
from ..common.serialize import dumps, loads
from ..rpc.server import ServicerApi, create_master_server
from . import messages as bm
from .algorithms import (
    JobCreateResourceAlgorithm,
    JobRunningResourceAlgorithm,
    OomRecoveryAlgorithm,
    OptimizePlan,
)
from .datastore import (
    BrainDataStore,
    JobMetricSample,
    JobProfile,
    JobRecord,
)


class BrainServicer(ServicerApi):
    def __init__(
        self,
        store: BrainDataStore,
        memory_limit_mb: float = 0.0,
        min_gain: float = 0.4,
    ):
        self._store = store
        self._create_algo = JobCreateResourceAlgorithm(store, min_gain)
        self._running_algo = JobRunningResourceAlgorithm(store, min_gain)
        self._oom_algo = OomRecoveryAlgorithm(store, memory_limit_mb)
        from .algorithms import (
            CompletionTimePredictor,
            JobInitAdjustAlgorithm,
        )

        self._init_adjust_algo = JobInitAdjustAlgorithm(store, min_gain)
        self._deadline_algo = CompletionTimePredictor(store, min_gain)
        # Master-epoch stamp (rpc/client.py fence): the brain service is
        # journal-less, so every response stamps 0 — "unfenced" as an
        # explicit decision rather than an accidental default; when the
        # brain gains a journal, only this attribute moves.
        self._epoch = 0

    def _respond(self, **kwargs) -> bytes:
        return dumps(comm.BaseResponse(master_epoch=self._epoch, **kwargs))

    # -- transport entry points -------------------------------------------

    def report(self, request_bytes: bytes) -> bytes:
        req = loads(request_bytes)
        msg = loads(req.data) if isinstance(req, comm.BaseRequest) else req
        try:
            if isinstance(msg, bm.BrainJobReport):
                self._store.upsert_job(
                    JobRecord(
                        job_uuid=msg.job_uuid,
                        job_name=msg.job_name,
                        model_signature=msg.model_signature,
                        workload=msg.workload,
                        worker_num=msg.worker_num,
                        node_unit=msg.node_unit,
                        status=msg.status,
                    )
                )
            elif isinstance(msg, bm.BrainMetricReport):
                self._store.add_metric(
                    JobMetricSample(
                        job_uuid=msg.job_uuid,
                        world_size=msg.world_size,
                        steps_per_second=msg.steps_per_second,
                        tokens_per_second=msg.tokens_per_second,
                        peak_memory_mb=msg.peak_memory_mb,
                        cpu_percent=msg.cpu_percent,
                    )
                )
            elif isinstance(msg, bm.BrainProfileReport):
                self._store.upsert_profile(
                    JobProfile(
                        job_uuid=msg.job_uuid,
                        param_count=msg.param_count,
                        flops_per_step=msg.flops_per_step,
                        tokens_per_batch=msg.tokens_per_batch,
                        seq_len=msg.seq_len,
                        arch=msg.arch,
                    )
                )
            elif isinstance(msg, bm.BrainEventReport):
                self._store.add_event(
                    msg.job_uuid, msg.event_type, msg.node_id, msg.detail
                )
            else:
                return self._respond(success=False, reason="unknown message")
            return self._respond(success=True)
        except Exception as e:  # noqa: BLE001
            logger.exception("brain report failed")
            return self._respond(success=False, reason=repr(e))

    def get(self, request_bytes: bytes) -> bytes:
        req = loads(request_bytes)
        msg = loads(req.data) if isinstance(req, comm.BaseRequest) else req
        try:
            if isinstance(msg, bm.BrainOptimizeRequest):
                result = self._optimize(msg)
            elif isinstance(msg, bm.BrainJobQuery):
                result = self._job_info(msg)
            elif isinstance(msg, bm.BrainFleetQuery):
                summary = self._store.fleet_summary()
                result = bm.BrainFleetReport(
                    cohorts=summary["cohorts"],
                    total_jobs=summary["total_jobs"],
                )
            elif isinstance(msg, bm.BrainAllocateRequest):
                from .algorithms import ClusterResourceArbiter

                result = bm.BrainAllocateResponse(
                    allocation=ClusterResourceArbiter(self._store).allocate(
                        msg.job_uuids,
                        msg.total_hosts,
                        node_unit=msg.node_unit,
                    )
                )
            else:
                return self._respond(success=False, reason="unknown message")
            return self._respond(success=True, data=dumps(result))
        except Exception as e:  # noqa: BLE001
            logger.exception("brain get failed")
            return self._respond(success=False, reason=repr(e))

    # -- handlers ----------------------------------------------------------

    def _optimize(self, msg: bm.BrainOptimizeRequest) -> bm.BrainOptimizeResponse:
        if msg.stage == "create":
            # A profile dict in extra enables fleet-scale (shape
            # similarity) warm start when the signature has no history.
            prof = msg.extra.get("profile")
            profile = (
                JobProfile(
                    job_uuid=msg.job_uuid,
                    param_count=float(prof.get("param_count", 0.0)),
                    flops_per_step=float(prof.get("flops_per_step", 0.0)),
                    tokens_per_batch=float(prof.get("tokens_per_batch", 0.0)),
                    seq_len=int(prof.get("seq_len", 0)),
                    arch=str(prof.get("arch", "")),
                )
                if isinstance(prof, dict)
                else None
            )
            plan = self._create_algo.optimize(
                msg.model_signature,
                workload=msg.workload,
                node_unit=msg.node_unit,
                max_workers=msg.max_workers,
                profile=profile,
            )
        elif msg.stage == "running":
            plan = self._running_algo.optimize(
                msg.job_uuid,
                current_workers=msg.current_workers,
                node_unit=msg.node_unit,
                max_workers=msg.max_workers,
            )
        elif msg.stage == "init_adjust":
            plan = self._init_adjust_algo.optimize(
                msg.job_uuid,
                node_unit=msg.node_unit,
                max_workers=msg.max_workers,
            )
        elif msg.stage == "deadline":
            plan = self._deadline_algo.optimize(
                msg.job_uuid,
                remaining_steps=int(msg.extra.get("remaining_steps", 0)),
                deadline_s=float(msg.extra.get("deadline_s", 0.0)),
                node_unit=msg.node_unit,
                max_workers=msg.max_workers,
            )
        elif msg.stage == "oom":
            plan = self._oom_algo.optimize(msg.job_uuid)
        else:
            plan = OptimizePlan(reason=f"unknown stage {msg.stage!r}")
        return bm.BrainOptimizeResponse(
            worker_num=plan.worker_num,
            memory_mb_per_host=plan.memory_mb_per_host,
            predicted_speed=plan.predicted_speed,
            reason=plan.reason,
            extra=plan.extra,
        )

    def _job_info(self, msg: bm.BrainJobQuery) -> bm.BrainJobInfo:
        job = self._store.get_job(msg.job_uuid)
        if job is None:
            return bm.BrainJobInfo(job_uuid=msg.job_uuid)
        return bm.BrainJobInfo(
            job_uuid=job.job_uuid,
            job_name=job.job_name,
            model_signature=job.model_signature,
            workload=job.workload,
            worker_num=job.worker_num,
            status=job.status,
            metric_count=len(self._store.job_metrics(job.job_uuid)),
        )


class BrainService:
    """The deployable unit: datastore + servicer + server."""

    def __init__(
        self,
        db_path: str = ":memory:",
        port: int = 0,
        service_type: str = "",
        memory_limit_mb: float = 0.0,
    ):
        from ..common.config import get_context
        from ..common.constants import CommsType

        self.store = BrainDataStore(db_path)
        self.servicer = BrainServicer(self.store, memory_limit_mb)
        service_type = service_type or get_context().master_comms()
        self._server, self.port = create_master_server(
            self.servicer, service_type, port
        )

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> None:
        self._server.start()
        logger.info("brain service on :%s", self.port)

    def stop(self) -> None:
        self._server.stop()
        self.store.close()


def main(argv: Optional[Tuple[str, ...]] = None) -> None:
    """``python -m dlrover_tpu.brain.service --port 8500 --db brain.db``"""
    import argparse
    import threading

    parser = argparse.ArgumentParser("dlrover-tpu brain")
    parser.add_argument("--port", type=int, default=8500)
    parser.add_argument("--db", default="brain.db")
    parser.add_argument("--memory_limit_mb", type=float, default=0.0)
    args = parser.parse_args(argv)
    service = BrainService(
        db_path=args.db, port=args.port, memory_limit_mb=args.memory_limit_mb
    )
    service.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        service.stop()


if __name__ == "__main__":
    main()
