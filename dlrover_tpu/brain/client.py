"""Brain client: the master-side consumer of the Brain service.

Reference: ``dlrover/python/brain/client.py`` (``GlobalBrainClient``) —
a thin typed wrapper; every call degrades to None on transport failure
so the master never depends on Brain availability.
"""

from typing import Optional

from ..common.log import logger
from ..rpc.client import MasterClient
from . import messages as bm


class BrainClient:
    def __init__(self, brain_addr: str, service_type: str = "", retries: int = 2):
        self._client = MasterClient(
            brain_addr,
            node_id=-1,
            node_type="master",
            service_type=service_type,
            retries=retries,
        )

    # -- writes ------------------------------------------------------------

    def report_job(
        self,
        job_uuid: str,
        job_name: str = "",
        model_signature: str = "",
        workload: str = "jax",
        worker_num: int = 0,
        node_unit: int = 1,
        status: str = "running",
    ) -> bool:
        try:
            self._client.report(
                bm.BrainJobReport(
                    job_uuid=job_uuid,
                    job_name=job_name,
                    model_signature=model_signature,
                    workload=workload,
                    worker_num=worker_num,
                    node_unit=node_unit,
                    status=status,
                )
            )
            return True
        except Exception as e:  # noqa: BLE001
            logger.debug("brain report_job failed: %r", e)
            return False

    def report_metrics(
        self,
        job_uuid: str,
        world_size: int = 0,
        steps_per_second: float = 0.0,
        tokens_per_second: float = 0.0,
        peak_memory_mb: float = 0.0,
        cpu_percent: float = 0.0,
    ) -> bool:
        try:
            self._client.report(
                bm.BrainMetricReport(
                    job_uuid=job_uuid,
                    world_size=world_size,
                    steps_per_second=steps_per_second,
                    tokens_per_second=tokens_per_second,
                    peak_memory_mb=peak_memory_mb,
                    cpu_percent=cpu_percent,
                )
            )
            return True
        except Exception as e:  # noqa: BLE001
            logger.debug("brain report_metrics failed: %r", e)
            return False

    def report_profile(
        self,
        job_uuid: str,
        param_count: float = 0.0,
        flops_per_step: float = 0.0,
        tokens_per_batch: float = 0.0,
        seq_len: int = 0,
        arch: str = "",
    ) -> bool:
        """Persist the job's workload shape so future jobs with no
        exact-signature history can warm-start from it."""
        try:
            self._client.report(
                bm.BrainProfileReport(
                    job_uuid=job_uuid,
                    param_count=param_count,
                    flops_per_step=flops_per_step,
                    tokens_per_batch=tokens_per_batch,
                    seq_len=seq_len,
                    arch=arch,
                )
            )
            return True
        except Exception as e:  # noqa: BLE001
            logger.debug("brain report_profile failed: %r", e)
            return False

    def report_event(
        self, job_uuid: str, event_type: str, node_id: int = -1, detail: str = ""
    ) -> bool:
        try:
            self._client.report(
                bm.BrainEventReport(
                    job_uuid=job_uuid,
                    event_type=event_type,
                    node_id=node_id,
                    detail=detail,
                )
            )
            return True
        except Exception as e:  # noqa: BLE001
            logger.debug("brain report_event failed: %r", e)
            return False

    # -- reads -------------------------------------------------------------

    def get_optimization_plan(
        self,
        stage: str,
        job_uuid: str = "",
        model_signature: str = "",
        workload: str = "",
        current_workers: int = 0,
        node_unit: int = 1,
        max_workers: int = 0,
        extra: Optional[dict] = None,
    ) -> Optional[bm.BrainOptimizeResponse]:
        try:
            resp = self._client.get(
                bm.BrainOptimizeRequest(
                    stage=stage,
                    job_uuid=job_uuid,
                    model_signature=model_signature,
                    workload=workload,
                    current_workers=current_workers,
                    node_unit=node_unit,
                    max_workers=max_workers,
                    extra=dict(extra or {}),
                )
            )
            if isinstance(resp, bm.BrainOptimizeResponse):
                return resp
            return None
        except Exception as e:  # noqa: BLE001
            logger.debug("brain optimize(%s) unreachable: %r", stage, e)
            return None

    def get_cluster_allocation(
        self, job_uuids, total_hosts: int, node_unit: int = 1
    ) -> Optional[dict]:
        """{job_uuid: hosts} from the Brain's cross-job arbiter."""
        try:
            resp = self._client.get(
                bm.BrainAllocateRequest(
                    job_uuids=list(job_uuids),
                    total_hosts=total_hosts,
                    node_unit=node_unit,
                )
            )
            if isinstance(resp, bm.BrainAllocateResponse):
                return dict(resp.allocation)
            return None
        except Exception as e:  # noqa: BLE001
            logger.debug("brain allocate unreachable: %r", e)
            return None

    def get_fleet_report(self) -> Optional[bm.BrainFleetReport]:
        """Per-signature fleet aggregates (ops view of the datastore)."""
        try:
            resp = self._client.get(bm.BrainFleetQuery())
            if isinstance(resp, bm.BrainFleetReport):
                return resp
            return None
        except Exception as e:  # noqa: BLE001
            logger.debug("brain fleet query unreachable: %r", e)
            return None

    def get_job_info(self, job_uuid: str) -> Optional[bm.BrainJobInfo]:
        try:
            resp = self._client.get(bm.BrainJobQuery(job_uuid=job_uuid))
            if isinstance(resp, bm.BrainJobInfo) and resp.job_name:
                return resp
            return None
        except Exception as e:  # noqa: BLE001
            logger.debug("brain job query unreachable: %r", e)
            return None

    def close(self) -> None:
        self._client.close()
