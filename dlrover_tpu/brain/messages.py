"""Brain wire messages (msgpack dataclasses over the 2-verb transport).

Reference: ``dlrover/proto/brain.proto`` — the Brain has its own message
surface separate from the master⇄agent one.  Same serialization registry
as :mod:`dlrover_tpu.common.comm`.
"""

from dataclasses import dataclass, field
from typing import Dict

from ..common.serialize import register_message


@register_message
@dataclass
class BrainJobReport:
    """Create/update one job's identity + outcome."""

    job_uuid: str = ""
    job_name: str = ""
    model_signature: str = ""
    workload: str = "jax"
    worker_num: int = 0
    node_unit: int = 1
    status: str = "running"


@register_message
@dataclass
class BrainMetricReport:
    """One runtime metrics sample from a running job's master."""

    job_uuid: str = ""
    world_size: int = 0
    steps_per_second: float = 0.0
    tokens_per_second: float = 0.0
    peak_memory_mb: float = 0.0
    cpu_percent: float = 0.0


@register_message
@dataclass
class BrainProfileReport:
    """Workload-shape features for fleet-scale similarity (profiles
    table). Reported once at job registration; lets the create stage
    warm-start models that have never run under this signature."""

    job_uuid: str = ""
    param_count: float = 0.0
    flops_per_step: float = 0.0
    tokens_per_batch: float = 0.0
    seq_len: int = 0
    arch: str = ""


@register_message
@dataclass
class BrainFleetQuery:
    """Ask for the per-signature fleet aggregates."""


@register_message
@dataclass
class BrainFleetReport:
    cohorts: Dict = field(default_factory=dict)
    total_jobs: int = 0


@register_message
@dataclass
class BrainEventReport:
    job_uuid: str = ""
    event_type: str = ""
    node_id: int = -1
    detail: str = ""


@register_message
@dataclass
class BrainOptimizeRequest:
    """Stage-based optimize query (reference brain_pb2 optimize RPC)."""

    # create | running | init_adjust | deadline | oom
    stage: str = "create"
    job_uuid: str = ""
    model_signature: str = ""
    workload: str = ""
    current_workers: int = 0
    node_unit: int = 1
    max_workers: int = 0
    # stage-specific knobs (deadline: remaining_steps, deadline_s)
    extra: Dict = field(default_factory=dict)


@register_message
@dataclass
class BrainAllocateRequest:
    """Cross-job host arbitration: split ``total_hosts`` across the
    running jobs by marginal-throughput gain."""

    job_uuids: list = field(default_factory=list)
    total_hosts: int = 0
    node_unit: int = 1


@register_message
@dataclass
class BrainAllocateResponse:
    allocation: Dict = field(default_factory=dict)  # job_uuid -> hosts


@register_message
@dataclass
class BrainOptimizeResponse:
    worker_num: int = 0
    memory_mb_per_host: float = 0.0
    predicted_speed: float = 0.0
    reason: str = ""
    extra: Dict = field(default_factory=dict)


@register_message
@dataclass
class BrainJobQuery:
    job_uuid: str = ""


@register_message
@dataclass
class BrainJobInfo:
    job_uuid: str = ""
    job_name: str = ""
    model_signature: str = ""
    workload: str = ""
    worker_num: int = 0
    status: str = ""
    metric_count: int = 0
