"""Brain: cluster-level resource optimization service.

TPU-native counterpart of the reference's Go Brain
(``dlrover/go/brain/``, ~15.2k LoC; SURVEY.md §2.14): a standalone
service that persists runtime metrics from every job into a datastore
and answers stage-based optimization queries (job creation, running
adjustment, OOM recovery) from that cross-job history.  Masters consume
it through :class:`dlrover_tpu.master.resource.brain_optimizer.
BrainResourceOptimizer` the way the reference master consumes Brain via
``master/resource/brain_optimizer.py:64`` — and degrade gracefully to
local optimization when the service is unreachable.
"""

from .algorithms import (
    JobCreateResourceAlgorithm,
    JobRunningResourceAlgorithm,
    OomRecoveryAlgorithm,
)
from .client import BrainClient
from .datastore import (
    BrainDataStore,
    JobMetricSample,
    JobProfile,
    JobRecord,
    profile_distance,
    transformer_profile,
)
from .service import BrainService

__all__ = [
    "BrainClient",
    "BrainDataStore",
    "BrainService",
    "JobCreateResourceAlgorithm",
    "JobMetricSample",
    "JobProfile",
    "JobRecord",
    "JobRunningResourceAlgorithm",
    "OomRecoveryAlgorithm",
    "profile_distance",
    "transformer_profile",
]
