"""Brain datastore: persistent cross-job metric history.

Reference: ``dlrover/go/brain/pkg/datastore/`` — a MySQL-backed store of
job metadata + runtime metrics that the optimizer algorithms mine.  The
TPU build uses sqlite (single file, zero-dependency, transactional),
which matches the deployment shape: one Brain per cluster, modest write
rates (one sample per job per ~30 s), read-mostly optimization queries.
"""

import json
import os
import sqlite3
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional


@dataclass
class JobRecord:
    """One job's identity + outcome (reference datastore job table)."""

    job_uuid: str
    job_name: str = ""
    # Signature fields drive similarity matching across jobs: same model
    # scale + workload type ⇒ history is transferable.
    model_signature: str = ""  # e.g. "gpt2-small-124M"
    workload: str = "jax"  # jax | torch | custom
    worker_num: int = 0
    node_unit: int = 1
    status: str = "running"  # running | completed | failed | oom
    created_at: float = field(default_factory=time.time)
    finished_at: float = 0.0
    extra: Dict = field(default_factory=dict)


@dataclass
class JobProfile:
    """Workload shape features for cross-model similarity.

    The reference Brain sizes new jobs from *exact* job-name history
    (``optimize_job_worker_create_resource.go`` keys on job cohorts); at
    fleet scale a brand-new model has no exact cohort, but its SHAPE
    (parameter count, step FLOPs, batch tokens) predicts which history
    transfers. Distances are computed in log-space — a 124M and a 350M
    model are "one doubling and a bit" apart regardless of absolute
    scale.
    """

    job_uuid: str
    param_count: float = 0.0  # model parameters
    flops_per_step: float = 0.0  # fwd+bwd FLOPs per optimizer step
    tokens_per_batch: float = 0.0  # global batch tokens per step
    seq_len: int = 0
    arch: str = ""  # model family: gpt | llama | moe | ...


def transformer_profile(
    job_uuid: str,
    n_params: float,
    global_batch: int,
    seq_len: int,
    arch: str = "gpt",
) -> JobProfile:
    """Profile for a dense-transformer LM job from first principles:
    tokens = batch*seq, step FLOPs ≈ 6*N*tokens (fwd 2N + bwd 4N per
    token) — the same accounting bench.py's MFU uses."""
    tokens = float(global_batch) * float(seq_len)
    return JobProfile(
        job_uuid=job_uuid,
        param_count=float(n_params),
        flops_per_step=6.0 * float(n_params) * tokens,
        tokens_per_batch=tokens,
        seq_len=int(seq_len),
        arch=arch,
    )


def profile_distance(a: JobProfile, b: JobProfile) -> float:
    """Log-space L1 distance over the shape features present on BOTH
    profiles, plus a flat penalty for an architecture-family mismatch
    (a MoE's step economics don't transfer to a dense model 1:1).

    The per-feature distances are combined as a WEIGHTED MEAN, not a
    sum: params and step FLOPs are near-perfectly correlated at equal
    batch tokens (flops ≈ 6·N·tokens), so a sum would double-count
    model scale and halve the effective transfer range.

    At least one SCALE feature (param count or step FLOPs) must be
    comparable: tokens-per-batch alone says nothing about model scale,
    and a distance built only on it would rank a 124M donor as an
    exact match for a 70B probe."""
    import math

    d = 0.0
    total_weight = 0.0
    scale_features = 0
    for attr, weight in (
        ("param_count", 1.0),
        ("flops_per_step", 1.0),
        ("tokens_per_batch", 0.5),
    ):
        va, vb = getattr(a, attr), getattr(b, attr)
        if va > 0 and vb > 0:
            d += weight * abs(math.log(va / vb))
            total_weight += weight
            if attr != "tokens_per_batch":
                scale_features += 1
    if scale_features == 0:
        return float("inf")
    d /= total_weight
    if a.arch and b.arch and a.arch != b.arch:
        d += 1.0
    return d


@dataclass
class JobMetricSample:
    """One runtime observation of a running job."""

    job_uuid: str
    timestamp: float = field(default_factory=time.time)
    world_size: int = 0
    steps_per_second: float = 0.0
    tokens_per_second: float = 0.0
    peak_memory_mb: float = 0.0
    cpu_percent: float = 0.0


class BrainDataStore:
    """Thread-safe sqlite store. ``path=':memory:'`` for tests."""

    def __init__(self, path: str = ":memory:"):
        if path != ":memory:":
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._mu = threading.Lock()
        with self._mu:
            self._conn.executescript(
                """
                CREATE TABLE IF NOT EXISTS jobs (
                    job_uuid TEXT PRIMARY KEY,
                    job_name TEXT,
                    model_signature TEXT,
                    workload TEXT,
                    worker_num INTEGER,
                    node_unit INTEGER,
                    status TEXT,
                    created_at REAL,
                    finished_at REAL,
                    extra TEXT
                );
                CREATE TABLE IF NOT EXISTS metrics (
                    job_uuid TEXT,
                    timestamp REAL,
                    world_size INTEGER,
                    steps_per_second REAL,
                    tokens_per_second REAL,
                    peak_memory_mb REAL,
                    cpu_percent REAL
                );
                CREATE INDEX IF NOT EXISTS idx_metrics_job
                    ON metrics (job_uuid, timestamp);
                CREATE TABLE IF NOT EXISTS events (
                    job_uuid TEXT,
                    timestamp REAL,
                    event_type TEXT,
                    node_id INTEGER,
                    detail TEXT
                );
                CREATE TABLE IF NOT EXISTS profiles (
                    job_uuid TEXT PRIMARY KEY,
                    param_count REAL,
                    flops_per_step REAL,
                    tokens_per_batch REAL,
                    seq_len INTEGER,
                    arch TEXT
                );
                """
            )
            self._conn.commit()

    # -- jobs --------------------------------------------------------------

    def upsert_job(self, job: JobRecord) -> None:
        with self._mu:
            self._conn.execute(
                "INSERT INTO jobs VALUES (?,?,?,?,?,?,?,?,?,?) "
                "ON CONFLICT(job_uuid) DO UPDATE SET "
                "job_name=excluded.job_name, "
                "model_signature=excluded.model_signature, "
                "workload=excluded.workload, "
                "worker_num=excluded.worker_num, "
                "node_unit=excluded.node_unit, "
                "status=excluded.status, "
                "finished_at=excluded.finished_at, "
                "extra=excluded.extra",
                (
                    job.job_uuid,
                    job.job_name,
                    job.model_signature,
                    job.workload,
                    job.worker_num,
                    job.node_unit,
                    job.status,
                    job.created_at,
                    job.finished_at,
                    json.dumps(job.extra),
                ),
            )
            self._conn.commit()

    def update_job_status(self, job_uuid: str, status: str) -> None:
        finished = (
            time.time() if status in ("completed", "failed", "oom") else 0.0
        )
        with self._mu:
            self._conn.execute(
                "UPDATE jobs SET status=?, finished_at=? WHERE job_uuid=?",
                (status, finished, job_uuid),
            )
            self._conn.commit()

    def get_job(self, job_uuid: str) -> Optional[JobRecord]:
        with self._mu:
            row = self._conn.execute(
                "SELECT * FROM jobs WHERE job_uuid=?", (job_uuid,)
            ).fetchone()
        return self._row_to_job(row) if row else None

    def similar_jobs(
        self,
        model_signature: str,
        workload: str = "",
        status: str = "completed",
        limit: int = 50,
    ) -> List[JobRecord]:
        """History transferable to a new job: same model signature (and
        workload, when given), most recent first."""
        q = "SELECT * FROM jobs WHERE model_signature=? AND status=?"
        args: List = [model_signature, status]
        if workload:
            q += " AND workload=?"
            args.append(workload)
        q += " ORDER BY created_at DESC LIMIT ?"
        args.append(limit)
        with self._mu:
            rows = self._conn.execute(q, args).fetchall()
        return [self._row_to_job(r) for r in rows]

    # -- profiles ----------------------------------------------------------

    def upsert_profile(self, profile: JobProfile) -> None:
        with self._mu:
            self._conn.execute(
                "INSERT INTO profiles VALUES (?,?,?,?,?,?) "
                "ON CONFLICT(job_uuid) DO UPDATE SET "
                "param_count=excluded.param_count, "
                "flops_per_step=excluded.flops_per_step, "
                "tokens_per_batch=excluded.tokens_per_batch, "
                "seq_len=excluded.seq_len, "
                "arch=excluded.arch",
                (
                    profile.job_uuid,
                    profile.param_count,
                    profile.flops_per_step,
                    profile.tokens_per_batch,
                    profile.seq_len,
                    profile.arch,
                ),
            )
            self._conn.commit()

    def get_profile(self, job_uuid: str) -> Optional[JobProfile]:
        with self._mu:
            row = self._conn.execute(
                "SELECT * FROM profiles WHERE job_uuid=?", (job_uuid,)
            ).fetchone()
        return self._row_to_profile(row) if row else None

    def nearest_profiles(
        self,
        profile: JobProfile,
        k: int = 8,
        status: str = "completed",
        limit: int = 500,
    ) -> List[tuple]:
        """The ``k`` profiled jobs (of the given status, most recent
        ``limit`` considered) nearest to ``profile`` in workload-shape
        space: ``[(JobRecord, JobProfile, distance), ...]`` ascending.
        This is the fleet-scale warm-start query — a new model with no
        exact-signature cohort borrows history from shape-similar jobs.
        """
        with self._mu:
            rows = self._conn.execute(
                "SELECT j.job_uuid, p.param_count, p.flops_per_step, "
                "p.tokens_per_batch, p.seq_len, p.arch "
                "FROM jobs j JOIN profiles p ON j.job_uuid = p.job_uuid "
                "WHERE j.status=? AND j.job_uuid != ? "
                "ORDER BY j.created_at DESC LIMIT ?",
                (status, profile.job_uuid, limit),
            ).fetchall()
        scored = []
        for r in rows:
            cand = self._row_to_profile(r)
            d = profile_distance(profile, cand)
            if d != float("inf"):
                scored.append((cand, d))
        scored.sort(key=lambda t: t[1])
        out = []
        for cand, d in scored[:k]:
            job = self.get_job(cand.job_uuid)
            if job is not None:
                out.append((job, cand, d))
        return out

    # -- fleet aggregates --------------------------------------------------

    def fleet_summary(self) -> Dict:
        """Per-signature fleet aggregates (reference Brain's cluster
        stats processors): job counts by outcome, the best observed
        speed and the peak memory across each cohort — the ops-facing
        view of what the datastore knows."""
        with self._mu:
            rows = self._conn.execute(
                "SELECT model_signature, status, COUNT(*) "
                "FROM jobs GROUP BY model_signature, status"
            ).fetchall()
            worker_rows = self._conn.execute(
                "SELECT model_signature, AVG(worker_num) "
                "FROM jobs GROUP BY model_signature"
            ).fetchall()
            speed_rows = self._conn.execute(
                "SELECT j.model_signature, MAX(m.steps_per_second), "
                "MAX(m.peak_memory_mb) FROM jobs j "
                "JOIN metrics m ON j.job_uuid = m.job_uuid "
                "GROUP BY j.model_signature"
            ).fetchall()
        cohorts: Dict[str, Dict] = {}
        for sig, status, count in rows:
            c = cohorts.setdefault(
                sig or "?", {"jobs": 0, "by_status": {}, "avg_workers": 0.0}
            )
            c["jobs"] += count
            c["by_status"][status] = count
        for sig, avg_workers in worker_rows:
            cohorts.setdefault(sig or "?", {"jobs": 0, "by_status": {}})[
                "avg_workers"
            ] = round(float(avg_workers or 0.0), 1)
        for sig, best_speed, peak_mem in speed_rows:
            c = cohorts.setdefault(sig or "?", {"jobs": 0, "by_status": {}})
            c["best_steps_per_s"] = round(float(best_speed or 0.0), 3)
            c["peak_memory_mb"] = round(float(peak_mem or 0.0), 1)
        total = sum(c["jobs"] for c in cohorts.values())
        return {"cohorts": cohorts, "total_jobs": total}

    # -- metrics -----------------------------------------------------------

    def add_metric(self, sample: JobMetricSample) -> None:
        with self._mu:
            self._conn.execute(
                "INSERT INTO metrics VALUES (?,?,?,?,?,?,?)",
                (
                    sample.job_uuid,
                    sample.timestamp,
                    sample.world_size,
                    sample.steps_per_second,
                    sample.tokens_per_second,
                    sample.peak_memory_mb,
                    sample.cpu_percent,
                ),
            )
            self._conn.commit()

    def job_metrics(
        self, job_uuid: str, since: float = 0.0, limit: int = 1000
    ) -> List[JobMetricSample]:
        with self._mu:
            rows = self._conn.execute(
                "SELECT * FROM metrics WHERE job_uuid=? AND timestamp>=? "
                "ORDER BY timestamp ASC LIMIT ?",
                (job_uuid, since, limit),
            ).fetchall()
        return [
            JobMetricSample(
                job_uuid=r[0],
                timestamp=r[1],
                world_size=r[2],
                steps_per_second=r[3],
                tokens_per_second=r[4],
                peak_memory_mb=r[5],
                cpu_percent=r[6],
            )
            for r in rows
        ]

    def speed_by_world_size(self, job_uuids: List[str]) -> Dict[int, float]:
        """Best observed steps/s per world size across the given jobs —
        the scaling curve the create-stage optimizer mines."""
        if not job_uuids:
            return {}
        marks = ",".join("?" * len(job_uuids))
        with self._mu:
            rows = self._conn.execute(
                f"SELECT world_size, MAX(steps_per_second) FROM metrics "
                f"WHERE job_uuid IN ({marks}) AND world_size>0 "
                f"GROUP BY world_size",
                job_uuids,
            ).fetchall()
        return {int(w): float(s) for w, s in rows if s}

    def peak_memory(self, job_uuids: List[str]) -> float:
        if not job_uuids:
            return 0.0
        marks = ",".join("?" * len(job_uuids))
        with self._mu:
            row = self._conn.execute(
                f"SELECT MAX(peak_memory_mb) FROM metrics "
                f"WHERE job_uuid IN ({marks})",
                job_uuids,
            ).fetchone()
        return float(row[0] or 0.0)

    # -- prometheus ingestion ----------------------------------------------

    # scraped-gauge base name -> JobMetricSample field. Covers both the
    # master-registry names (metrics_snapshot) and the agent-scrape
    # names so either side of the plane round-trips.
    GAUGE_FIELD_MAP = {
        "dlrover_job_steps_per_second": "steps_per_second",
        "dlrover_steps_per_second": "steps_per_second",
        "dlrover_job_tokens_per_second": "tokens_per_second",
        "dlrover_tokens_per_second": "tokens_per_second",
        "dlrover_job_peak_memory_mb": "peak_memory_mb",
        "dlrover_peak_memory_mb": "peak_memory_mb",
        "dlrover_cpu_percent": "cpu_percent",
        "dlrover_agent_world_size": "world_size",
        "dlrover_world_size": "world_size",
    }

    # how labeled series of one family combine into one sample value:
    # throughput sums across workers, memory takes the worst host,
    # utilization averages, world size is a max (every series reports
    # the same world; max tolerates a straggler's stale 0)
    _FIELD_AGG = {
        "steps_per_second": "sum",
        "tokens_per_second": "sum",
        "peak_memory_mb": "max",
        "cpu_percent": "mean",
        "world_size": "max",
    }

    def ingest_gauges(
        self,
        job_uuid: str,
        gauges: Dict[str, float],
        world_size: int = 0,
        timestamp: float = 0.0,
        field_map: Optional[Dict[str, str]] = None,
    ) -> Optional[JobMetricSample]:
        """Round-trip scraped metrics into one :class:`JobMetricSample`.

        Accepts the flattened key format ``parse_prometheus``
        (``agent/metric_collector.py``) emits: every sample keeps its
        FULL exposition key (``name{labels}``) and each labeled family
        additionally carries a bare-name alias holding its last
        sample. Keys are grouped by base name (the part before
        ``{``); when a family has labeled series, its bare alias is
        IGNORED — counting both would double the last worker's
        contribution. Per-field aggregation follows ``_FIELD_AGG``.

        Returns the stored sample, or None when no key mapped to a
        sample field (nothing is written).
        """
        fmap = field_map or self.GAUGE_FIELD_MAP
        series: Dict[str, List[float]] = {}
        has_labels: Dict[str, bool] = {}
        for key, value in gauges.items():
            base, brace, _ = key.partition("{")
            if base not in fmap:
                continue
            labeled = brace == "{"
            if labeled and not has_labels.get(base):
                # first labeled series wins the family: drop any bare
                # alias collected before it
                series[base] = []
                has_labels[base] = True
            elif not labeled and has_labels.get(base):
                continue  # bare alias of a labeled family
            series.setdefault(base, []).append(float(value))
        fields: Dict[str, float] = {}
        for base, values in series.items():
            if not values:
                continue
            name = fmap[base]
            agg = self._FIELD_AGG.get(name, "max")
            if agg == "sum":
                fields[name] = sum(values)
            elif agg == "mean":
                fields[name] = sum(values) / len(values)
            else:
                fields[name] = max(values)
        if not fields:
            return None
        sample = JobMetricSample(
            job_uuid=job_uuid,
            timestamp=timestamp or time.time(),
            world_size=world_size or int(fields.get("world_size", 0)),
            steps_per_second=fields.get("steps_per_second", 0.0),
            tokens_per_second=fields.get("tokens_per_second", 0.0),
            peak_memory_mb=fields.get("peak_memory_mb", 0.0),
            cpu_percent=fields.get("cpu_percent", 0.0),
        )
        self.add_metric(sample)
        return sample

    # -- events ------------------------------------------------------------

    def add_event(
        self, job_uuid: str, event_type: str, node_id: int = -1, detail: str = ""
    ) -> None:
        with self._mu:
            self._conn.execute(
                "INSERT INTO events VALUES (?,?,?,?,?)",
                (job_uuid, time.time(), event_type, node_id, detail),
            )
            self._conn.commit()

    def job_events(self, job_uuid: str, event_type: str = "") -> List[Dict]:
        q = "SELECT * FROM events WHERE job_uuid=?"
        args: List = [job_uuid]
        if event_type:
            q += " AND event_type=?"
            args.append(event_type)
        with self._mu:
            rows = self._conn.execute(q, args).fetchall()
        return [
            {
                "job_uuid": r[0],
                "timestamp": r[1],
                "event_type": r[2],
                "node_id": r[3],
                "detail": r[4],
            }
            for r in rows
        ]

    def close(self) -> None:
        with self._mu:
            self._conn.close()

    @staticmethod
    def _row_to_profile(row) -> JobProfile:
        return JobProfile(
            job_uuid=row[0],
            param_count=float(row[1] or 0.0),
            flops_per_step=float(row[2] or 0.0),
            tokens_per_batch=float(row[3] or 0.0),
            seq_len=int(row[4] or 0),
            arch=row[5] or "",
        )

    @staticmethod
    def _row_to_job(row) -> JobRecord:
        return JobRecord(
            job_uuid=row[0],
            job_name=row[1],
            model_signature=row[2],
            workload=row[3],
            worker_num=row[4],
            node_unit=row[5],
            status=row[6],
            created_at=row[7],
            finished_at=row[8],
            extra=json.loads(row[9] or "{}"),
        )


def job_record_to_dict(job: JobRecord) -> Dict:
    return asdict(job)
