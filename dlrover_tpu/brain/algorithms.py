"""Brain optimization algorithms.

Reference: ``dlrover/go/brain/pkg/optimizer/implementation/optalgorithm/``
— stage-specific algorithms (job create, init adjust, running, OOM
recovery) mining the datastore.  The PS-specific ones (hot-PS) have no
TPU counterpart; what carries over is the *stage* structure and the
history-driven decision style, re-targeted at slice-count selection:

- create stage: pick the initial worker (host) count and per-host memory
  from similar completed jobs' scaling curves (marginal-gain knee).
- running stage: compare this job's observed curve against history; grow
  while history says the next size still pays, shrink advice when past
  the knee.
- OOM recovery: bump memory by a factor with a cluster-wide cap
  (reference ``optimize_job_worker_create_oom_resource.go``).
"""

from dataclasses import dataclass, field
from typing import Dict, Optional

from ..common.log import logger
from .datastore import BrainDataStore, JobProfile

DEFAULT_MEMORY_SAFETY = 1.2  # headroom over historical peak
OOM_MEMORY_FACTOR = 1.5  # reference OOM algorithms use 1.5x-2x bumps


@dataclass
class OptimizePlan:
    """Brain's answer to one optimize query (wire-friendly)."""

    worker_num: int = 0  # 0 = no opinion
    memory_mb_per_host: float = 0.0
    reason: str = ""
    # steps/s the history predicts at worker_num (0 = unknown)
    predicted_speed: float = 0.0
    extra: Dict = field(default_factory=dict)

    def empty(self) -> bool:
        return self.worker_num <= 0 and self.memory_mb_per_host <= 0


def _knee_of_curve(
    curve: Dict[int, float], node_unit: int, max_workers: int, min_gain: float
) -> int:
    """Largest world size on ``curve`` whose marginal speedup per host is
    still ≥ ``min_gain`` of linear; the classic scaling-knee rule the
    local ThroughputScalingOptimizer applies online, applied offline to
    history here."""
    sizes = sorted(s for s in curve if s <= max_workers)
    if not sizes:
        return 0
    best = sizes[0]
    for prev, cur in zip(sizes, sizes[1:]):
        gained = curve[cur] - curve[prev]
        per_host = gained / max(1, cur - prev)
        linear_per_host = curve[prev] / prev if prev else 0.0
        if linear_per_host <= 0 or per_host >= min_gain * linear_per_host:
            best = cur
        else:
            break
    # snap to slice granularity
    if node_unit > 1:
        best = (best // node_unit) * node_unit
    return best


class JobCreateResourceAlgorithm:
    """Initial resources for a brand-new job (reference
    ``optimize_job_worker_create_resource.go``): warm-start from similar
    completed jobs; with a :class:`JobProfile`, a model with NO
    exact-signature history borrows shape-similar jobs' curves instead
    (fleet-scale warm start); true cold-start returns no opinion so the
    master falls back to its configured defaults."""

    # Neighbors farther than this in log-shape space (weighted-mean
    # per-feature |log ratio|) carry no transferable signal: e^2 ≈ 7.4x
    # scale mismatch, or a closer scale with an arch-family mismatch.
    MAX_PROFILE_DISTANCE = 2.0
    # Per-host memory transfer: activations/optimizer state scale with
    # params, but not past this clamp either way.
    MEM_RATIO_CLAMP = (0.5, 4.0)

    def __init__(self, store: BrainDataStore, min_gain: float = 0.4):
        self._store = store
        self._min_gain = min_gain

    def optimize(
        self,
        model_signature: str,
        workload: str = "",
        node_unit: int = 1,
        max_workers: int = 0,
        profile: Optional[JobProfile] = None,
    ) -> OptimizePlan:
        history = self._store.similar_jobs(model_signature, workload)
        if not history:
            if profile is not None:
                return self._profile_warm_start(
                    profile, node_unit, max_workers
                )
            return OptimizePlan(reason="cold start: no similar job history")
        uuids = [j.job_uuid for j in history]
        curve = self._store.speed_by_world_size(uuids)
        limit = max_workers or max((j.worker_num for j in history), default=0)
        worker_num = _knee_of_curve(curve, node_unit, limit, self._min_gain)
        if worker_num <= 0:
            # history exists but carries no usable speed curve; recommend
            # the most common successful size
            sizes = sorted(j.worker_num for j in history if j.worker_num > 0)
            worker_num = sizes[len(sizes) // 2] if sizes else 0
        peak_mem = self._store.peak_memory(uuids)
        return OptimizePlan(
            worker_num=worker_num,
            memory_mb_per_host=peak_mem * DEFAULT_MEMORY_SAFETY,
            predicted_speed=curve.get(worker_num, 0.0),
            reason=f"warm start from {len(history)} similar jobs",
            extra={"speed_curve": {str(k): v for k, v in curve.items()}},
        )

    def _profile_warm_start(
        self, profile: JobProfile, node_unit: int, max_workers: int
    ) -> OptimizePlan:
        """Fleet-scale sizing: no job with this signature has ever run,
        but shape-similar jobs have. Each neighbor's speed curve is
        transferred by its FLOPs ratio (same tokens per step, a job
        doing r× the FLOPs runs at 1/r the steps/s — the compute-bound
        first-order model), then the transferred curves are merged and
        the usual marginal-gain knee applies. Memory transfers by the
        param-count ratio, clamped: parameters and optimizer state
        scale linearly, activations sublinearly."""
        neighbors = [
            (job, prof, dist)
            for job, prof, dist in self._store.nearest_profiles(profile)
            if dist <= self.MAX_PROFILE_DISTANCE
        ]
        if not neighbors:
            return OptimizePlan(
                reason="cold start: no signature or shape-similar history"
            )
        curve: Dict[int, float] = {}
        mem_mb = 0.0
        for job, prof, dist in neighbors:
            # Transfer scale: FLOPs ratio when both sides report it,
            # param ratio as the proxy otherwise (FLOPs ∝ active params
            # at equal tokens). A neighbor comparable on NEITHER never
            # got past profile_distance's scale-feature requirement, so
            # an unscaled (scale=1) transfer cannot happen here.
            if profile.flops_per_step > 0 and prof.flops_per_step > 0:
                scale = prof.flops_per_step / profile.flops_per_step
            else:
                scale = prof.param_count / profile.param_count
            for size, speed in self._store.speed_by_world_size(
                [job.job_uuid]
            ).items():
                transferred = speed * scale
                if transferred > curve.get(size, 0.0):
                    curve[size] = transferred
            peak = self._store.peak_memory([job.job_uuid])
            if peak > 0:
                lo, hi = self.MEM_RATIO_CLAMP
                if profile.param_count > 0 and prof.param_count > 0:
                    ratio = min(
                        hi, max(lo, profile.param_count / prof.param_count)
                    )
                else:
                    # params not comparable: the donor's own peak is
                    # still a better floor than recommending 0 MB
                    ratio = 1.0
                mem_mb = max(mem_mb, peak * ratio)
        limit = max_workers or max(curve, default=0)
        worker_num = _knee_of_curve(curve, node_unit, limit, self._min_gain)
        if worker_num <= 0:
            sizes = sorted(j.worker_num for j, _, _ in neighbors if j.worker_num > 0)
            worker_num = sizes[len(sizes) // 2] if sizes else 0
        nearest = neighbors[0]
        return OptimizePlan(
            worker_num=worker_num,
            memory_mb_per_host=mem_mb * DEFAULT_MEMORY_SAFETY,
            predicted_speed=curve.get(worker_num, 0.0),
            reason=(
                f"profile warm start from {len(neighbors)} shape-similar "
                f"jobs (nearest: {nearest[0].model_signature!r} at "
                f"distance {nearest[2]:.2f})"
            ),
            extra={
                "profile_neighbors": [
                    {
                        "model_signature": j.model_signature,
                        "distance": round(d, 3),
                    }
                    for j, _, d in neighbors
                ],
                "speed_curve": {str(k): round(v, 4) for k, v in curve.items()},
            },
        )


class JobRunningResourceAlgorithm:
    """Adjust a running job (reference
    ``optimize_job_worker_resource.go``): combine the job's own observed
    scaling points with history from similar jobs, and recommend the
    knee. A recommendation equal to the current size means hold."""

    def __init__(self, store: BrainDataStore, min_gain: float = 0.4):
        self._store = store
        self._min_gain = min_gain

    def optimize(
        self,
        job_uuid: str,
        current_workers: int,
        node_unit: int = 1,
        max_workers: int = 0,
    ) -> OptimizePlan:
        job = self._store.get_job(job_uuid)
        if job is None:
            return OptimizePlan(reason=f"unknown job {job_uuid}")
        own_curve = self._store.speed_by_world_size([job_uuid])
        similar = self._store.similar_jobs(job.model_signature, job.workload)
        hist_curve = self._store.speed_by_world_size(
            [j.job_uuid for j in similar]
        )
        # Own observations dominate; history fills in sizes not yet tried.
        curve = dict(hist_curve)
        curve.update(own_curve)
        if not curve:
            return OptimizePlan(reason="no scaling observations yet")
        limit = max_workers or max(max(curve), current_workers)
        target = _knee_of_curve(curve, node_unit, limit, self._min_gain)
        if target <= 0 or target == current_workers:
            return OptimizePlan(
                reason=f"hold at {current_workers} (knee={target or 'n/a'})"
            )
        return OptimizePlan(
            worker_num=target,
            predicted_speed=curve.get(target, 0.0),
            reason=(
                f"scaling knee at {target} hosts "
                f"(observed {sorted(own_curve)}, history {sorted(hist_curve)})"
            ),
        )


class JobInitAdjustAlgorithm:
    """Early-stage sanity adjust (reference
    ``optimize_job_worker_init_adjust_resource.go``): once the first
    real samples land, compare the job's observed speed against what
    history PREDICTS for its size. A job running far below its cohort
    is misconfigured (bad host, wrong batch size, thermal throttle) —
    flag it and recommend the cohort's knee rather than letting the
    online optimizer slow-walk the discovery."""

    # below this fraction of the cohort's speed at the same size, the
    # job is anomalous, not just noisy
    UNDERPERF_FRACTION = 0.6

    def __init__(self, store: BrainDataStore, min_gain: float = 0.4):
        self._store = store
        self._min_gain = min_gain

    def optimize(
        self,
        job_uuid: str,
        node_unit: int = 1,
        max_workers: int = 0,
    ) -> OptimizePlan:
        job = self._store.get_job(job_uuid)
        if job is None:
            return OptimizePlan(reason=f"unknown job {job_uuid}")
        own = self._store.speed_by_world_size([job_uuid])
        if not own:
            return OptimizePlan(reason="no samples yet")
        similar = [
            j
            for j in self._store.similar_jobs(
                job.model_signature, job.workload
            )
            if j.job_uuid != job_uuid
        ]
        if not similar:
            return OptimizePlan(reason="no cohort to compare against")
        cohort = self._store.speed_by_world_size(
            [j.job_uuid for j in similar]
        )
        size, speed = max(own.items())  # newest/largest observed size
        expected = cohort.get(size)
        if expected is None:
            # Interpolate between BRACKETING cohort sizes only. Linear
            # extrapolation through the origin past the cohort's
            # largest observation assumes linear scaling — the exact
            # assumption saturating curves violate — and would flag
            # healthy large jobs as anomalous.
            smaller = [s for s in cohort if s < size]
            larger = [s for s in cohort if s > size]
            if smaller and larger:
                s0, s1 = max(smaller), min(larger)
                frac = (size - s0) / (s1 - s0)
                expected = cohort[s0] + frac * (cohort[s1] - cohort[s0])
        if not expected or expected <= 0:
            return OptimizePlan(reason="cohort has no comparable size")
        ratio = speed / expected
        if ratio >= self.UNDERPERF_FRACTION:
            return OptimizePlan(
                reason=f"healthy: {ratio:.0%} of cohort speed at {size} hosts",
                extra={"cohort_ratio": round(ratio, 3)},
            )
        limit = max_workers or max(cohort)
        knee = _knee_of_curve(cohort, node_unit, limit, self._min_gain)
        self._store.add_event(
            job_uuid,
            "init_underperformance",
            detail=f"{ratio:.2f} of cohort at {size} hosts",
        )
        return OptimizePlan(
            worker_num=knee,
            predicted_speed=cohort.get(knee, 0.0),
            reason=(
                f"underperforming cohort ({ratio:.0%} of expected "
                f"{expected:.2f} steps/s at {size} hosts) — check for a "
                f"slow host; cohort knee is {knee}"
            ),
            extra={"cohort_ratio": round(ratio, 3), "anomaly": True},
        )


class CompletionTimePredictor:
    """Deadline-aware sizing: predict remaining wall time at candidate
    world sizes from the speed curve (own + cohort) and pick the
    SMALLEST size that meets the deadline — the reference Brain's
    training-speed estimators serve the same 'what do I need to finish
    by X' question; hosts beyond that size are quota other jobs could
    use."""

    def __init__(self, store: BrainDataStore, min_gain: float = 0.4):
        self._store = store
        self._min_gain = min_gain

    def optimize(
        self,
        job_uuid: str,
        remaining_steps: int,
        deadline_s: float,
        node_unit: int = 1,
        max_workers: int = 0,
    ) -> OptimizePlan:
        job = self._store.get_job(job_uuid)
        if job is None:
            return OptimizePlan(reason=f"unknown job {job_uuid}")
        own = self._store.speed_by_world_size([job_uuid])
        cohort = self._store.speed_by_world_size(
            [
                j.job_uuid
                for j in self._store.similar_jobs(
                    job.model_signature, job.workload
                )
            ]
        )
        curve = dict(cohort)
        curve.update(own)
        if not curve or remaining_steps <= 0 or deadline_s <= 0:
            return OptimizePlan(reason="insufficient data for prediction")
        limit = max_workers or max(curve)
        # Candidates are the OBSERVED sizes (snapping first would index
        # the curve at keys that were never measured and silently drop
        # cohorts run at off-granularity sizes); the final pick is
        # rounded UP to slice granularity — a bigger slice only
        # finishes sooner.
        etas = {
            s: remaining_steps / speed
            for s, speed in curve.items()
            if 0 < s <= limit and speed > 0
        }
        feasible = [s for s, eta in etas.items() if eta <= deadline_s]
        if feasible:
            observed = min(feasible)
            pick = -(-observed // node_unit) * node_unit
            if pick > limit:
                # rounding up crossed the caller's cap: stay at the
                # observed (in-quota) size even if off-granularity
                pick = observed
            return OptimizePlan(
                worker_num=pick,
                predicted_speed=curve[observed],
                reason=(
                    f"{remaining_steps} steps in {etas[observed]:.0f}s at "
                    f"{observed} hosts meets the {deadline_s:.0f}s deadline"
                    + (
                        f" (rounded to slice multiple {pick})"
                        if pick != observed
                        else ""
                    )
                ),
                extra={"eta_s": {str(s): round(e, 1) for s, e in etas.items()}},
            )
        # nothing meets it: recommend the knee (fastest EFFICIENT size)
        # and say so — burning hosts past the knee won't save the
        # deadline either.
        knee = _knee_of_curve(curve, node_unit, limit, self._min_gain)
        best_eta = min(etas.values()) if etas else 0.0
        return OptimizePlan(
            worker_num=knee,
            predicted_speed=curve.get(knee, 0.0),
            reason=(
                f"deadline unreachable (best ETA {best_eta:.0f}s > "
                f"{deadline_s:.0f}s); recommending the efficiency knee {knee}"
            ),
            extra={"deadline_unreachable": True},
        )


class ClusterResourceArbiter:
    """Cross-JOB host allocation — the genuinely cluster-level piece of
    the reference Brain (its optimizers mine a cross-job datastore to
    size every job against shared quota). Given the running jobs and a
    host pool, allocate hosts greedily by MARGINAL throughput gain per
    host (each job's gain read off its own/cohort speed curve), so a
    saturated job never holds hosts a scaling job could convert into
    cluster throughput."""

    def __init__(self, store: BrainDataStore):
        self._store = store

    def _curve(self, job) -> Dict[int, float]:
        curve = self._store.speed_by_world_size(
            [
                j.job_uuid
                for j in self._store.similar_jobs(
                    job.model_signature, job.workload
                )
            ]
        )
        curve.update(self._store.speed_by_world_size([job.job_uuid]))
        return curve

    @staticmethod
    def _marginal(curve: Dict[int, float], size: int, unit: int) -> float:
        """Estimated steps/s gained by growing ``size`` -> ``size+unit``,
        interpolated/extrapolated from the observed points."""
        if not curve:
            return 0.0
        nxt = size + unit
        if size in curve and nxt in curve:
            return curve[nxt] - curve[size]
        sizes = sorted(curve)
        below = [s for s in sizes if s <= size]
        above = [s for s in sizes if s > size]
        if below and above:
            # interpolate: linear fit through the bracketing points
            s0, s1 = below[-1], above[0]
        elif len(sizes) >= 2:
            # extrapolate with the TAIL slope (the two largest
            # observed sizes). Average throughput (curve[s]/s) here
            # would report a large "marginal" gain for a SATURATED
            # curve — e.g. {1: 10, 8: 11} averages 1.4/host while the
            # real tail marginal is 0.14 — and the greedy allocator
            # would feed the whole pool to exactly the job that can't
            # use it.
            s0, s1 = sizes[-2], sizes[-1]
        else:
            s0 = s1 = sizes[0]
        if s0 == s1:
            # single observed point: no slope is knowable; claim
            # nothing rather than inventing linear scaling
            return 0.0
        slope = (curve[s1] - curve[s0]) / (s1 - s0)
        return max(0.0, slope * unit)

    def allocate(
        self,
        job_uuids,
        total_hosts: int,
        node_unit: int = 1,
    ) -> Dict[str, int]:
        """{job_uuid: host_count} summing to ≤ total_hosts. Every known
        job gets at least one slice (starvation-free); remaining slices
        go to the highest marginal gain."""
        jobs = [
            j
            for j in (self._store.get_job(u) for u in job_uuids)
            if j is not None
        ]
        if not jobs or total_hosts < node_unit * len(jobs):
            return {}
        alloc = {j.job_uuid: node_unit for j in jobs}
        curves = {j.job_uuid: self._curve(j) for j in jobs}
        spare = total_hosts - node_unit * len(jobs)
        while spare >= node_unit:
            gains = {
                u: self._marginal(curves[u], alloc[u], node_unit)
                for u in alloc
            }
            u_best = max(gains, key=lambda u: gains[u])
            if gains[u_best] <= 0:
                break  # everyone saturated; leave the rest in the pool
            alloc[u_best] += node_unit
            spare -= node_unit
        return alloc


class OomRecoveryAlgorithm:
    """Memory bump after an OOM (reference
    ``optimize_job_worker_create_oom_resource.go``): factor increase over
    the observed peak, capped by the per-host limit."""

    def __init__(self, store: BrainDataStore, memory_limit_mb: float = 0.0):
        self._store = store
        self._limit = memory_limit_mb

    def optimize(self, job_uuid: str) -> OptimizePlan:
        peak = self._store.peak_memory([job_uuid])
        if peak <= 0:
            # no usage data: nothing principled to say
            return OptimizePlan(reason="no memory observations for job")
        target = peak * OOM_MEMORY_FACTOR
        if self._limit and target > self._limit:
            if peak >= self._limit:
                logger.warning(
                    "job %s OOM at peak %.0f MB already at limit %.0f MB",
                    job_uuid,
                    peak,
                    self._limit,
                )
                return OptimizePlan(
                    reason="peak memory already at cluster limit",
                    extra={"at_limit": True},
                )
            target = self._limit
        self._store.add_event(job_uuid, "oom_recovery_plan", detail=f"{target:.0f}MB")
        return OptimizePlan(
            memory_mb_per_host=target,
            reason=f"OOM recovery: {peak:.0f} MB peak -> {target:.0f} MB",
        )
