"""Brain optimization algorithms.

Reference: ``dlrover/go/brain/pkg/optimizer/implementation/optalgorithm/``
— stage-specific algorithms (job create, init adjust, running, OOM
recovery) mining the datastore.  The PS-specific ones (hot-PS) have no
TPU counterpart; what carries over is the *stage* structure and the
history-driven decision style, re-targeted at slice-count selection:

- create stage: pick the initial worker (host) count and per-host memory
  from similar completed jobs' scaling curves (marginal-gain knee).
- running stage: compare this job's observed curve against history; grow
  while history says the next size still pays, shrink advice when past
  the knee.
- OOM recovery: bump memory by a factor with a cluster-wide cap
  (reference ``optimize_job_worker_create_oom_resource.go``).
"""

from dataclasses import dataclass, field
from typing import Dict

from ..common.log import logger
from .datastore import BrainDataStore

DEFAULT_MEMORY_SAFETY = 1.2  # headroom over historical peak
OOM_MEMORY_FACTOR = 1.5  # reference OOM algorithms use 1.5x-2x bumps


@dataclass
class OptimizePlan:
    """Brain's answer to one optimize query (wire-friendly)."""

    worker_num: int = 0  # 0 = no opinion
    memory_mb_per_host: float = 0.0
    reason: str = ""
    # steps/s the history predicts at worker_num (0 = unknown)
    predicted_speed: float = 0.0
    extra: Dict = field(default_factory=dict)

    def empty(self) -> bool:
        return self.worker_num <= 0 and self.memory_mb_per_host <= 0


def _knee_of_curve(
    curve: Dict[int, float], node_unit: int, max_workers: int, min_gain: float
) -> int:
    """Largest world size on ``curve`` whose marginal speedup per host is
    still ≥ ``min_gain`` of linear; the classic scaling-knee rule the
    local ThroughputScalingOptimizer applies online, applied offline to
    history here."""
    sizes = sorted(s for s in curve if s <= max_workers)
    if not sizes:
        return 0
    best = sizes[0]
    for prev, cur in zip(sizes, sizes[1:]):
        gained = curve[cur] - curve[prev]
        per_host = gained / max(1, cur - prev)
        linear_per_host = curve[prev] / prev if prev else 0.0
        if linear_per_host <= 0 or per_host >= min_gain * linear_per_host:
            best = cur
        else:
            break
    # snap to slice granularity
    if node_unit > 1:
        best = (best // node_unit) * node_unit
    return best


class JobCreateResourceAlgorithm:
    """Initial resources for a brand-new job (reference
    ``optimize_job_worker_create_resource.go``): warm-start from similar
    completed jobs; cold-start returns no opinion so the master falls
    back to its configured defaults."""

    def __init__(self, store: BrainDataStore, min_gain: float = 0.4):
        self._store = store
        self._min_gain = min_gain

    def optimize(
        self,
        model_signature: str,
        workload: str = "",
        node_unit: int = 1,
        max_workers: int = 0,
    ) -> OptimizePlan:
        history = self._store.similar_jobs(model_signature, workload)
        if not history:
            return OptimizePlan(reason="cold start: no similar job history")
        uuids = [j.job_uuid for j in history]
        curve = self._store.speed_by_world_size(uuids)
        limit = max_workers or max((j.worker_num for j in history), default=0)
        worker_num = _knee_of_curve(curve, node_unit, limit, self._min_gain)
        if worker_num <= 0:
            # history exists but carries no usable speed curve; recommend
            # the most common successful size
            sizes = sorted(j.worker_num for j in history if j.worker_num > 0)
            worker_num = sizes[len(sizes) // 2] if sizes else 0
        peak_mem = self._store.peak_memory(uuids)
        return OptimizePlan(
            worker_num=worker_num,
            memory_mb_per_host=peak_mem * DEFAULT_MEMORY_SAFETY,
            predicted_speed=curve.get(worker_num, 0.0),
            reason=f"warm start from {len(history)} similar jobs",
            extra={"speed_curve": {str(k): v for k, v in curve.items()}},
        )


class JobRunningResourceAlgorithm:
    """Adjust a running job (reference
    ``optimize_job_worker_resource.go``): combine the job's own observed
    scaling points with history from similar jobs, and recommend the
    knee. A recommendation equal to the current size means hold."""

    def __init__(self, store: BrainDataStore, min_gain: float = 0.4):
        self._store = store
        self._min_gain = min_gain

    def optimize(
        self,
        job_uuid: str,
        current_workers: int,
        node_unit: int = 1,
        max_workers: int = 0,
    ) -> OptimizePlan:
        job = self._store.get_job(job_uuid)
        if job is None:
            return OptimizePlan(reason=f"unknown job {job_uuid}")
        own_curve = self._store.speed_by_world_size([job_uuid])
        similar = self._store.similar_jobs(job.model_signature, job.workload)
        hist_curve = self._store.speed_by_world_size(
            [j.job_uuid for j in similar]
        )
        # Own observations dominate; history fills in sizes not yet tried.
        curve = dict(hist_curve)
        curve.update(own_curve)
        if not curve:
            return OptimizePlan(reason="no scaling observations yet")
        limit = max_workers or max(max(curve), current_workers)
        target = _knee_of_curve(curve, node_unit, limit, self._min_gain)
        if target <= 0 or target == current_workers:
            return OptimizePlan(
                reason=f"hold at {current_workers} (knee={target or 'n/a'})"
            )
        return OptimizePlan(
            worker_num=target,
            predicted_speed=curve.get(target, 0.0),
            reason=(
                f"scaling knee at {target} hosts "
                f"(observed {sorted(own_curve)}, history {sorted(hist_curve)})"
            ),
        )


class OomRecoveryAlgorithm:
    """Memory bump after an OOM (reference
    ``optimize_job_worker_create_oom_resource.go``): factor increase over
    the observed peak, capped by the per-host limit."""

    def __init__(self, store: BrainDataStore, memory_limit_mb: float = 0.0):
        self._store = store
        self._limit = memory_limit_mb

    def optimize(self, job_uuid: str) -> OptimizePlan:
        peak = self._store.peak_memory([job_uuid])
        if peak <= 0:
            # no usage data: nothing principled to say
            return OptimizePlan(reason="no memory observations for job")
        target = peak * OOM_MEMORY_FACTOR
        if self._limit and target > self._limit:
            if peak >= self._limit:
                logger.warning(
                    "job %s OOM at peak %.0f MB already at limit %.0f MB",
                    job_uuid,
                    peak,
                    self._limit,
                )
                return OptimizePlan(
                    reason="peak memory already at cluster limit",
                    extra={"at_limit": True},
                )
            target = self._limit
        self._store.add_event(job_uuid, "oom_recovery_plan", detail=f"{target:.0f}MB")
        return OptimizePlan(
            memory_mb_per_host=target,
            reason=f"OOM recovery: {peak:.0f} MB peak -> {target:.0f} MB",
        )
