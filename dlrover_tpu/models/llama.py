"""Llama-family decoder (RMSNorm / RoPE / GQA / SwiGLU) with optional
GShard-style mixture-of-experts blocks, TPU-first.

Second flagship model family next to :mod:`dlrover_tpu.models.gpt`
(reference parity: the reference's examples span multiple model families
— GPT, Llama fine-tunes under FSDP/DeepSpeed, e.g.
``examples/pytorch/llama2/``; the runtime must not be shaped around one
architecture). Same discipline as gpt.py: bf16 activations, fp32 params,
logical-axis annotations everywhere, no data-dependent Python control
flow, remat per block.

The MoE layer is the einsum (GShard/Mesh-TF) formulation: top-2 gating
with a static per-expert capacity, dispatch/combine as one-hot einsums —
all shapes static, so XLA turns the expert-sharded matmuls into
all-to-alls over the ``ep`` mesh axis instead of host-side routing.
"""

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import partitioning as nn_partitioning

param_with_axes = nn_partitioning.param_with_axes


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 2048
    num_layers: int = 8
    num_heads: int = 8
    num_kv_heads: int = 4  # < num_heads → grouped-query attention
    head_dim: int = 64
    embed_dim: int = 512
    mlp_dim: int = 1408  # ~8/3 * embed, rounded to a multiple of 128
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    use_remat: bool = True
    # >0: targets passed to __call__ fuse head+CE over seq chunks of
    # this size (see gpt.GPTConfig.ce_chunk — same contract/math)
    ce_chunk: int = 0
    attention_impl: str = ""  # "" → dense; flash|ring as in gpt.py
    # int8 decode KV cache with per-token per-kv-head scales (see
    # gpt.GPTConfig.kv_cache_int8 — same contract/math)
    kv_cache_int8: bool = False
    # MoE: num_experts > 0 replaces every `moe_every`-th block's MLP with
    # a top-2 expert layer (0 = dense model).
    num_experts: int = 0
    moe_every: int = 2
    expert_mlp_dim: int = 0  # 0 → mlp_dim
    capacity_factor: float = 1.25

    @property
    def moe_mlp_dim(self) -> int:
        return self.expert_mlp_dim or self.mlp_dim

    def is_moe_block(self, layer_idx: int) -> bool:
        # Every `moe_every`-th block, LAST of each group: moe_every=1
        # means every block, moe_every=2 means layers 1, 3, 5, ...
        # NOTE: this rule changed from `% moe_every == 1` (which placed
        # no MoE blocks at all for moe_every=1 and layers 1,4,7 for
        # moe_every=3). Checkpoints trained under the old rule with
        # moe_every>2 have MoE params at different layer indices; a
        # restore fails loudly with "checkpoint missing leaf
        # layers_<i>/moe/..." (engine._restore_into_template) rather
        # than mis-restoring, because leaf paths encode the layer index.
        return self.num_experts > 0 and (
            layer_idx % self.moe_every == self.moe_every - 1
        )

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        base = dict(
            vocab_size=256,
            max_seq_len=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=8,
            embed_dim=32,
            mlp_dim=64,
            use_remat=False,
        )
        base.update(overrides)
        return LlamaConfig(**base)


def _constrain(x, *axes):
    from ..parallel.sharding import with_logical_constraint

    return with_logical_constraint(x, *axes)


class RMSNorm(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        scale = param_with_axes(
            "scale",
            nn.initializers.ones,
            (x.shape[-1],),
            cfg.param_dtype,
            axes=("norm",),
        )
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + cfg.rms_eps)
        return (y * scale).astype(cfg.dtype)


def rope_tables(seq_len: int, head_dim: int, theta: float):
    """(cos, sin) [T, head_dim//2] in fp32 — computed once per trace."""
    freqs = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = jnp.outer(jnp.arange(seq_len, dtype=jnp.float32), freqs)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin):
    """Rotate pairs of channels; x is [B, T, H, Hd]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    return jnp.concatenate(
        (x1 * cos - x2 * sin, x1 * sin + x2 * cos), axis=-1
    ).astype(x.dtype)


def apply_rope_at(x, cos_table, sin_table, positions):
    """RoPE at per-row absolute positions; x [B,T,H,Hd], positions [B,T].

    The decode path's variant of :func:`apply_rope`: left-padded rows
    sit at different absolute token positions for the same cache slot,
    so the angle tables are gathered per (row, slot) instead of shared
    across the batch.
    """
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos_table[positions][:, :, None, :]  # [B, T, 1, Hd//2]
    sin = sin_table[positions][:, :, None, :]
    return jnp.concatenate(
        (x1 * cos - x2 * sin, x1 * sin + x2 * cos), axis=-1
    ).astype(x.dtype)


class LlamaAttention(nn.Module):
    """GQA causal attention with rotary embeddings."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, x, *, decode: bool = False, positions=None, kv_valid=None, cache_slots=None):
        cfg = self.config
        B, T, D = x.shape
        H, KVH, Hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        if H % KVH:
            raise ValueError(f"num_heads {H} not a multiple of kv heads {KVH}")

        wq = param_with_axes(
            "wq",
            nn.initializers.normal(0.02),
            (D, H, Hd),
            cfg.param_dtype,
            axes=("embed", "heads", "kv"),
        )
        wk = param_with_axes(
            "wk",
            nn.initializers.normal(0.02),
            (D, KVH, Hd),
            cfg.param_dtype,
            axes=("embed", "kv_heads", "kv"),
        )
        wv = param_with_axes(
            "wv",
            nn.initializers.normal(0.02),
            (D, KVH, Hd),
            cfg.param_dtype,
            axes=("embed", "kv_heads", "kv"),
        )
        wo = param_with_axes(
            "wo",
            nn.initializers.normal(0.02 / jnp.sqrt(2 * cfg.num_layers)),
            (H, Hd, D),
            cfg.param_dtype,
            axes=("heads", "kv", "embed"),
        )
        q = jnp.einsum("btd,dhk->bthk", x, wq.astype(cfg.dtype))
        k = jnp.einsum("btd,dgk->btgk", x, wk.astype(cfg.dtype))
        v = jnp.einsum("btd,dgk->btgk", x, wv.astype(cfg.dtype))

        if decode:
            # RoPE at the tokens' absolute positions (left-padded prompts
            # carry a per-row position array), then cache the SMALL
            # pre-repeat GQA k/v — the KVH-wide cache is the whole point
            # of grouped-query attention at decode time.
            from .gpt import cached_decode_attention

            cos_t, sin_t = rope_tables(
                cfg.max_seq_len, Hd, cfg.rope_theta
            )
            if positions is None:
                raise ValueError("decode=True needs absolute positions")
            q = apply_rope_at(q, cos_t, sin_t, positions)
            k = apply_rope_at(k, cos_t, sin_t, positions)
            # no repeat: the grouped contraction runs q heads
            # against the narrow KVH-wide cache instead of widening it
            # every step (int8 caches take the int8 x int8 path)
            return cached_decode_attention(
                self, cfg.max_seq_len, q, k, v, kv_valid, cache_slots,
                wo, cfg,
            )

        cos, sin = rope_tables(T, Hd, cfg.rope_theta)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        # Expand kv groups to full heads for the shared attention kernels
        # (flash/ring take equal head counts). The repeat is free under
        # XLA when the kv tensor is small (KVH << H is the GQA point).
        k = jnp.repeat(k, H // KVH, axis=2)
        v = jnp.repeat(v, H // KVH, axis=2)
        q = _constrain(q, "batch", "seq", "heads", "kv")
        k = _constrain(k, "batch", "seq", "heads", "kv")
        v = _constrain(v, "batch", "seq", "heads", "kv")

        impl = cfg.attention_impl or "dense"
        if impl == "ring":
            from ..ops.ring_attention import ring_attention_sharded
            from ..parallel.mesh import get_current_mesh

            mesh = get_current_mesh()
            if mesh is None:
                raise ValueError("attention_impl='ring' needs current_mesh")
            out = ring_attention_sharded(q, k, v, mesh, causal=True)
        elif impl == "flash":
            from ..ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, causal=True)
        elif impl == "dense":
            scale = 1.0 / jnp.sqrt(Hd).astype(cfg.dtype)
            logits = jnp.einsum("bqhk,bshk->bhqs", q, k) * scale
            mask = jnp.tril(jnp.ones((T, T), dtype=bool))
            logits = jnp.where(mask[None, None, :, :], logits, -1e9)
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
                cfg.dtype
            )
            out = jnp.einsum("bhqs,bshk->bqhk", probs, v)
        else:
            raise ValueError(f"unknown attention_impl {impl!r}")
        out = _constrain(out, "batch", "seq", "heads", "kv")
        y = jnp.einsum("bqhk,hkd->bqd", out, wo.astype(cfg.dtype))
        return _constrain(y, "batch", "seq", "embed")


class SwiGluMlp(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        D, F = cfg.embed_dim, cfg.mlp_dim
        w_gate = param_with_axes(
            "w_gate",
            nn.initializers.normal(0.02),
            (D, F),
            cfg.param_dtype,
            axes=("embed", "mlp"),
        )
        w_up = param_with_axes(
            "w_up",
            nn.initializers.normal(0.02),
            (D, F),
            cfg.param_dtype,
            axes=("embed", "mlp"),
        )
        w_down = param_with_axes(
            "w_down",
            nn.initializers.normal(0.02 / jnp.sqrt(2 * cfg.num_layers)),
            (F, D),
            cfg.param_dtype,
            axes=("mlp", "embed"),
        )
        h = jax.nn.silu(jnp.dot(x, w_gate.astype(cfg.dtype))) * jnp.dot(
            x, w_up.astype(cfg.dtype)
        )
        h = _constrain(h, "batch", "seq", "mlp")
        y = jnp.dot(h, w_down.astype(cfg.dtype))
        return _constrain(y, "batch", "seq", "embed")


class MoeMlp(nn.Module):
    """Top-2 expert-parallel SwiGLU layer (GShard einsum formulation).

    Static shapes throughout: gating produces a [B,S,E,C] dispatch mask
    via one-hot position-in-expert bookkeeping; dispatch and combine are
    einsums, so the expert-sharded matmuls compile to a2a + local matmul
    over the ``ep`` axis — no host routing, no dynamic shapes.
    Auxiliary load-balance loss is stored via ``self.sow`` under
    ``("losses", "moe_aux")``.
    """

    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        B, S, D = x.shape
        E = cfg.num_experts
        F = cfg.moe_mlp_dim
        # capacity: tokens each expert may accept from each batch row
        C = max(1, int(cfg.capacity_factor * 2 * S / E))

        w_router = param_with_axes(
            "w_router",
            nn.initializers.normal(0.02),
            (D, E),
            cfg.param_dtype,
            axes=("embed", None),
        )
        w_gate = param_with_axes(
            "w_gate",
            nn.initializers.normal(0.02),
            (E, D, F),
            cfg.param_dtype,
            axes=("expert", "embed", "expert_mlp"),
        )
        w_up = param_with_axes(
            "w_up",
            nn.initializers.normal(0.02),
            (E, D, F),
            cfg.param_dtype,
            axes=("expert", "embed", "expert_mlp"),
        )
        w_down = param_with_axes(
            "w_down",
            nn.initializers.normal(0.02 / jnp.sqrt(2 * cfg.num_layers)),
            (E, F, D),
            cfg.param_dtype,
            axes=("expert", "expert_mlp", "embed"),
        )

        # -- top-2 gating (fp32 for a stable softmax/argmax) --------------
        logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32), w_router)
        probs = jax.nn.softmax(logits, axis=-1)
        gate1 = jnp.argmax(probs, axis=-1)  # [B,S]
        p1 = jnp.take_along_axis(probs, gate1[..., None], axis=-1)[..., 0]
        masked = probs * (1.0 - jax.nn.one_hot(gate1, E))
        gate2 = jnp.argmax(masked, axis=-1)
        p2 = jnp.take_along_axis(masked, gate2[..., None], axis=-1)[..., 0]
        denom = jnp.maximum(p1 + p2, 1e-9)
        p1, p2 = p1 / denom, p2 / denom

        # load-balance aux loss (GShard eq.4): mean gate prob * mean
        # assignment fraction per expert, scaled by E
        me = jnp.mean(probs, axis=(0, 1))
        ce = jnp.mean(jax.nn.one_hot(gate1, E), axis=(0, 1))
        self.sow("losses", "moe_aux", E * jnp.sum(me * ce))

        def dispatch_mask(gate, prio_offset):
            """[B,S,E,C] one-hot of (expert, position-within-capacity)."""
            onehot = jax.nn.one_hot(gate, E)  # [B,S,E]
            pos = jnp.cumsum(onehot, axis=1) - 1 + prio_offset  # [B,S,E]
            keep = (pos < C) & (onehot > 0)
            pos_oh = jax.nn.one_hot(pos, C)  # [B,S,E,C]
            return pos_oh * keep[..., None], pos

        mask1, pos1 = dispatch_mask(gate1, 0.0)
        # second choices queue behind every first-choice token
        count1 = jnp.sum(jax.nn.one_hot(gate1, E), axis=1, keepdims=True)
        mask2, _ = dispatch_mask(gate2, count1)

        combine = (
            mask1 * p1[..., None, None] + mask2 * p2[..., None, None]
        ).astype(cfg.dtype)  # [B,S,E,C]
        dispatch = (mask1 + mask2).astype(cfg.dtype)

        # -- dispatch -> expert compute -> combine ------------------------
        xe = jnp.einsum("bsec,bsd->becd", dispatch, x)  # [B,E,C,D]
        xe = _constrain(xe, "batch", "expert", None, "embed")
        h = jax.nn.silu(
            jnp.einsum("becd,edf->becf", xe, w_gate.astype(cfg.dtype))
        ) * jnp.einsum("becd,edf->becf", xe, w_up.astype(cfg.dtype))
        h = _constrain(h, "batch", "expert", None, "expert_mlp")
        ye = jnp.einsum("becf,efd->becd", h, w_down.astype(cfg.dtype))
        y = jnp.einsum("bsec,becd->bsd", combine, ye)
        return _constrain(y, "batch", "seq", "embed")


class LlamaBlock(nn.Module):
    config: LlamaConfig
    layer_idx: int = 0

    @nn.compact
    def __call__(self, x, *, decode: bool = False, positions=None, kv_valid=None, cache_slots=None):
        cfg = self.config
        x = x + LlamaAttention(cfg)(
            RMSNorm(cfg)(x),
            decode=decode,
            positions=positions,
            kv_valid=kv_valid,
            cache_slots=cache_slots,
        )
        mlp = MoeMlp(cfg) if cfg.is_moe_block(self.layer_idx) else SwiGluMlp(cfg)
        x = x + mlp(RMSNorm(cfg)(x))
        return x


class Llama(nn.Module):
    """``__call__(tokens[B,T]) -> logits[B,T,V]``.

    ``targets`` given → per-token losses ``[B, T]`` through the fused
    chunked-CE path (gpt.py contract; pair with
    :func:`dlrover_tpu.models.gpt.token_loss_mean`).
    """

    config: LlamaConfig

    @nn.compact
    def __call__(
        self,
        tokens,
        *,
        targets=None,
        decode: bool = False,
        positions=None,
        kv_valid=None,
        cache_slots=None,
    ):
        cfg = self.config
        B, T = tokens.shape
        wte = param_with_axes(
            "wte",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.embed_dim),
            cfg.param_dtype,
            axes=("vocab", "embed"),
        )
        x = wte.astype(cfg.dtype)[tokens]
        x = _constrain(x, "batch", "seq", "embed")
        # decode bypasses remat: no backward pass, and the decode kwargs
        # must not cross jax.checkpoint (it would trace the bool).
        if cfg.use_remat and not decode:
            block = nn.remat(
                LlamaBlock,
                prevent_cse=False,
                policy=jax.checkpoint_policies.nothing_saveable,
                static_argnums=(),
            )
            for i in range(cfg.num_layers):
                x = block(cfg, layer_idx=i, name=f"block_{i}")(x)
        else:
            for i in range(cfg.num_layers):
                x = LlamaBlock(cfg, layer_idx=i, name=f"block_{i}")(
                    x,
                    decode=decode,
                    positions=positions,
                    kv_valid=kv_valid,
                    cache_slots=cache_slots,
                )
        x = RMSNorm(cfg, name="norm_f")(x)
        w_lm = param_with_axes(
            "lm_head",
            nn.initializers.normal(0.02),
            (cfg.embed_dim, cfg.vocab_size),
            cfg.param_dtype,
            axes=("embed", "vocab"),
        )
        if targets is not None:
            from .gpt import _chunked_token_ce

            return _chunked_token_ce(
                x,
                w_lm.astype(cfg.dtype),
                targets,
                cfg.ce_chunk or T,
                vocab_first=False,
            )
        logits = jnp.dot(x, w_lm.astype(cfg.dtype))
        return _constrain(logits, "batch", "seq", "vocab")


def llama_loss(model_vars_or_logits, targets=None, aux_weight: float = 0.01):
    """CE loss; when applied through ``apply(..., mutable=["losses"])`` the
    caller adds the sowed MoE aux terms — this helper covers the plain
    logits path used by the generic train step."""
    from .gpt import cross_entropy_loss

    return cross_entropy_loss(model_vars_or_logits, targets)
