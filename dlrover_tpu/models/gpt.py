"""GPT-style decoder-only transformer, TPU-first.

Flagship model family for the runtime (the reference's headline workloads
are GPT-2/GLM elastic jobs — e.g. ``examples/pytorch/gpt``). Written for
the MXU: bf16 activations, fp32 params/optimizer, matmul-heavy blocks,
logical-axis annotations everywhere so the same module runs 1-chip or
pjit over any dp/fsdp/tp/sp mesh. No data-dependent Python control flow —
everything traces once.
"""

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import partitioning as nn_partitioning

param_with_axes = nn_partitioning.param_with_axes


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304  # padded to a multiple of 128 for the MXU
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    head_dim: int = 64
    embed_dim: int = 768
    mlp_ratio: int = 4
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    use_remat: bool = True  # jax.checkpoint each block: HBM for FLOPs
    # Remat aggressiveness when use_remat: "nothing" recomputes the
    # whole block in backward (min HBM, ~1 extra fwd of FLOPs); "dots"
    # saves matmul outputs and recomputes only elementwise ops (middle
    # ground — the MXU work is NOT redone, only VPU ops are). With
    # fused-CE freeing the logits HBM, "dots" (or use_remat=False) can
    # buy back most of the remat FLOPs at the headline batch.
    remat_policy: str = "nothing"  # "nothing" | "dots"
    # >0: when targets are passed to __call__, compute per-token CE
    # inside the model over seq chunks of this size — the [B,T,V] fp32
    # logits (the HBM ceiling: 6.6 GB at bs=32/seq=1024/vocab=50k)
    # never materialize whole, and backward recomputes each chunk's
    # logits (jax.checkpoint), unlocking larger batches.
    ce_chunk: int = 0
    use_flash_attention: bool = False  # pallas kernel from dlrover_tpu.ops
    # "dense" | "flash" (pallas kernel, single-device/data-parallel) |
    # "ring" (sp-sharded exact attention via shard_map; needs
    # parallel.mesh.current_mesh to be active)
    attention_impl: str = ""
    tie_embeddings: bool = True
    # int8 decode KV cache: values stored int8 with a per-token
    # per-kv-head scale (amax/127), dequantized in-register on the
    # attention read. Decode attention is HBM-bound — halving the
    # cache bytes is the decode-throughput lever (and doubles the
    # batch a given HBM budget serves). The whole decode-mode path
    # (prefill AND incremental steps) attends over the quantized
    # cache; only the training forward (no cache) is untouched.
    kv_cache_int8: bool = False

    def resolved_attention_impl(self) -> str:
        if self.attention_impl:
            return self.attention_impl
        return "flash" if self.use_flash_attention else "dense"

    @property
    def mlp_dim(self) -> int:
        return self.mlp_ratio * self.embed_dim

    @staticmethod
    def tiny() -> "GPTConfig":
        return GPTConfig(
            vocab_size=256,
            max_seq_len=128,
            num_layers=2,
            num_heads=4,
            head_dim=8,
            embed_dim=32,
            use_remat=False,
        )

    @staticmethod
    def gpt2_small() -> "GPTConfig":
        return GPTConfig(num_layers=12, num_heads=12, head_dim=64, embed_dim=768)

    @staticmethod
    def gpt2_xl() -> "GPTConfig":
        return GPTConfig(num_layers=48, num_heads=25, head_dim=64, embed_dim=1600)


def _constrain(x, *axes):
    from ..parallel.sharding import with_logical_constraint

    return with_logical_constraint(x, *axes)


def _quant_kv(x):
    """Per-token per-kv-head symmetric int8: [B, T, KVH, Hd] →
    (int8 values, f32 scales [B, T, KVH])."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / 127.0, 1e-8)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _dequant_kv(q, scale, dtype):
    """Inverse of :func:`_quant_kv` — round-trip/debug helper only.

    NOT used by the attention path: dequantizing the cache before the
    einsums materializes the wide bf16 tensor to HBM (XLA does not
    fuse converts into dot operands), which measured 0.81x the bf16
    cache on silicon. The production path keeps operands int8 end to
    end — see :func:`_masked_attention_int8`."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def _update_decode_cache(module, max_len, k, v, kv_valid, cache_slots=None):
    """Write this call's K/V into the module's decode cache; return the
    full cache plus the attention mask for the queries of this call.

    Incremental decoding the flax way (``"cache"`` variable collection),
    shared by GPT and Llama attention. The DEFAULT path follows the
    engine convention (:mod:`dlrover_tpu.models.generation`): LEFT-
    padded prompts, so every batch row shares one static write offset
    and the cache update is a single ``dynamic_update_slice`` — the
    shape XLA tiles well for multi-token prefill writes. ``kv_valid``
    [B, max_len] marks which cache slots hold real tokens (False =
    left-pad); queries at local position i attend valid slots s with
    s <= offset + i.

    ``cache_slots`` int32 switches to PER-ROW write slots: ``[B]`` for
    single-token decode (the continuous-batching engine's per-row
    cache layout: every request advances its own frontier, so
    admissions never leave frontier-wide holes and the stream never
    compacts) or ``[B, T]`` for a T-token window written at per-row
    slots (the in-scheduler speculative verify). The write is a
    B(×T)-row scatter — tiny next to the attention pass that reads the
    whole cache anyway — and the causal mask keys on each query's own
    slot (returned mask is [B, T, max_len]). Requires an explicit
    ``kv_valid``.

    Reference RL rollouts lean on vLLM for this
    (examples/unified/rl/openrlhf/ppo/main.py:26-60); here generation is
    a first-class jit-compiled path over the training parameters.
    """
    B, T = k.shape[0], k.shape[1]
    int8_cache = bool(getattr(module.config, "kv_cache_int8", False))
    if int8_cache:
        k_store, k_scale = _quant_kv(k)
        v_store, v_scale = _quant_kv(v)
        store_dtype = jnp.int8
    else:
        k_store, v_store = k, v
        k_scale = v_scale = None
        store_dtype = k.dtype
    ck = module.variable(
        "cache", "k", jnp.zeros, (B, max_len) + k.shape[2:], store_dtype
    )
    cv = module.variable(
        "cache", "v", jnp.zeros, (B, max_len) + v.shape[2:], store_dtype
    )
    if int8_cache:
        csk = module.variable(
            "cache", "k_scale", jnp.zeros, (B, max_len) + k.shape[2:3],
            jnp.float32,
        )
        csv = module.variable(
            "cache", "v_scale", jnp.zeros, (B, max_len) + v.shape[2:3],
            jnp.float32,
        )
    cidx = module.variable(
        "cache", "index", lambda: jnp.zeros((), jnp.int32)
    )

    def _read(mask):
        """bf16 cache → (k, v, mask); int8 cache → the RAW int8
        tensors + scales (k8, ks, v8, vs, mask). Never dequantize here:
        a materialized [B, max_len, KVH, Hd] bf16 tensor costs more
        HBM traffic than the narrow cache saves (measured 0.81x on
        silicon) — the int8 attention path consumes the int8 operands
        directly (see _masked_attention_int8)."""
        if not int8_cache:
            return ck.value, cv.value, mask
        return ck.value, csk.value, cv.value, csv.value, mask

    if cache_slots is not None:
        if kv_valid is None:
            raise ValueError("cache_slots mode needs explicit kv_valid")
        # [B] (single-token decode) or [B, T] (a T-token window written
        # at per-row slots — the in-engine speculative verify)
        slots_bt = (
            cache_slots[:, None] if cache_slots.ndim == 1 else cache_slots
        )
        if slots_bt.shape != (B, T):
            raise ValueError(
                f"cache_slots {cache_slots.shape} incompatible with "
                f"tokens [B={B}, T={T}]"
            )
        rows = jnp.arange(B)[:, None]
        ck.value = ck.value.at[rows, slots_bt].set(k_store)
        cv.value = cv.value.at[rows, slots_bt].set(v_store)
        if int8_cache:
            csk.value = csk.value.at[rows, slots_bt].set(k_scale)
            csv.value = csv.value.at[rows, slots_bt].set(v_scale)
        # cidx (the shared frontier) is meaningless per-row; leave it.
        # causal per (row, query): query written at slot slots_bt[b, t]
        # sees valid slots <= its own
        causal = (
            jnp.arange(max_len)[None, None, :] <= slots_bt[:, :, None]
        )  # [B, T, max_len]
        mask = kv_valid[:, None, :] & causal  # [B, T, max_len]
        return _read(mask)
    offset = cidx.value
    ck.value = jax.lax.dynamic_update_slice(
        ck.value, k_store, (0, offset, 0, 0)
    )
    cv.value = jax.lax.dynamic_update_slice(
        cv.value, v_store, (0, offset, 0, 0)
    )
    if int8_cache:
        csk.value = jax.lax.dynamic_update_slice(
            csk.value, k_scale, (0, offset, 0)
        )
        csv.value = jax.lax.dynamic_update_slice(
            csv.value, v_scale, (0, offset, 0)
        )
    cidx.value = offset + T
    if kv_valid is None:
        # all slots up to the write frontier are real tokens
        kv_valid = jnp.arange(max_len)[None, :] < (offset + T)
        kv_valid = jnp.broadcast_to(kv_valid, (B, max_len))
    # causal-by-slot: query at absolute slot offset+i sees slots <= it
    slot_q = offset + jnp.arange(T)  # [T]
    causal = jnp.arange(max_len)[None, :] <= slot_q[:, None]  # [T, max_len]
    mask = kv_valid[:, None, :] & causal[None, :, :]  # [B, T, max_len]
    return _read(mask)


def cached_decode_attention(
    module, max_len, q, k, v, kv_valid, cache_slots, wo, cfg
):
    """Update the module's decode cache with this call's K/V, then run
    attention in the cache's STORAGE precision: the bf16 cache feeds
    the plain masked einsum; the int8 cache feeds the int8 x int8 MXU
    path. The single decode-attention entry point for GPT and Llama.
    """
    res = _update_decode_cache(module, max_len, k, v, kv_valid, cache_slots)
    if len(res) == 3:
        k_full, v_full, mask = res
        return _masked_attention(q, k_full, v_full, mask, wo, cfg)
    k8, ks, v8, vs, mask = res
    return _masked_attention_int8(q, k8, ks, v8, vs, mask, wo, cfg)


def _masked_attention_int8(q, k8, ks, v8, vs, mask, wo, cfg):
    """Decode attention computed IN int8 over the quantized cache.

    The first int8 attempt dequantized the cache to bf16 before the
    einsums; XLA materialized the [B, max_len, KVH, Hd] bf16 tensor to
    HBM, so the step paid int8-read + bf16-write + bf16-read — 24%
    SLOWER than the bf16 cache on silicon (SILICON_r05_1785579811:
    decode_int8_vs_bf16 0.809). The fix is to never materialize a wide
    dequantized tensor: quantize the QUERY too and run int8 x int8
    MXU dots with the scales factored out of the contractions —

    - QK: per-(token, head) q scales and per-(token, kv-head) k scales
      both factor OUT of the dot (they are constant along the
      contracted Hd axis): logits = (q8 . k8)_i32 * qs * ks.
    - PV: the v scale varies along the CONTRACTED slot axis, so it
      cannot factor out; instead fold it into the probs (a [.., S]
      tensor, tiny next to the cache), re-quantize the folded weights
      per row, and run int8 x int8 again.

    HBM traffic per step: the int8 cache + scales, read once, directly
    as dot operands.
    """
    Hd = q.shape[-1]
    H, KVH = q.shape[2], k8.shape[2]
    B, T = q.shape[:2]
    G = H // KVH
    qg = q.reshape(B, T, KVH, G, Hd)
    q8, qs = _quant_kv(qg)  # scales [B, T, KVH, G]
    logits = jnp.einsum(
        "btgck,bsgk->bgcts", q8, k8, preferred_element_type=jnp.int32
    ).astype(jnp.float32)
    logits = logits * jnp.transpose(qs, (0, 2, 3, 1))[..., None]
    logits = logits * jnp.transpose(ks, (0, 2, 1))[:, :, None, None, :]
    logits = logits / jnp.sqrt(jnp.float32(Hd))
    logits = jnp.where(mask[:, None, None, :, :], logits, -1e9)
    probs = jax.nn.softmax(logits, axis=-1)  # fp32
    w = probs * jnp.transpose(vs, (0, 2, 1))[:, :, None, None, :]
    wscale = jnp.maximum(jnp.max(jnp.abs(w), axis=-1) / 127.0, 1e-12)
    w8 = jnp.clip(jnp.round(w / wscale[..., None]), -127, 127).astype(
        jnp.int8
    )
    out = jnp.einsum(
        "bgcts,bsgk->btgck", w8, v8, preferred_element_type=jnp.int32
    ).astype(jnp.float32)
    out = out * jnp.transpose(wscale, (0, 3, 1, 2))[..., None]
    out = out.reshape(B, T, H, Hd).astype(cfg.dtype)
    y = jnp.einsum("bqhk,hkd->bqd", out, wo.astype(cfg.dtype))
    return _constrain(y, "batch", "seq", "embed")


def _masked_attention(q, k, v, mask, wo, cfg):
    """Dense attention over the full decode cache with an explicit mask.

    Decode is HBM-bound gather work, not MXU work — a plain einsum over
    the cache is the right TPU shape (the flash kernel's tiling pays off
    only on long training sequences). When the cache is GQA-narrow
    (k/v head count < q head count) the contraction is grouped instead
    of widening the cache: re-materializing [B, max_len, H, Hd] every
    single-token step would multiply exactly the HBM traffic the narrow
    cache exists to avoid.
    """
    Hd = q.shape[-1]
    H, KVH = q.shape[2], k.shape[2]
    scale = 1.0 / jnp.sqrt(Hd).astype(q.dtype)
    if H != KVH:
        B, T = q.shape[:2]
        G = H // KVH
        qg = q.reshape(B, T, KVH, G, Hd)
        logits = jnp.einsum("btgck,bsgk->bgcts", qg, k) * scale
        logits = jnp.where(mask[:, None, None, :, :], logits, -1e9)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
            q.dtype
        )
        out = jnp.einsum("bgcts,bsgk->btgck", probs, v).reshape(B, T, H, Hd)
    else:
        logits = jnp.einsum("bqhk,bshk->bhqs", q, k) * scale
        logits = jnp.where(mask[:, None, :, :], logits, -1e9)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
            q.dtype
        )
        out = jnp.einsum("bhqs,bshk->bqhk", probs, v)
    y = jnp.einsum("bqhk,hkd->bqd", out, wo.astype(cfg.dtype))
    return _constrain(y, "batch", "seq", "embed")


class CausalSelfAttention(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(
        self,
        x,
        *,
        deterministic: bool = True,
        decode: bool = False,
        kv_valid=None,
        cache_slots=None,
    ):
        cfg = self.config
        B, T, D = x.shape
        H, Hd = cfg.num_heads, cfg.head_dim

        wqkv = param_with_axes(
            "wqkv",
            nn.initializers.normal(0.02),
            (D, 3, H, Hd),
            cfg.param_dtype,
            axes=("embed", None, "heads", "kv"),
        )
        wo = param_with_axes(
            "wo",
            nn.initializers.normal(0.02 / jnp.sqrt(2 * cfg.num_layers)),
            (H, Hd, D),
            cfg.param_dtype,
            axes=("heads", "kv", "embed"),
        )
        qkv = jnp.einsum("btd,dchk->cbthk", x, wqkv.astype(cfg.dtype))
        q, k, v = qkv[0], qkv[1], qkv[2]
        q = _constrain(q, "batch", "seq", "heads", "kv")
        k = _constrain(k, "batch", "seq", "heads", "kv")
        v = _constrain(v, "batch", "seq", "heads", "kv")

        if decode:
            return cached_decode_attention(
                self, cfg.max_seq_len, q, k, v, kv_valid, cache_slots,
                wo, cfg,
            )

        impl = cfg.resolved_attention_impl()
        if impl not in ("dense", "flash", "ring"):
            raise ValueError(
                f"unknown attention_impl {impl!r}; expected dense|flash|ring"
            )
        if impl == "ring":
            from ..ops.ring_attention import ring_attention_sharded
            from ..parallel.mesh import get_current_mesh

            mesh = get_current_mesh()
            if mesh is None:
                raise ValueError(
                    "attention_impl='ring' needs parallel.mesh.current_mesh "
                    "active around model application"
                )
            out = ring_attention_sharded(q, k, v, mesh, causal=True)
        elif impl == "flash":
            from ..ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, causal=True)
        else:
            scale = 1.0 / jnp.sqrt(Hd).astype(cfg.dtype)
            logits = jnp.einsum("bqhk,bshk->bhqs", q, k) * scale
            mask = jnp.tril(jnp.ones((T, T), dtype=bool))
            logits = jnp.where(mask[None, None, :, :], logits, -1e9)
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(cfg.dtype)
            out = jnp.einsum("bhqs,bshk->bqhk", probs, v)
        out = _constrain(out, "batch", "seq", "heads", "kv")
        y = jnp.einsum("bqhk,hkd->bqd", out, wo.astype(cfg.dtype))
        return _constrain(y, "batch", "seq", "embed")


class Mlp(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        D, F = cfg.embed_dim, cfg.mlp_dim
        w1 = param_with_axes(
            "w1",
            nn.initializers.normal(0.02),
            (D, F),
            cfg.param_dtype,
            axes=("embed", "mlp"),
        )
        b1 = param_with_axes(
            "b1", nn.initializers.zeros, (F,), cfg.param_dtype, axes=("mlp",)
        )
        w2 = param_with_axes(
            "w2",
            nn.initializers.normal(0.02 / jnp.sqrt(2 * cfg.num_layers)),
            (F, D),
            cfg.param_dtype,
            axes=("mlp", "embed"),
        )
        b2 = param_with_axes(
            "b2", nn.initializers.zeros, (D,), cfg.param_dtype, axes=("embed",)
        )
        h = jnp.dot(x, w1.astype(cfg.dtype)) + b1.astype(cfg.dtype)
        h = _constrain(h, "batch", "seq", "mlp")
        h = jax.nn.gelu(h)
        y = jnp.dot(h, w2.astype(cfg.dtype)) + b2.astype(cfg.dtype)
        return _constrain(y, "batch", "seq", "embed")


class LayerNorm(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        scale = param_with_axes(
            "scale", nn.initializers.ones, (x.shape[-1],), cfg.param_dtype, axes=("norm",)
        )
        bias = param_with_axes(
            "bias", nn.initializers.zeros, (x.shape[-1],), cfg.param_dtype, axes=("norm",)
        )
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + 1e-5)
        return (y * scale + bias).astype(cfg.dtype)


class Block(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(
        self,
        x,
        *,
        deterministic: bool = True,
        decode: bool = False,
        kv_valid=None,
        cache_slots=None,
    ):
        x = x + CausalSelfAttention(self.config)(
            LayerNorm(self.config)(x),
            deterministic=deterministic,
            decode=decode,
            kv_valid=kv_valid,
            cache_slots=cache_slots,
        )
        x = x + Mlp(self.config)(LayerNorm(self.config)(x))
        return x


class GPT(nn.Module):
    """Decoder-only LM. ``__call__(tokens[B,T]) -> logits[B,T,V]``.

    With ``targets`` given the return value is per-token losses
    ``[B, T]`` (fp32, 0.0 at ``ignore_index`` positions) — pair with
    :func:`token_loss_mean` as the train-step loss. ``cfg.ce_chunk``
    > 0 additionally fuses head + CE chunk-by-chunk so the full logits
    tensor never exists (0 = one whole-sequence chunk).
    """

    config: GPTConfig

    @nn.compact
    def __call__(
        self,
        tokens,
        *,
        targets=None,
        deterministic: bool = True,
        decode: bool = False,
        positions=None,
        kv_valid=None,
        cache_slots=None,
    ):
        cfg = self.config
        B, T = tokens.shape
        wte = param_with_axes(
            "wte",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.embed_dim),
            cfg.param_dtype,
            axes=("vocab", "embed"),
        )
        wpe = param_with_axes(
            "wpe",
            nn.initializers.normal(0.01),
            (cfg.max_seq_len, cfg.embed_dim),
            cfg.param_dtype,
            axes=(None, "embed"),
        )
        if positions is None:
            if decode:
                raise ValueError("decode=True needs absolute positions")
            pos_emb = wpe.astype(cfg.dtype)[None, :T]
        else:
            pos_emb = wpe.astype(cfg.dtype)[positions]  # [B, T, D]
        x = wte.astype(cfg.dtype)[tokens] + pos_emb
        x = _constrain(x, "batch", "seq", "embed")

        # remat trades FLOPs for HBM in training; during incremental
        # decode there is no backward pass and the cache collection must
        # stay plainly mutable, so bypass it. The decode kwargs must not
        # cross nn.remat either — jax.checkpoint would trace the bool.
        if cfg.use_remat and not decode:
            policies = {
                "nothing": jax.checkpoint_policies.nothing_saveable,
                "dots": jax.checkpoint_policies.dots_saveable,
            }
            if cfg.remat_policy not in policies:
                raise ValueError(
                    f"unknown remat_policy {cfg.remat_policy!r}; "
                    f"expected one of {sorted(policies)}"
                )
            policy = policies[cfg.remat_policy]
            block = nn.remat(
                Block,
                prevent_cse=False,
                policy=policy,
            )
            for i in range(cfg.num_layers):
                x = block(cfg, name=f"block_{i}")(
                    x, deterministic=deterministic
                )
        else:
            for i in range(cfg.num_layers):
                x = Block(cfg, name=f"block_{i}")(
                    x,
                    deterministic=deterministic,
                    decode=decode,
                    kv_valid=kv_valid,
                    cache_slots=cache_slots,
                )
        x = LayerNorm(cfg, name="ln_f")(x)

        if cfg.tie_embeddings:
            w_head = wte.astype(cfg.dtype)  # [V, D]
            vocab_first = True
        else:
            w_head = param_with_axes(
                "lm_head",
                nn.initializers.normal(0.02),
                (cfg.embed_dim, cfg.vocab_size),
                cfg.param_dtype,
                axes=("embed", "vocab"),
            ).astype(cfg.dtype)  # [D, V]
            vocab_first = False

        if targets is not None:
            # uniform contract: targets given -> per-token losses.
            # ce_chunk=0 degenerates to one whole-sequence chunk (the
            # dense math, just routed through the fused path) so the
            # pairing with token_loss_mean can never be silently wrong.
            return _chunked_token_ce(
                x, w_head, targets, cfg.ce_chunk or T, vocab_first
            )

        if vocab_first:
            logits = jnp.einsum("btd,vd->btv", x, w_head)
        else:
            logits = jnp.dot(x, w_head)
        return _constrain(logits, "batch", "seq", "vocab")


def _token_ce(logits, targets, ignore_index: int = -1):
    """Masked per-token CE in fp32: [..., V] logits -> [...] losses
    (0.0 at ignored positions). Single source of the CE math for both
    the dense loss and the chunked fused path."""
    logits = logits.astype(jnp.float32)
    mask = targets != ignore_index
    safe_targets = jnp.where(mask, targets, 0)
    logps = jax.nn.log_softmax(logits, axis=-1)
    token_loss = -jnp.take_along_axis(
        logps, safe_targets[..., None], axis=-1
    )[..., 0]
    return jnp.where(mask, token_loss, 0.0)


def cross_entropy_loss(logits, targets, ignore_index: int = -1):
    """Mean next-token CE in fp32 (MXU-friendly: one log_softmax fusion)."""
    return token_loss_mean(
        _token_ce(logits, targets, ignore_index), targets, ignore_index
    )


def _chunked_token_ce(
    x, w_head, targets, chunk: int, vocab_first: bool, ignore_index: int = -1
):
    """Per-token CE fused with the LM head, seq-chunked: [B,T,D] -> [B,T].

    The fp32 logits for the full sequence are the HBM ceiling of a
    small-model/large-vocab step (bs=32 x 1024 x 50304 fp32 = 6.6 GB).
    A ``lax.scan`` over T/chunk slices computes each chunk's logits,
    reduces them to token losses, and — with ``jax.checkpoint`` on the
    body — recomputes them in backward instead of storing them, so live
    logits are [B, chunk, V] at any moment. Costs one extra head matmul
    in backward; buys the batch sizes the dense path cannot fit.
    """
    B, T, D = x.shape
    if T % chunk:
        raise ValueError(f"seq len {T} not divisible by ce_chunk {chunk}")
    C = T // chunk
    xc = jnp.swapaxes(x.reshape(B, C, chunk, D), 0, 1)  # [C, B, c, D]
    tc = jnp.swapaxes(targets.reshape(B, C, chunk), 0, 1)  # [C, B, c]

    @jax.checkpoint
    def body(carry, xs):
        xb, tb = xs
        if vocab_first:  # w_head [V, D] (tied embeddings)
            logits = jnp.einsum("bcd,vd->bcv", xb, w_head)
        else:  # w_head [D, V]
            logits = jnp.einsum("bcd,dv->bcv", xb, w_head)
        return carry, _token_ce(logits, tb, ignore_index)

    _, tls = jax.lax.scan(body, (), (xc, tc))  # [C, B, c]
    return jnp.swapaxes(tls, 0, 1).reshape(B, T)


def token_loss_mean(token_losses, targets, ignore_index: int = -1):
    """Loss head for the fused-CE path: mean of model-computed per-token
    losses over non-ignored positions (the model already zeroed them)."""
    if token_losses.ndim != targets.ndim:
        raise ValueError(
            f"token_loss_mean expects per-token losses shaped like targets "
            f"{targets.shape}, got {token_losses.shape} — a [B,T,V] rank "
            f"means the model ran with ce_chunk=0 (raw logits); pair that "
            f"with cross_entropy_loss instead"
        )
    mask = targets != ignore_index
    return token_losses.sum() / jnp.maximum(mask.sum(), 1)
