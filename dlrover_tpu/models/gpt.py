"""GPT-style decoder-only transformer, TPU-first.

Flagship model family for the runtime (the reference's headline workloads
are GPT-2/GLM elastic jobs — e.g. ``examples/pytorch/gpt``). Written for
the MXU: bf16 activations, fp32 params/optimizer, matmul-heavy blocks,
logical-axis annotations everywhere so the same module runs 1-chip or
pjit over any dp/fsdp/tp/sp mesh. No data-dependent Python control flow —
everything traces once.
"""

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import partitioning as nn_partitioning

param_with_axes = nn_partitioning.param_with_axes


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304  # padded to a multiple of 128 for the MXU
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    head_dim: int = 64
    embed_dim: int = 768
    mlp_ratio: int = 4
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    use_remat: bool = True  # jax.checkpoint each block: HBM for FLOPs
    use_flash_attention: bool = False  # pallas kernel from dlrover_tpu.ops
    # "dense" | "flash" (pallas kernel, single-device/data-parallel) |
    # "ring" (sp-sharded exact attention via shard_map; needs
    # parallel.mesh.current_mesh to be active)
    attention_impl: str = ""
    tie_embeddings: bool = True

    def resolved_attention_impl(self) -> str:
        if self.attention_impl:
            return self.attention_impl
        return "flash" if self.use_flash_attention else "dense"

    @property
    def mlp_dim(self) -> int:
        return self.mlp_ratio * self.embed_dim

    @staticmethod
    def tiny() -> "GPTConfig":
        return GPTConfig(
            vocab_size=256,
            max_seq_len=128,
            num_layers=2,
            num_heads=4,
            head_dim=8,
            embed_dim=32,
            use_remat=False,
        )

    @staticmethod
    def gpt2_small() -> "GPTConfig":
        return GPTConfig(num_layers=12, num_heads=12, head_dim=64, embed_dim=768)

    @staticmethod
    def gpt2_xl() -> "GPTConfig":
        return GPTConfig(num_layers=48, num_heads=25, head_dim=64, embed_dim=1600)


def _constrain(x, *axes):
    from ..parallel.sharding import with_logical_constraint

    return with_logical_constraint(x, *axes)


class CausalSelfAttention(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        cfg = self.config
        B, T, D = x.shape
        H, Hd = cfg.num_heads, cfg.head_dim

        wqkv = param_with_axes(
            "wqkv",
            nn.initializers.normal(0.02),
            (D, 3, H, Hd),
            cfg.param_dtype,
            axes=("embed", None, "heads", "kv"),
        )
        wo = param_with_axes(
            "wo",
            nn.initializers.normal(0.02 / jnp.sqrt(2 * cfg.num_layers)),
            (H, Hd, D),
            cfg.param_dtype,
            axes=("heads", "kv", "embed"),
        )
        qkv = jnp.einsum("btd,dchk->cbthk", x, wqkv.astype(cfg.dtype))
        q, k, v = qkv[0], qkv[1], qkv[2]
        q = _constrain(q, "batch", "seq", "heads", "kv")
        k = _constrain(k, "batch", "seq", "heads", "kv")
        v = _constrain(v, "batch", "seq", "heads", "kv")

        impl = cfg.resolved_attention_impl()
        if impl not in ("dense", "flash", "ring"):
            raise ValueError(
                f"unknown attention_impl {impl!r}; expected dense|flash|ring"
            )
        if impl == "ring":
            from ..ops.ring_attention import ring_attention_sharded
            from ..parallel.mesh import get_current_mesh

            mesh = get_current_mesh()
            if mesh is None:
                raise ValueError(
                    "attention_impl='ring' needs parallel.mesh.current_mesh "
                    "active around model application"
                )
            out = ring_attention_sharded(q, k, v, mesh, causal=True)
        elif impl == "flash":
            from ..ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, causal=True)
        else:
            scale = 1.0 / jnp.sqrt(Hd).astype(cfg.dtype)
            logits = jnp.einsum("bqhk,bshk->bhqs", q, k) * scale
            mask = jnp.tril(jnp.ones((T, T), dtype=bool))
            logits = jnp.where(mask[None, None, :, :], logits, -1e9)
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(cfg.dtype)
            out = jnp.einsum("bhqs,bshk->bqhk", probs, v)
        out = _constrain(out, "batch", "seq", "heads", "kv")
        y = jnp.einsum("bqhk,hkd->bqd", out, wo.astype(cfg.dtype))
        return _constrain(y, "batch", "seq", "embed")


class Mlp(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        D, F = cfg.embed_dim, cfg.mlp_dim
        w1 = param_with_axes(
            "w1",
            nn.initializers.normal(0.02),
            (D, F),
            cfg.param_dtype,
            axes=("embed", "mlp"),
        )
        b1 = param_with_axes(
            "b1", nn.initializers.zeros, (F,), cfg.param_dtype, axes=("mlp",)
        )
        w2 = param_with_axes(
            "w2",
            nn.initializers.normal(0.02 / jnp.sqrt(2 * cfg.num_layers)),
            (F, D),
            cfg.param_dtype,
            axes=("mlp", "embed"),
        )
        b2 = param_with_axes(
            "b2", nn.initializers.zeros, (D,), cfg.param_dtype, axes=("embed",)
        )
        h = jnp.dot(x, w1.astype(cfg.dtype)) + b1.astype(cfg.dtype)
        h = _constrain(h, "batch", "seq", "mlp")
        h = jax.nn.gelu(h)
        y = jnp.dot(h, w2.astype(cfg.dtype)) + b2.astype(cfg.dtype)
        return _constrain(y, "batch", "seq", "embed")


class LayerNorm(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        scale = param_with_axes(
            "scale", nn.initializers.ones, (x.shape[-1],), cfg.param_dtype, axes=("norm",)
        )
        bias = param_with_axes(
            "bias", nn.initializers.zeros, (x.shape[-1],), cfg.param_dtype, axes=("norm",)
        )
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + 1e-5)
        return (y * scale + bias).astype(cfg.dtype)


class Block(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        x = x + CausalSelfAttention(self.config)(
            LayerNorm(self.config)(x), deterministic=deterministic
        )
        x = x + Mlp(self.config)(LayerNorm(self.config)(x))
        return x


class GPT(nn.Module):
    """Decoder-only LM. ``__call__(tokens[B,T]) -> logits[B,T,V]``."""

    config: GPTConfig

    @nn.compact
    def __call__(self, tokens, *, deterministic: bool = True):
        cfg = self.config
        B, T = tokens.shape
        wte = param_with_axes(
            "wte",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.embed_dim),
            cfg.param_dtype,
            axes=("vocab", "embed"),
        )
        wpe = param_with_axes(
            "wpe",
            nn.initializers.normal(0.01),
            (cfg.max_seq_len, cfg.embed_dim),
            cfg.param_dtype,
            axes=(None, "embed"),
        )
        x = wte.astype(cfg.dtype)[tokens] + wpe.astype(cfg.dtype)[None, :T]
        x = _constrain(x, "batch", "seq", "embed")

        block = Block
        if cfg.use_remat:
            block = nn.remat(
                Block,
                prevent_cse=False,
                policy=jax.checkpoint_policies.nothing_saveable,
            )
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"block_{i}")(x, deterministic=deterministic)
        x = LayerNorm(cfg, name="ln_f")(x)

        if cfg.tie_embeddings:
            logits = jnp.einsum("btd,vd->btv", x, wte.astype(cfg.dtype))
        else:
            w_lm = param_with_axes(
                "lm_head",
                nn.initializers.normal(0.02),
                (cfg.embed_dim, cfg.vocab_size),
                cfg.param_dtype,
                axes=("embed", "vocab"),
            )
            logits = jnp.dot(x, w_lm.astype(cfg.dtype))
        return _constrain(logits, "batch", "seq", "vocab")


def cross_entropy_loss(logits, targets, ignore_index: int = -1):
    """Mean next-token CE in fp32 (MXU-friendly: one log_softmax fusion)."""
    logits = logits.astype(jnp.float32)
    mask = targets != ignore_index
    safe_targets = jnp.where(mask, targets, 0)
    logps = jax.nn.log_softmax(logits, axis=-1)
    token_loss = -jnp.take_along_axis(logps, safe_targets[..., None], axis=-1)[..., 0]
    token_loss = jnp.where(mask, token_loss, 0.0)
    return token_loss.sum() / jnp.maximum(mask.sum(), 1)
