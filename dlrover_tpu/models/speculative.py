"""Speculative decoding over the generation engine's decode contract.

Rollout acceleration: a small DRAFT model proposes ``k`` tokens
autoregressively; the TARGET model scores all of them in ONE decode
call; standard rejection sampling accepts a prefix and emits one extra
(resampled or bonus) token, so each target forward yields 1..k+1
tokens while the output distribution provably stays the target's
(Leviathan et al. / Chen et al. speculative sampling — public
algorithm). The reference has nothing comparable; its rollouts inherit
whatever vLLM deploys.

TPU-first mechanics (everything under ONE jit, static shapes):

- **Shared slot layout, per-model caches.** Each iteration claims
  ``k+1`` cache slots: the previous iteration's emitted token, then
  the k draft proposals. BOTH models write the same slots (the draft
  feeds its own last proposal once more to stay aligned), so the two
  caches share one validity mask. Rejected proposals are never
  rewound — their slots are simply marked invalid ("holes") and the
  per-row absolute positions (a count of valid slots) keep RoPE /
  learned embeddings exact. The decode contract
  (``positions`` + ``kv_valid``, models/gpt.py) already supports this.
- **``lax.while_loop``** over speculation rounds: trip count is
  data-dependent (acceptance varies), the body is compiled once.
  Worst case each round emits 1 token; best case k+1.
- **Cache budget**: ``max_seq_len`` must cover
  ``prompt + (k+1) * max_new`` slots (holes included) — the price of
  never rewinding. Callers size the config accordingly.

EOS: rows keep stepping (static shapes) and the returned mask cuts
off after the first EOS, like the plain engine; unlike it, tokens are
still *generated* past EOS and simply masked out.
"""

from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp

from .generation import SamplingConfig, filter_logits, init_cache

__all__ = ["SpecConfig", "build_speculative_generate_fn"]


@dataclass(frozen=True)
class SpecConfig:
    num_draft: int = 4  # k: proposals per round


def _apply_decode(model, params, cache, tokens, positions, kv_valid):
    from .generation import decode_apply

    logits, cache = decode_apply(
        model, params, cache, tokens, positions, kv_valid
    )
    return logits.astype(jnp.float32), cache


def _dist(logits, s: SamplingConfig):
    """The SAMPLING distribution (temperature + top-k/top-p filters,
    renormalized) — the acceptance math must target exactly what the
    plain engine samples from, or the speculative output silently
    follows a different distribution. Greedy is handled by callers."""
    t = max(s.temperature, 1e-6)
    return jax.nn.softmax(
        filter_logits(logits / t, s.top_k, s.top_p), axis=-1
    )


def build_speculative_generate_fn(
    target_model,
    draft_model,
    sampling: SamplingConfig,
    prompt_width: int,
    spec: SpecConfig = SpecConfig(),
    mesh=None,
    target_shardings=None,
    draft_shardings=None,
    rules=None,
) -> Callable:
    """fn(t_params, d_params, prompt_tokens, prompt_mask, rng) ->
    (tokens[B,N], mask[B,N], logprobs[B,N], accept_stats).

    Same contract as :func:`generation.build_generate_fn` plus the
    draft params and per-call acceptance stats
    ``{"rounds": r, "drafted": d, "accepted": a}``. Greedy
    (temperature=0) speculative output is token-exact with plain
    greedy decode for ANY draft model — the keystone test.

    With ``mesh`` (+ the two models' param sharding trees) the whole
    speculation loop runs SPMD, mirroring
    :func:`generation.build_generate_fn`'s sharded mode — a big target
    can be served across chips while a small replicated draft
    proposes.
    """
    k = spec.num_draft
    s = sampling
    N = s.max_new_tokens
    L = target_model.config.max_seq_len
    if draft_model.config.max_seq_len != L:
        raise ValueError("draft and target must share max_seq_len")
    if draft_model.config.vocab_size != target_model.config.vocab_size:
        raise ValueError("draft and target must share the vocabulary")
    # worst case: every round emits one token and burns k+1 slots
    need = prompt_width + (k + 1) * N
    if need > L:
        raise ValueError(
            f"speculative cache budget: prompt {prompt_width} + "
            f"(k+1)*max_new {(k + 1) * N} = {need} slots > max_seq_len "
            f"{L}; raise max_seq_len or lower num_draft/max_new"
        )
    greedy = s.temperature == 0.0

    def _sample_from(dist, rng):
        if greedy:
            return jnp.argmax(dist, axis=-1)
        return jax.random.categorical(rng, jnp.log(dist + 1e-30), axis=-1)

    def _generate(t_params, d_params, prompt_tokens, prompt_mask, rng):
        B, T0 = prompt_tokens.shape
        if T0 != prompt_width:
            raise ValueError(
                f"prompt width {T0} != built prompt_width {prompt_width}"
            )
        t_cache = init_cache(target_model, B)
        d_cache = init_cache(draft_model, B)

        positions = jnp.maximum(
            jnp.cumsum(prompt_mask.astype(jnp.int32), axis=1) - 1, 0
        )
        kv_valid = jnp.zeros((B, L), bool)
        kv_valid = kv_valid.at[:, :T0].set(prompt_mask)

        # prefill BOTH models on the prompt; first token from target
        t_logits, t_cache = _apply_decode(
            target_model, t_params, t_cache, prompt_tokens, positions,
            kv_valid,
        )
        _, d_cache = _apply_decode(
            draft_model, d_params, d_cache, prompt_tokens, positions,
            kv_valid,
        )
        rng, sub = jax.random.split(rng)
        p0 = _dist(t_logits[:, -1], s)
        tok0 = _sample_from(p0, sub)
        lp0 = jnp.log(
            jnp.take_along_axis(
                jax.nn.softmax(t_logits[:, -1], axis=-1),
                tok0[:, None],
                axis=-1,
            )[:, 0]
            + 1e-30
        )

        n_ctx = prompt_mask.sum(axis=1).astype(jnp.int32)  # valid tokens
        out_toks = jnp.full((B, N), s.pad_id, jnp.int32)
        out_toks = out_toks.at[:, 0].set(tok0)
        out_lps = jnp.zeros((B, N), jnp.float32)
        out_lps = out_lps.at[:, 0].set(lp0)
        n_emit = jnp.ones((B,), jnp.int32)

        def emit(buf, vals, offsets, active):
            """buf[b, offsets[b]] = vals[b] where active[b]."""
            oh = jax.nn.one_hot(
                jnp.where(active, offsets, N), N + 1, dtype=buf.dtype
            )[:, :N]
            return buf * (1 - oh) + oh * vals[:, None]

        def cond(carry):
            (_tc, _dc, _kv, _ot, _ol, n_emit, _nc, _ft, ptr, _rg, stats) = (
                carry
            )
            return (n_emit.min() < N) & (ptr + k + 1 <= L)

        def body(carry):
            (
                t_cache,
                d_cache,
                kv_valid,
                out_toks,
                out_lps,
                n_emit,
                n_ctx,
                final_tok,
                ptr,
                rng,
                stats,
            ) = carry

            # -- draft k proposals, one decode step each; the previous
            # emitted token leads the window at slot ptr
            d_toks = []
            q_dists = []
            cur = final_tok
            cur_pos = n_ctx  # final token's position per row
            dc = d_cache
            kv = kv_valid
            # final token's slot is valid context for everyone
            kv = kv | (jnp.arange(L)[None, :] == ptr)
            for j in range(k):
                q_logits, dc = _apply_decode(
                    draft_model, d_params, dc, cur[:, None],
                    cur_pos[:, None], kv,
                )
                qd = _dist(q_logits[:, 0], s)
                rng, sub = jax.random.split(rng)
                nxt = _sample_from(qd, sub)
                q_dists.append(qd)
                d_toks.append(nxt)
                # tentatively treat the proposal's slot as valid
                # context for the NEXT proposal
                kv = kv | (jnp.arange(L)[None, :] == ptr + 1 + j)
                cur = nxt
                cur_pos = cur_pos + 1
            # align the draft cache: feed the last proposal too, so
            # both caches have written the same slots [ptr..ptr+k]
            # (final + d_1..d_k) and one validity mask serves both
            _, dc = _apply_decode(
                draft_model, d_params, dc, cur[:, None],
                cur_pos[:, None], kv,
            )
            drafted = jnp.stack(d_toks, axis=1)  # [B, k]

            # -- target verifies the window [final, d_1..d_k] at once
            win = jnp.concatenate([final_tok[:, None], drafted], axis=1)
            win_pos = n_ctx[:, None] + jnp.arange(k + 1)[None, :]
            t_logits, tc = _apply_decode(
                target_model, t_params, t_cache, win, win_pos, kv,
            )
            p_dists = _dist(t_logits, s)  # [B, k+1, V]
            p_raw = jax.nn.softmax(t_logits, axis=-1)

            # -- rejection sampling per row
            #    p_j = p_dists[:, j-1] scores d_j; p_dists[:, k] = bonus
            p_at = jnp.take_along_axis(
                p_dists[:, :k], drafted[:, :, None], axis=-1
            )[:, :, 0]
            q_at = jnp.stack(
                [
                    jnp.take_along_axis(q, d[:, None], axis=-1)[:, 0]
                    for q, d in zip(q_dists, d_toks)
                ],
                axis=1,
            )  # [B, k]
            if greedy:
                ok = drafted == jnp.argmax(p_dists[:, :k], axis=-1)
            else:
                rng, sub = jax.random.split(rng)
                u = jax.random.uniform(sub, (B, k))
                ok = u < jnp.minimum(1.0, p_at / jnp.maximum(q_at, 1e-30))
            # a = accepted prefix length
            a = jnp.where(
                ok.all(axis=1), k, jnp.argmin(ok.astype(jnp.int32), axis=1)
            )

            # residual resample at the first rejected position; bonus
            # sample from p_{k+1} when everything was accepted
            rej_p = jnp.take_along_axis(
                p_dists[:, :k],
                jnp.minimum(a, k - 1)[:, None, None],
                axis=1,
            )[:, 0]
            rej_q = jnp.stack(q_dists, axis=1)
            rej_q = jnp.take_along_axis(
                rej_q, jnp.minimum(a, k - 1)[:, None, None], axis=1
            )[:, 0]
            resid = jnp.maximum(rej_p - rej_q, 0.0)
            resid = resid / jnp.maximum(
                resid.sum(axis=-1, keepdims=True), 1e-30
            )
            # degenerate residual (p==q exactly): fall back to p
            resid = jnp.where(
                resid.sum(axis=-1, keepdims=True) > 0, resid, rej_p
            )
            bonus_p = p_dists[:, k]
            rng, s1, s2 = jax.random.split(rng, 3)
            if greedy:
                resampled = jnp.argmax(rej_p, axis=-1)
            else:
                resampled = _sample_from(resid, s1)
            bonus = _sample_from(bonus_p, s2)
            all_ok = a == k
            extra_tok = jnp.where(all_ok, bonus, resampled)

            # -- validity: slots are [ptr]=final, [ptr+1..ptr+k]=
            # drafts. The final + accepted prefix becomes real context;
            # rejected slots become permanent holes (never rewound —
            # positions count only valid slots, so RoPE stays exact)
            slot_idx = jnp.arange(L)[None, :]
            keep = slot_idx <= (ptr + a[:, None])  # final + accepted
            window_slots = (slot_idx >= ptr) & (slot_idx < ptr + k + 1)
            kv_valid = jnp.where(window_slots, keep, kv_valid)

            # -- emit accepted drafts then the extra token
            active_row = n_emit < N
            ne = n_emit
            ot, ol = out_toks, out_lps
            t_lp_at = jnp.log(
                jnp.take_along_axis(
                    p_raw[:, :k], drafted[:, :, None], axis=-1
                )[:, :, 0]
                + 1e-30
            )
            for j in range(k):
                put = active_row & (j < a) & (ne < N)
                ot = emit(ot, drafted[:, j], ne, put)
                ol = emit(ol, t_lp_at[:, j], ne, put)
                ne = ne + put.astype(jnp.int32)
            extra_raw_p = jnp.where(all_ok[:, None], p_raw[:, k], p_raw[
                jnp.arange(B), jnp.minimum(a, k - 1)
            ])
            extra_lp = jnp.log(
                jnp.take_along_axis(
                    extra_raw_p, extra_tok[:, None], axis=-1
                )[:, 0]
                + 1e-30
            )
            put = active_row & (ne < N)
            ot = emit(ot, extra_tok, ne, put)
            ol = emit(ol, extra_lp, ne, put)
            ne = ne + put.astype(jnp.int32)

            n_ctx = n_ctx + 1 + a  # final + accepted (extra not in cache)
            # stats count only rows still emitting: a finished row's
            # free-running proposals would bias the acceptance rate a
            # caller uses to tune num_draft
            n_active = active_row.sum()
            stats = (
                stats[0] + 1,
                stats[1] + k * n_active,
                stats[2] + jnp.where(active_row, a, 0).sum(),
            )
            return (
                tc,
                dc,
                kv_valid,
                ot,
                ol,
                ne,
                n_ctx,
                extra_tok,
                ptr + k + 1,
                rng,
                stats,
            )

        stats0 = (
            jnp.zeros((), jnp.int32),
            jnp.zeros((), jnp.int32),
            jnp.zeros((), jnp.int32),
        )
        carry = (
            t_cache,
            d_cache,
            kv_valid,
            out_toks,
            out_lps,
            n_emit,
            n_ctx,
            tok0,
            jnp.asarray(T0, jnp.int32),
            rng,
            stats0,
        )
        carry = jax.lax.while_loop(cond, body, carry)
        (_tc, _dc, _kv, out_toks, out_lps, n_emit, _nc, _ft, _ptr, _rg, st) = (
            carry
        )

        # post-mask: cut after the first EOS (the EOS itself is kept)
        if s.eos_id >= 0:
            is_eos = out_toks == s.eos_id
            after = jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
            mask = (after - is_eos.astype(jnp.int32)) == 0
            out_toks = jnp.where(mask, out_toks, s.pad_id)
        else:
            mask = jnp.ones_like(out_toks, bool)
        # Positions past n_emit are unfilled pad slots. The constructor's
        # cache-budget check makes early exit via the slot guard
        # unreachable today, but if that invariant ever breaks, truncation
        # must surface as masked-out slots, not as "valid" pad tokens.
        mask = mask & (jnp.arange(N)[None, :] < n_emit[:, None])
        stats = {"rounds": st[0], "drafted": st[1], "accepted": st[2]}
        return out_toks, mask, out_lps, stats

    if mesh is None:
        return jax.jit(_generate)

    from ..parallel.sharding import sharded_generate_jit

    # either tree may be None (that model replicates — the usual shape
    # for a small draft next to a sharded target)
    return sharded_generate_jit(
        _generate,
        mesh,
        (target_shardings, draft_shardings),
        n_data_args=2,
        rules=rules,
    )
