"""Paged KV-cache building blocks (vLLM's serving-memory idea).

The slot-dense engine layouts (``frontier`` / ``per_row``) reserve a
full ``[max_seq_len]`` cache row per batch slot, so HBM pays worst-case
padding on every admission and a shared prompt prefix is stored once
per row. The ``paged`` layout breaks the cache into fixed-size token
BLOCKS drawn from one pool:

- :class:`BlockPool` — the host-side allocator: a free list plus
  per-block refcounts. Admission is bounded by free *blocks*, not by
  decode slots; a registered prefix's fully-covered blocks are
  refcounted and shared across every row using it (copy-on-write: rows
  never write inside a shared block — decode writes start past the
  prefix — and the partially-filled tail block is the per-row "copy").
- :func:`gather_cache` / :func:`scatter_cache` — the jit-side halves:
  a per-request block table ``[B, L // block_size]`` indexes the pool
  ``(num_blocks, block_size, ...)``; gather materializes the dense
  ``[B, L, ...]`` view the shared decode-chunk body runs on, scatter
  writes it back. Block 0 is the TRASH block: unallocated table
  entries point at it, so a retired row's parked writes (the chunk
  body keeps stepping done rows — static shapes) land somewhere
  harmless, and ``kv_valid`` masks whatever gather reads from it.
- :func:`pack_row_state` / :func:`unpack_row_state` — host-portable
  serialization of one prefilled row (cache + logits + position + kv
  mask), the prefill/decode disaggregation hand-off payload: a
  prefill-role replica fills a prompt's row and ships it to a
  decode-role replica over the gateway's existing HTTP plumbing.

Everything here is deliberately framework-thin: the pool is plain
Python (the scheduler already runs the host side of admission), and
the gather/scatter are pure ``jnp`` tree maps traced INTO the decode
chunk program — one dispatch per chunk, same as the dense layouts.
"""

import base64
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "TRASH_BLOCK",
    "BlockPool",
    "blocks_for",
    "build_table_row",
    "gather_cache",
    "scatter_cache",
    "pack_row_state",
    "unpack_row_state",
]

# block id 0 is never allocated: every unpopulated block-table entry
# points here, so stray writes (done rows' clamped write slot, table
# rows parked at retirement) have a harmless destination
TRASH_BLOCK = 0


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks covering ``tokens`` cache positions (ceil division)."""
    return -(-int(tokens) // int(block_size))


class BlockPool:
    """Host-side allocator for the paged KV pool.

    Refcounted: ``alloc`` hands out blocks at refcount 1, ``share``
    bumps the count (a row joining a registered prefix's blocks), and
    ``free`` decrements — a block returns to the free list only when
    its LAST holder releases it, which is what makes prefix sharing
    safe against any retire/unregister order.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks {num_blocks} must be >= 2 (block 0 is "
                f"the reserved trash block)"
            )
        if block_size < 1:
            raise ValueError(f"block_size {block_size} must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: recently-freed blocks are re-used first
        # (their pool pages are the warmest)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}

    @property
    def blocks_total(self) -> int:
        """Allocatable blocks (the trash block is not one)."""
        return self.num_blocks - 1

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    def refcount(self, bid: int) -> int:
        return self._ref.get(bid, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take ``n`` fresh blocks at refcount 1, or None (and take
        NOTHING) when fewer than ``n`` are free — admission either
        gets its whole table or leaves the pool untouched."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        for b in ids:
            self._ref[b] = 1
        return ids

    def share(self, ids: List[int]) -> None:
        """Add one holder to each of ``ids`` (must be live)."""
        for b in ids:
            if self._ref.get(b, 0) <= 0:
                raise ValueError(f"share of unallocated block {b}")
            self._ref[b] += 1

    def free(self, ids: List[int]) -> int:
        """Release one holder per id; returns how many blocks actually
        went back to the free list (refcount reached zero)."""
        returned = 0
        for b in ids:
            r = self._ref.get(b, 0)
            if r <= 0:
                raise ValueError(f"double free of block {b}")
            if r == 1:
                del self._ref[b]
                self._free.append(b)
                returned += 1
            else:
                self._ref[b] = r - 1
        return returned


def build_table_row(block_ids: List[int], table_width: int) -> np.ndarray:
    """One request's block table: its blocks in position order, padded
    with the trash block out to the fixed table width (L // bs)."""
    if len(block_ids) > table_width:
        raise ValueError(
            f"{len(block_ids)} blocks > table width {table_width}"
        )
    row = np.full((table_width,), TRASH_BLOCK, np.int32)
    row[: len(block_ids)] = block_ids
    return row


def gather_cache(pool, tables):
    """Dense ``[B, L, ...]`` view of the paged pool: each cache leaf
    ``(num_blocks, bs, ...)`` is gathered by the ``[B, nb]`` block
    table and re-flattened. 0-d leaves (the shared write-index
    scalars) pass through. Traced inside the decode chunk program —
    the shared chunk body then runs UNCHANGED on the view, which is
    what makes the paged layout bit-exact with ``per_row``."""
    B, nb = tables.shape
    return jax.tree_util.tree_map(
        lambda p: p if p.ndim == 0 else (
            p[tables].reshape((B, nb * p.shape[1]) + p.shape[2:])
        ),
        pool,
    )


def scatter_cache(pool, tables, dense):
    """Write an advanced dense view back into the pool by block table.
    Duplicate table entries (the trash block; prefix blocks shared
    across rows) receive an unspecified writer — harmless by
    construction: trash content is never read with kv_valid set, and
    every sharer of a prefix block writes back the identical prefix
    values (decode writes land past the prefix, so the gathered
    prefix region rides through unchanged)."""
    B, nb = tables.shape
    return jax.tree_util.tree_map(
        lambda p, d: p if p.ndim == 0 else p.at[tables].set(
            d.reshape((B, nb, p.shape[1]) + p.shape[2:])
        ),
        pool,
        dense,
    )


def scatter_row(pool, table_row, row):
    """Insert one prefilled ``[1, L, ...]`` row into its blocks
    (``table_row``: ``[nb]`` int32). Trash-padded entries write the
    row's uncovered tail into the trash block — never read valid."""
    nb = table_row.shape[0]
    return jax.tree_util.tree_map(
        lambda p, r: p if p.ndim == 0 else p.at[table_row].set(
            r[0].reshape((nb, p.shape[1]) + r.shape[2:]).astype(p.dtype)
        ),
        pool,
        row,
    )


# -- prefill/decode disaggregation hand-off payload ---------------------


def _enc(arr) -> Dict:
    a = np.asarray(arr)
    return {
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _dec(d: Dict) -> np.ndarray:
    a = np.frombuffer(
        base64.b64decode(d["b64"]), dtype=np.dtype(d["dtype"])
    )
    return a.reshape(d["shape"])


def pack_row_state(
    row_cache, row_logits, row_pos, row_kv, width: int,
    prompt: List[int],
) -> Dict:
    """Serialize one prefilled row for the prefill→decode hand-off:
    JSON-safe (base64 leaves), host-portable, model-agnostic on the
    wire — the receiver validates shapes against ITS model before
    admitting (a payload from a mismatched config must 400, never
    corrupt a cache row)."""
    leaves = jax.tree_util.tree_leaves(row_cache)
    return {
        "v": 1,
        "width": int(width),
        "prompt": [int(t) for t in prompt],
        "cache_leaves": [_enc(x) for x in leaves],
        "logits": _enc(row_logits),
        "pos": _enc(row_pos),
        "kv": _enc(row_kv),
    }


def unpack_row_state(payload: Dict, like_cache):
    """Rebuild ``(row_cache, row_logits, row_pos, row_kv, width,
    prompt)`` from a hand-off payload. ``like_cache`` is the RECEIVING
    engine's ``init_cache(model, 1)`` — structure and per-leaf shapes
    must match exactly or the payload is rejected."""
    if payload.get("v") != 1:
        raise ValueError(f"unknown handoff payload version {payload.get('v')!r}")
    like_leaves, treedef = jax.tree_util.tree_flatten(like_cache)
    enc = payload["cache_leaves"]
    if len(enc) != len(like_leaves):
        raise ValueError(
            f"handoff cache has {len(enc)} leaves, engine expects "
            f"{len(like_leaves)}"
        )
    leaves = []
    for got, want in zip(enc, like_leaves):
        arr = _dec(got)
        if tuple(arr.shape) != tuple(want.shape):
            raise ValueError(
                f"handoff leaf shape {tuple(arr.shape)} != engine "
                f"{tuple(want.shape)} (mismatched model config)"
            )
        leaves.append(jnp.asarray(arr, want.dtype))
    row_cache = jax.tree_util.tree_unflatten(treedef, leaves)
    return (
        row_cache,
        jnp.asarray(_dec(payload["logits"])),
        jnp.asarray(_dec(payload["pos"])),
        jnp.asarray(_dec(payload["kv"])),
        int(payload["width"]),
        [int(t) for t in payload["prompt"]],
    )
