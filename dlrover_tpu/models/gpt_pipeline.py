"""Pipeline-parallel GPT: the flagship family over the pp mesh axis.

Bridges :mod:`dlrover_tpu.models.gpt` (the flax single-program model) and
:mod:`dlrover_tpu.parallel.pipeline` (the SPMD GPipe schedule): the
homogeneous transformer blocks run inside the pipeline as a stage fn,
embedding/unembedding stay outside (heterogeneous), and the whole
train step — embed → pipelined blocks → unembed → CE loss → grads →
adam — jits into one XLA program.  Reference: Megatron-style pp is
*integrated* by the reference, never implemented
(``megatron_engine.py:52-62`` tracks pp_rank only for checkpoint shard
math); here the schedule itself is native.

Params are plain pytrees (no flax): block params stacked
``[stages, layers_per_stage, ...]`` and sharded over ``pp``
(:func:`pipeline.stage_sharding`); checkpoint/re-mesh rides the normal
flash-ckpt path, and :func:`pipeline.refold_stages` re-stages them when
the pp extent changes.
"""

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.pipeline import (
    merge_microbatches,
    pipeline_apply,
    split_microbatches,
    stack_stage_params,
    stage_sharding,
)
from .gpt import GPTConfig, cross_entropy_loss


def init_gpt_pipeline_params(
    cfg: GPTConfig, num_stages: int, rng: jax.Array
) -> Dict[str, Any]:
    """{embed: {wte, wpe}, stages: [S, L, ...] blocks, ln_f, lm_head}.

    Layers must divide evenly into stages. Init matches gpt.py's scales
    (normal 0.02, residual-out scaled by 1/sqrt(2L))."""
    if cfg.num_layers % num_stages:
        raise ValueError(
            f"{cfg.num_layers} layers not divisible into {num_stages} stages"
        )
    layers_per_stage = cfg.num_layers // num_stages
    D, H, Hd, F = cfg.embed_dim, cfg.num_heads, cfg.head_dim, cfg.mlp_dim
    out_scale = 0.02 / np.sqrt(2 * cfg.num_layers)

    def one_layer(key):
        ks = jax.random.split(key, 4)
        pd = cfg.param_dtype
        return {
            "ln1_scale": jnp.ones((D,), pd),
            "ln1_bias": jnp.zeros((D,), pd),
            "wqkv": jax.random.normal(ks[0], (D, 3, H, Hd), pd) * 0.02,
            "wo": jax.random.normal(ks[1], (H, Hd, D), pd) * out_scale,
            "ln2_scale": jnp.ones((D,), pd),
            "ln2_bias": jnp.zeros((D,), pd),
            "w1": jax.random.normal(ks[2], (D, F), pd) * 0.02,
            "b1": jnp.zeros((F,), pd),
            "w2": jax.random.normal(ks[3], (F, D), pd) * out_scale,
            "b2": jnp.zeros((D,), pd),
        }

    key_embed, key_blocks, key_head = jax.random.split(rng, 3)
    layer_keys = jax.random.split(key_blocks, cfg.num_layers)
    stages = []
    for s in range(num_stages):
        layers = [
            one_layer(layer_keys[s * layers_per_stage + i])
            for i in range(layers_per_stage)
        ]
        stages.append(jax.tree.map(lambda *ls: jnp.stack(ls), *layers))
    ke1, ke2 = jax.random.split(key_embed)
    return {
        "embed": {
            "wte": jax.random.normal(
                ke1, (cfg.vocab_size, cfg.embed_dim), cfg.param_dtype
            )
            * 0.02,
            "wpe": jax.random.normal(
                ke2, (cfg.max_seq_len, cfg.embed_dim), cfg.param_dtype
            )
            * 0.01,
        },
        "stages": stack_stage_params(stages),
        "ln_f": {
            "scale": jnp.ones((cfg.embed_dim,), cfg.param_dtype),
            "bias": jnp.zeros((cfg.embed_dim,), cfg.param_dtype),
        },
        "lm_head": jax.random.normal(
            key_head, (cfg.embed_dim, cfg.vocab_size), cfg.param_dtype
        )
        * 0.02,
    }


def _layer_norm(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    return ((x32 - mean) * jax.lax.rsqrt(var + 1e-5) * scale + bias).astype(
        x.dtype
    )


def gpt_stage_fn(cfg: GPTConfig):
    """Stage fn for :func:`pipeline_apply`: scans this stage's blocks.
    x is [mb, T, D] in cfg.dtype; causal dense attention (the sp/flash
    variants belong to the sp axis, not pp)."""

    def block(x, p):
        T = x.shape[1]
        h = _layer_norm(x, p["ln1_scale"], p["ln1_bias"])
        qkv = jnp.einsum("btd,dchk->cbthk", h, p["wqkv"].astype(x.dtype))
        q, k, v = qkv[0], qkv[1], qkv[2]
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(x.dtype)
        logits = jnp.einsum("bqhk,bshk->bhqs", q, k) * scale
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        logits = jnp.where(mask[None, None], logits, -1e9)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
            x.dtype
        )
        att = jnp.einsum("bhqs,bshk->bqhk", probs, v)
        x = x + jnp.einsum("bqhk,hkd->bqd", att, p["wo"].astype(x.dtype))
        h = _layer_norm(x, p["ln2_scale"], p["ln2_bias"])
        h = jax.nn.gelu(
            jnp.dot(h, p["w1"].astype(x.dtype)) + p["b1"].astype(x.dtype)
        )
        x = x + jnp.dot(h, p["w2"].astype(x.dtype)) + p["b2"].astype(x.dtype)
        return x, None

    def stage(stage_params, x):
        x, _ = jax.lax.scan(block, x, stage_params)
        return x

    return stage


_DATA_AXES = ("dp", "fsdp")


def gpt_pipeline_forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: GPTConfig,
    mesh,
    num_microbatches: int,
) -> jax.Array:
    """tokens [B, T] → logits [B, T, V] through the pipelined blocks.
    The microbatch dim stays sharded over dp/fsdp through the pipeline
    (each dp rank pipelines only its batch slice)."""
    T = tokens.shape[1]
    embed = params["embed"]
    x = (
        embed["wte"].astype(cfg.dtype)[tokens]
        + embed["wpe"].astype(cfg.dtype)[None, :T]
    )
    mb = split_microbatches(x, num_microbatches)
    # Keep the microbatch dim dp-sharded when it divides the data
    # extent; otherwise fall back to replicated (correct, redundant) —
    # callers wanting dp scaling should pick M <= B / (dp*fsdp).
    data_extent = mesh.shape["dp"] * mesh.shape["fsdp"]
    if mb.shape[1] % data_extent == 0:
        data_spec = P(None, _DATA_AXES)
    else:
        data_spec = P()
    mb = jax.lax.with_sharding_constraint(
        mb, NamedSharding(mesh, data_spec)
    )
    y = pipeline_apply(
        gpt_stage_fn(cfg),
        params["stages"],
        mb,
        mesh,
        data_spec=data_spec,
    )
    y = merge_microbatches(y)
    y = _layer_norm(y, params["ln_f"]["scale"], params["ln_f"]["bias"])
    return jnp.dot(y, params["lm_head"].astype(cfg.dtype))


def gpt_pipeline_shardings(params: Dict[str, Any], mesh) -> Dict[str, Any]:
    """Stages over pp; embed/head/ln replicated. The BATCH is what rides
    dp/fsdp (see gpt_pipeline_forward's data_spec); sharding the
    embed/head params over fsdp too can be layered on via the normal
    logical rules when memory demands it."""
    replicated = NamedSharding(mesh, P())
    return {
        "embed": jax.tree.map(lambda _: replicated, params["embed"]),
        "stages": stage_sharding(params["stages"], mesh),
        "ln_f": jax.tree.map(lambda _: replicated, params["ln_f"]),
        "lm_head": replicated,
    }


def build_gpt_pipeline_train_step(
    cfg: GPTConfig,
    mesh,
    tx,
    num_microbatches: int,
    shardings: Dict[str, Any],
    donate: bool = True,
):
    """Jitted (params, opt_state, tokens, targets) -> (params', opt', loss)
    — embed → pipeline → unembed → CE → grads → optimizer, one program.
    ``donate=False`` keeps the input params/opt_state buffers alive
    (e.g. to diff before/after or retry a step)."""
    import optax

    replicated = NamedSharding(mesh, P())
    batch_sharded = NamedSharding(mesh, P(_DATA_AXES))

    def step(params, opt_state, tokens, targets):
        def loss_fn(p):
            logits = gpt_pipeline_forward(
                p, tokens, cfg, mesh, num_microbatches
            )
            return cross_entropy_loss(logits, targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def run(params, opt_state, tokens, targets):
        with mesh:
            return jitted(params, opt_state, tokens, targets)

    # opt-state shardings stay None: tx.init(params) builds slots on the
    # params' own placements (adam moments mirror param shapes), so jit
    # keeps whatever layout the state already has.
    jitted = jax.jit(
        step,
        in_shardings=(shardings, None, batch_sharded, batch_sharded),
        out_shardings=(shardings, None, replicated),
        donate_argnums=(0, 1) if donate else (),
    )
    return run
