"""Continuous batching over the generation engine, TPU-first.

The reference serves RL rollouts through vLLM (continuous batching +
paged KV — examples/unified/rl/openrlhf/ppo/main.py:26-60). This
module is that capability over the repo's own engine: a request-queue
scheduler that admits new prompts into freed batch slots while other
rows keep decoding, so a rollout role serving a mixed-length prompt
stream does not pay worst-case padding in every batch
(VERDICT r4 #5).

TPU shape — every device program is static-shape and compiled once:

- **Slot admission.** A new request's prompt is prefilled into a fresh
  single-row cache at slots ``[0, W)`` (W = the smallest width bucket
  that fits it, at most Pw) and the whole row is inserted into the
  batch cache; positions count only valid slots, so RoPE/posembs never
  see pad holes — the same contract speculative decoding proves
  token-exact.
- **Decode runs in chunks**: a ``lax.scan`` of ``decode_chunk`` steps
  per scheduler iteration, so the host pays one dispatch + one result
  fetch per chunk, not per token (the tunnel RTT is the cost model).
- **Two cache layouts** (``cache_layout=``):

  - ``"frontier"``: every row writes at one shared slot per step (a
    single ``dynamic_update_slice``). Admissions leave kv_valid holes
    up to the frontier; slots are a stream-wide budget, and when
    headroom runs out the scheduler re-prefills every live row's full
    history into a fresh cache (compaction — one batched MXU-friendly
    forward), width-bucketed to bound recompiles. Liveness:
    ``aligned(prompt_width + max_new_tokens) + max(max_new_tokens,
    decode_chunk) <= max_seq_len``.
  - ``"per_row"``: every row writes at its OWN next slot (a B-row
    scatter — gpt._update_decode_cache ``cache_slots`` mode). No
    shared frontier, no holes past a prompt's bucket, no compaction
    ever: the paged-KV property, recovered in a static ``[B, L]``
    cache by per-request slot reuse. Liveness is per-request:
    ``prompt_width + max_new_tokens <= max_seq_len``.
  - ``"paged"``: the full vLLM-style serving memory (models/
    kv_blocks.py). The cache is a pool of fixed-size token blocks;
    each slot carries a block TABLE, the decode chunk gathers the
    dense view by table, runs the SAME per-row step body (bit-exact
    by construction), and scatters back. Admission is bounded by free
    BLOCKS (a short request reserves its bucket + cap, not a whole
    [L] row), a registered prefix's fully-covered blocks are
    refcounted and shared copy-on-write across every row using it,
    and an out-of-blocks burst queues (bounded) instead of OOMing.

- **Weight hot-swap between chunks**: ``set_params`` replaces the
  parameter argument of the jitted programs (same shapes — no
  recompile), so a WeightBus push lands at the next chunk boundary;
  ``swap_latency_s`` of the last swap is recorded.
- **Overlapped (double-buffered) round** (``overlap=True``, the
  default): each ``step()`` dispatches chunk N+1 *before* it syncs and
  retires chunk N, so the device queue never drains between rounds and
  the host's emission/retirement/admission work runs while the next
  chunk executes. Per-row stop enforcement lives ON THE DEVICE for
  this (cap counters + done-masking inside the jitted chunk fn): a row
  that hits its cap or EOS mid-flight is silenced by the device state
  itself, so the one-chunk lag between device progress and host
  bookkeeping can neither over-emit nor corrupt KV. The host sees a
  one-chunk emission latency; greedy streams are bit-identical with
  the synchronous round (``overlap=False``, kept as the A/B baseline).
  Weight swaps adopt only at a drained pipeline (no chunk in flight),
  so a push can never split a round between parameter versions.
  Host time hidden behind in-flight chunks is stamped as the
  ``overlap_hidden`` phase (attribution.phases).
- **decode_chunk auto-tuning** (``auto_chunk=True``): the measured
  ``serving_host_frac`` drives the chunk length between dispatches —
  host-bound streams grow the chunk (amortize per-round host cost over
  more tokens), device-bound streams shrink it back (less wasted tail
  decode and faster admission). One compiled program per candidate
  length, all liveness-checked.
"""

import contextlib
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..attribution.phases import PhaseAccumulator
from ..chaos import faults
from . import kv_blocks
from .generation import (
    SamplingConfig,
    decode_apply,
    init_cache,
    left_pad_prompts,
    prefill_prompt,
    sample_step,
)

__all__ = [
    "Completion",
    "ContinuousBatchingEngine",
    "SpeculativeBatchingEngine",
]


@dataclass
class Completion:
    uid: int
    tokens: List[int]
    logprobs: List[float]
    # per-request service metrics (host wall-clock)
    queue_s: float = 0.0  # submit → slot admission
    ttft_s: float = 0.0  # admission → first emitted token
    total_s: float = 0.0  # admission → retirement


def _tree_ready(tree) -> bool:
    """Non-blocking: every leaf of ``tree`` has finished computing /
    transferring (``Array.is_ready``). The one readiness poll shared
    by async weight adoption and the pipeline's zero-lag probe."""
    return all(
        leaf.is_ready()
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "is_ready")
    )


def _device_put_like(tree, like):
    """Enqueue ``tree`` to the device preserving ``like``'s per-leaf
    placement: a WeightBus push delivers HOST arrays, and a bare
    ``device_put`` would commit them to one device — collapsing
    tp/fsdp-sharded serving onto a single chip and forcing a
    recompile. Shared by the target and draft swap paths."""
    try:
        spec = jax.tree_util.tree_map(lambda x: x.sharding, like)
    except AttributeError:  # engine was built with host arrays
        spec = None
    return jax.device_put(tree, spec)


@dataclass
class _Slot:
    uid: int = -1  # -1 = empty
    prompt: List[int] = field(default_factory=list)
    emitted: List[int] = field(default_factory=list)
    logprobs: List[float] = field(default_factory=list)
    finished: bool = False  # EOS seen (device done flag)
    cap: int = 0  # this request's max_new_tokens (<= engine budget)
    submit_t: float = 0.0
    admit_t: float = 0.0
    first_tok_t: float = 0.0


class _ChunkAutoTuner:
    """Retunes ``decode_chunk`` between dispatches from the measured
    ``serving_host_frac`` (attribution.phases): when the host fraction
    of a window of rounds runs high, per-round host cost dominates —
    grow the chunk so one dispatch/readback amortizes over more
    tokens; when it runs low, shrink back — small chunks waste fewer
    tail steps on finished rows and admit queued requests sooner.
    Candidates are fixed at construction (one compiled program each)
    and every one satisfies the engine's liveness bound, so a retune
    can never strand the stream."""

    WINDOW = 8  # rounds per decision — enough samples to smooth noise
    HIGH = 0.35
    LOW = 0.10

    def __init__(self, engine):
        s = engine.s
        cands = {engine.d} | {4, 8, 16, 32}
        cands = {
            c for c in cands
            if c == engine.d or 1 <= c <= s.max_new_tokens
        }
        if engine.layout == "frontier":
            worst = engine._align(engine.Pw + s.max_new_tokens)
            cands = {
                c for c in cands
                if c == engine.d
                or worst + max(s.max_new_tokens, c) <= engine.L
            }
        self.candidates = sorted(cands)
        self.engine = engine
        self.retunes = 0
        self._mark = self._snapshot()

    def _snapshot(self):
        split = self.engine.phases.split()
        return (split.host_s, split.total_s, split.rounds)

    def maybe_retune(self) -> Optional[int]:
        """Called once per scheduler round; returns the new chunk
        length when a retune happened, else None. The off-decision
        rounds pay one integer compare — a full split() only builds
        on decision rounds."""
        h0, t0, r0 = self._mark
        rounds = self.engine.phases.rounds
        if rounds < r0:  # accumulator was reset (bench warm/reset)
            self._mark = self._snapshot()
            return None
        if rounds - r0 < self.WINDOW:
            return None
        split = self.engine.phases.split()
        dh, dt = split.host_s - h0, split.total_s - t0
        self._mark = (split.host_s, split.total_s, split.rounds)
        if dt <= 0 or dh < 0:  # accumulator was reset mid-window
            return None
        frac = dh / dt
        idx = self.candidates.index(self.engine.d)
        if frac > self.HIGH and idx + 1 < len(self.candidates):
            self.engine.d = self.candidates[idx + 1]
        elif frac < self.LOW and idx > 0:
            self.engine.d = self.candidates[idx - 1]
        else:
            return None
        self.retunes += 1
        return self.engine.d


class ContinuousBatchingEngine:
    """Serve a stream of prompts through ``batch_size`` decode slots.

    ``submit(tokens)`` enqueues a request and returns its uid;
    ``run()`` drives the scheduler until queue and slots drain,
    returning ``Completion``s. Greedy output is token-exact with
    :func:`generation.build_generate_fn` on the same prompt — the
    keystone test (admission holes and compaction are invisible to the
    math).
    """

    def __init__(
        self,
        model,
        params,
        sampling: SamplingConfig,
        batch_size: int,
        prompt_width: int,
        decode_chunk: int = 8,
        mesh=None,
        rules=None,
        cache_layout: str = "frontier",
        overlap: bool = True,
        auto_chunk: bool = False,
        kv_block_size: int = 16,
        kv_pool_blocks: int = 0,
    ):
        """With ``mesh`` (+ optional logical-axis ``rules``) every
        device program runs SPMD over it: pass params already placed in
        their trainer shardings (tp/fsdp) and the whole engine serves a
        model bigger than one chip — same scheduler, XLA inserts the
        decode collectives. The stream state rides the batch axis
        REPLICATED (serve-mesh convention: scale batch by running one
        engine per data shard; the mesh scales the MODEL), so use
        tp/fsdp axes only.

        ``cache_layout``:

        - ``"frontier"`` (default): all rows write at one shared slot
          (single ``dynamic_update_slice`` per step). Admissions leave
          kv_valid holes up to the frontier, and the stream compacts
          (a batched re-prefill) when the frontier nears the cache end.
        - ``"per_row"``: every row writes at its OWN next slot via a
          B-row scatter (``gpt._update_decode_cache`` ``cache_slots``
          mode). No frontier, no holes past a request's prompt bucket,
          and NO compaction ever — the paged-KV property that matters
          on this engine (slots are reused in place; a request's
          lifetime is bounded by its own prompt+budget, not by the
          stream's). Liveness is simply prompt_width + max_new_tokens
          <= max_seq_len. Preferred for long mixed streams.
        - ``"paged"``: per_row's write discipline over a BLOCK POOL
          (models/kv_blocks.py): ``kv_block_size`` tokens per block,
          ``kv_pool_blocks`` blocks total (0 = the dense equivalent,
          ``batch_size * L/bs + 1``; size it smaller to serve the same
          batch in less HBM). Each slot holds a block table; the chunk
          program gathers the dense view, runs the per_row step body
          unchanged, and scatters back — greedy streams are bit-exact
          with both dense layouts. Admission allocates ``ceil((bucket
          + cap)/bs)`` blocks (bounded by free blocks, NOT free
          slots); a registered prefix's fully-covered blocks are
          shared refcounted across rows (copy-on-write: decode writes
          start past the prefix, the partial tail block is the
          per-row copy), and idle prefix blocks evict LRU under pool
          pressure.

        ``overlap`` selects the double-buffered scheduler round (the
        default): chunk N+1 is dispatched before chunk N's results are
        synced, and the host's emission/retirement/admission runs while
        the device executes. ``overlap=False`` keeps the host-serial
        round (the pre-pipeline behavior; the bench's A/B baseline).
        ``auto_chunk`` lets the engine retune ``decode_chunk`` between
        dispatches from the measured host fraction.
        """
        cfg = model.config
        L = cfg.max_seq_len
        if cache_layout not in ("frontier", "per_row", "paged"):
            raise ValueError(
                f"cache_layout {cache_layout!r}: frontier | per_row | "
                f"paged"
            )
        self.layout = cache_layout
        if cache_layout in ("per_row", "paged"):
            # per-row liveness: each request lives in its own slots
            if prompt_width + sampling.max_new_tokens > L:
                raise ValueError(
                    f"{cache_layout} liveness: prompt_width + "
                    f"max_new_tokens = "
                    f"{prompt_width + sampling.max_new_tokens} > "
                    f"max_seq_len {L}"
                )
        else:
            # Liveness: the worst compacted frontier is the aligned
            # longest possible history (prompt + full budget); after it
            # there must still be room for a whole request's decode AND
            # for the next chunk's writes — otherwise compaction can
            # strand the stream (or the chunk would write past the
            # cache end, which dynamic_update_slice silently CLAMPS
            # into valid slots).
            worst = self._align(prompt_width + sampling.max_new_tokens)
            need = worst + max(sampling.max_new_tokens, decode_chunk)
            if need > L:
                raise ValueError(
                    f"continuous batching liveness: aligned(prompt_width"
                    f" + max_new_tokens) + max(max_new_tokens, "
                    f"decode_chunk) = {need} > max_seq_len {L}"
                )
        self.model = model
        self.params = params
        self.s = sampling
        self.mesh = mesh
        self.rules = rules
        self.B = batch_size
        self.Pw = prompt_width
        self.L = L
        self.d = decode_chunk
        self.overlap = bool(overlap)
        # double-buffer queue: chunks dispatched but not yet synced /
        # emitted. Each entry is (output futures..., done futures, the
        # per-slot uid snapshot AT DISPATCH — emission only credits a
        # slot whose uid still matches, so a cancel + re-admit during
        # the one-chunk lag can never leak another request's tokens).
        self._inflight: List[tuple] = []
        # tokens emitted by drains OUTSIDE a step (swap adoption):
        # folded into the next step()'s return so the per-call count
        # never silently drops a chunk
        self._drained_uncounted = 0
        self.swap_latency_s: Optional[float] = None
        self._pending_params = None  # in-flight async weight swap
        self._pending_t0 = 0.0
        # A failed swap (device transfer error, poisoned payload) is
        # ABORTED, not served: the engine keeps the old weights, clears
        # the pending state so the pipeline never wedges waiting on a
        # transfer that will not land, and surfaces the failure here.
        self.swap_failures = 0
        self.last_swap_error: Optional[str] = None
        self._uid = 0
        # (uid, tokens, submit_t, cap, prefix_id)
        self._queue: List[tuple] = []
        self._slots = [_Slot() for _ in range(batch_size)]
        self._completions: List[Completion] = []
        self._compact_fns: Dict[int, Callable] = {}
        # eager admission prefill (overlapped round): queued requests'
        # prompt rows computed WHILE a decode chunk is in flight, so
        # admission later pays only the cheap insert. Keyed by uid;
        # dropped on weight swap (stale-weight KV) and on cancel.
        self._prefilled: Dict[int, tuple] = {}
        # prefix caching: registered token lists + their lazily built
        # device row states (dropped on weight swap — stale KV would
        # silently serve the OLD model's prefix encoding)
        self._prefixes: Dict[int, List[int]] = {}
        self._prefix_states: Dict[int, tuple] = {}
        self._next_prefix_id = 0
        # host/device phase accounting: every scheduler round stamps
        # admission / prefill / decode_dispatch / host_sync /
        # retirement spans; attribution.phases reduces them to
        # serving_host_frac (the VERDICT r5 #4 unmeasured gap)
        self.phases = PhaseAccumulator()
        # rolling completion-latency window: (retire_t, total_s,
        # emitted tokens) per finished request. Sized to smooth over
        # bursts while still tracking weight-swap / load regime changes
        # within a few hundred requests; feeds the p50/p95 + tokens/s
        # stats the fleet gateway routes on and the autoscaler scales on
        self._lat_window: deque = deque(maxlen=256)
        self.completed_total = 0
        # paged-layout accounting (zeroed-but-present in every layout
        # so stats()/healthz keys stay uniform across a mixed fleet)
        self.kv_block_size = int(kv_block_size)
        self.prefix_hits = 0
        self.alloc_failures = 0
        self.prefix_evictions = 0
        if cache_layout == "paged":
            bs = self.kv_block_size
            if bs < 1 or L % bs != 0:
                raise ValueError(
                    f"kv_block_size {bs} must divide max_seq_len {L}"
                )
            self._nb = L // bs  # block-table width (blocks per row)
            n = int(kv_pool_blocks) or batch_size * self._nb + 1
            worst = kv_blocks.blocks_for(
                self.Pw + sampling.max_new_tokens, bs
            )
            if worst > n - 1:
                raise ValueError(
                    f"kv_pool_blocks {n}: a worst-case request needs "
                    f"{worst} blocks but only {n - 1} are allocatable "
                    f"(block 0 is the trash block)"
                )
            self._pool = kv_blocks.BlockPool(n, bs)
            self._row_blocks: Dict[int, List[int]] = {}
            # pid -> shared block ids, LRU-ordered for idle eviction
            self._prefix_blocks: "OrderedDict[int, List[int]]" = (
                OrderedDict()
            )
        self._build_programs()
        self._reset_device_state()
        self._tuner = _ChunkAutoTuner(self) if auto_chunk else None

    # -- device programs (compiled once each; the decode contract and
    # sampling live in generation.py — token-exactness with the
    # one-shot engine depends on sharing them, not mirroring them) ----

    def _build_programs(self):
        s, L = self.s, self.L
        model = self.model

        def prefill_row(params, toks, mask):
            """[1, W] prompt → (row cache, last logits, last pos,
            row kv_valid)."""
            cache, last_logits, last_pos, kv_valid = prefill_prompt(
                model, params, toks, mask
            )
            return cache, last_logits[0], last_pos[0], kv_valid[0]

        def continue_prefill_row(
            params, row_cache, toks, mask, row_kv, last_pos, start
        ):
            """Extend a stored prefix row cache with a LEFT-padded
            [1, W] suffix at slots [start, start+W) — prefix caching's
            device half. ``start`` (static: one compile per bucket
            pair) is the prefix's bucket width = the row cache's write
            index; positions continue the prefix's real-token count.
            The stored prefix cache is immutable — every admission
            derives a fresh row from it."""
            W = toks.shape[1]
            positions = last_pos + jnp.cumsum(
                mask.astype(jnp.int32), axis=1
            )
            kvv = row_kv[None, :].at[:, start:start + W].set(mask)
            logits, cache = decode_apply(
                model, params, row_cache, toks, positions, kvv
            )
            return (
                cache,
                logits[0, -1].astype(jnp.float32),
                positions[0, -1],
                kvv[0],
            )

        def admit(state, row_cache, row_logits, row_pos, row_kv,
                  row_allow, slot, next_slot, cap):
            """Insert a prefilled row at ``slot`` (traced — one compile
            covers every slot). The batch cache's shared frontier scalar
            is kept; the row's KV live at low slots, the gap up to the
            frontier is kv_valid=False holes (frontier layout) or
            nothing (per-row layout: the row's own write slot restarts
            at ``next_slot`` = its prompt bucket width). ``cap`` arms
            the row's DEVICE-side emission budget: the chunk fn
            decrements it per emitted token and done-masks the row at
            zero, so cap enforcement cannot lag the device (the
            overlapped round's one-chunk window)."""
            (cache, kv_valid, last_logits, cur_pos, allow, budget, done,
             row_f) = state
            cache = ContinuousBatchingEngine._insert_row(
                cache, row_cache, slot
            )
            return (
                cache,
                kv_valid.at[slot].set(row_kv),
                last_logits.at[slot].set(row_logits),
                cur_pos.at[slot].set(row_pos),
                allow.at[slot].set(row_allow),
                budget.at[slot].set(cap),
                done.at[slot].set(False),
                row_f.at[slot].set(next_slot),
            )

        def make_decode_chunk(layout: str, d: int):
            """Build the d-step decode program for one layout; returns
            stacked (toks, emits, logps) [d, B] and the advanced state.
            ONE step body serves every layout (the sampling contract,
            kv_valid handling, and logits dtype must never diverge
            between them — token-exactness in each layout is proven
            against the same one-shot engine): ``layout`` only selects
            the write-slot source and, for ``paged``, wraps the body in
            a block-table gather/scatter. Frontier layout: all rows
            write at the stream-wide ``frontier + t`` (the per-row
            frontier in the state rides along untouched). Per-row
            layout: each row writes at its own frontier
            (``cache_slots`` scatter); done/empty rows keep stepping on
            pad (static shapes) with their write slot parked clamped at
            L-1 — their kv bit and cache row are fully replaced at the
            next admission, so the parked writes are invisible. Paged
            layout: the state's cache element is ``(pool, tables)``;
            the chunk gathers the dense [B, L] view by block table,
            runs the per_row body on it unchanged (bit-exactness is
            structural, not re-proven), and scatters the advanced view
            back — one dispatch per chunk, same as the dense layouts.
            A retired slot's table is parked on the trash block, so
            its clamped writes can never touch a re-allocated block.

            Per-row stop enforcement is ON THE DEVICE: each row carries
            a remaining-emission budget (its request cap), decremented
            per emitted token; at zero the row is done-masked exactly
            like EOS. The host never needs to intervene to stop a row,
            which is what makes dispatching chunk N+1 before reading
            chunk N safe — a capped row cannot emit past its cap or
            consume liveness headroom during the lag window."""

            per_row = layout != "frontier"

            def chunk(params, state, frontier, rng):
                if layout == "paged":
                    (pool, tables) = state[0]
                    state = (
                        kv_blocks.gather_cache(pool, tables), *state[1:]
                    )

                def step(carry, t):
                    (cache, kv_valid, last_logits, cur_pos, allow,
                     budget, done, row_f, rng) = carry
                    rng, sub = jax.random.split(rng)
                    # per-request constrained decoding (RL action
                    # spaces): sampling AND behavior logprobs come from
                    # the masked distribution — what the policy can
                    # actually emit. An all-True row is a no-op.
                    tok, emit, tok_logp, done = sample_step(
                        jnp.where(allow, last_logits, -jnp.inf), done,
                        sub, s,
                    )
                    # device-side cap: the token that exhausts the
                    # budget is still emitted (host parity: emit while
                    # count < cap), then the row is done
                    emit = emit & (budget > 0)
                    budget = budget - emit.astype(jnp.int32)
                    done = done | (budget <= 0)
                    if per_row:
                        write_slots = jnp.minimum(row_f, L - 1)
                        slot_hits = (
                            jnp.arange(L)[None, :] == write_slots[:, None]
                        )
                        row_f = row_f + 1
                    else:
                        write_slots = None
                        slot_hits = (
                            jnp.arange(L)[None, :] == frontier + t
                        )
                    kv_valid = kv_valid | slot_hits
                    pos = cur_pos + 1
                    logits, cache = decode_apply(
                        model, params, cache, tok[:, None], pos[:, None],
                        kv_valid, cache_slots=write_slots,
                    )
                    return (
                        cache,
                        kv_valid,
                        logits[:, 0].astype(jnp.float32),
                        pos,
                        allow,
                        budget,
                        done,
                        row_f,
                        rng,
                    ), (tok, emit, tok_logp)

                carry, out = jax.lax.scan(
                    step, (*state, rng), jnp.arange(d)
                )
                new_state = carry[:-1]
                if layout == "paged":
                    new_state = (
                        (
                            kv_blocks.scatter_cache(
                                pool, tables, new_state[0]
                            ),
                            tables,
                        ),
                        *new_state[1:],
                    )
                return new_state, out

            return chunk

        def admit_many(state, rows, slots, next_slots, caps):
            """Burst admission: K row inserts in ONE dispatch. A wave
            of slots tends to retire together (equal caps), so the
            scheduler frequently admits K rows back-to-back — K
            separate admit calls cost K jit dispatches of the full
            batch state (~1 ms each on CPU), the dominant host-serial
            cost left in the overlapped round. Row shapes are
            width-independent ([1, L] caches), so jax re-traces only
            per distinct K (at most B traces)."""
            for row, slot, nxt, cap in zip(rows, slots, next_slots,
                                           caps):
                state = admit(state, *row, slot, nxt, cap)
            return state

        def paged_admit(state, row_cache, row_logits, row_pos, row_kv,
                        row_allow, slot, next_slot, cap, table_row):
            """Paged-layout insert: scatter the prefilled [1, L] row
            into ITS freshly planned blocks (``table_row``, trash-
            padded past its coverage) and point the slot's table at
            them. Shared prefix blocks in the table receive the row's
            prefix values — bitwise identical to every other sharer's
            (all derive from the one stored prefix state), so the
            overwrite is a semantic no-op and COW needs no masking."""
            (pg, kv_valid, last_logits, cur_pos, allow, budget, done,
             row_f) = state
            pool, tables = pg
            pool = kv_blocks.scatter_row(pool, table_row, row_cache)
            tables = tables.at[slot].set(table_row)
            return (
                (pool, tables),
                kv_valid.at[slot].set(row_kv),
                last_logits.at[slot].set(row_logits),
                cur_pos.at[slot].set(row_pos),
                allow.at[slot].set(row_allow),
                budget.at[slot].set(cap),
                done.at[slot].set(False),
                row_f.at[slot].set(next_slot),
            )

        def paged_admit_many(state, rows, slots, next_slots, caps,
                             table_rows):
            for row, slot, nxt, cap, tr in zip(
                rows, slots, next_slots, caps, table_rows
            ):
                state = paged_admit(state, *row, slot, nxt, cap, tr)
            return state

        self._prefill_fn = jax.jit(prefill_row)
        self._continue_fn = jax.jit(continue_prefill_row, static_argnums=6)
        if self.layout == "paged":
            self._admit_fn = jax.jit(paged_admit)
            self._admit_many_fn = jax.jit(paged_admit_many)
        else:
            self._admit_fn = jax.jit(admit)
            self._admit_many_fn = jax.jit(admit_many)
        # chunk programs are cached per (layout, d): the auto-tuner
        # changes d between dispatches and each length is one compile
        self._chunk_src = make_decode_chunk
        self._chunk_fns: Dict[tuple, Callable] = {}

        def compact(params, toks, mask):
            """Batched re-prefill of every live row's history into a
            fresh cache: frontier drops to the aligned width W."""
            cache, last_logits, last_pos, kv_valid = prefill_prompt(
                model, params, toks, mask
            )
            return cache, kv_valid, last_logits, last_pos

        self._compact_src = compact

    _NULL_CTX = contextlib.nullcontext()

    def _ctx(self):
        """Mesh + logical-rule contexts around every device call in
        SPMD mode (sharding constraints resolve at trace time, the mesh
        must be active at call time); no-op single-device. On the hot
        path twice per round (admission + dispatch) — the no-op case
        must stay allocation-free."""
        if self.mesh is None:
            return self._NULL_CTX
        from ..parallel.mesh import current_mesh
        from ..parallel.sharding import apply_rules

        stack = contextlib.ExitStack()
        stack.enter_context(self.mesh)
        stack.enter_context(apply_rules(self.rules))
        stack.enter_context(current_mesh(self.mesh))
        return stack

    def _i32(self, v: int):
        """Cached device scalar: ``jnp.int32(v)`` dispatches a
        conversion op per call (~0.2 ms on CPU), and the scheduler
        passes the same few slot/width/cap/frontier values every
        round — host time the dispatch path does not need to pay."""
        cache = self.__dict__.setdefault("_i32_cache", {})
        arr = cache.get(v)
        if arr is None:
            arr = cache[v] = jnp.int32(v)
        return arr

    def _compact_for(self, width):
        if width not in self._compact_fns:
            self._compact_fns[width] = jax.jit(self._compact_src)
        return self._compact_fns[width]

    def _chunk_for(self, d: int) -> Callable:
        key = (self.layout, d)
        if key not in self._chunk_fns:
            self._chunk_fns[key] = jax.jit(self._chunk_src(*key))
        return self._chunk_fns[key]

    @staticmethod
    def _set_cache_frontier(cache, f: int):
        """Pin the cache's shared write-index scalars (one per layer).
        Decode writes land at the frontier for EVERY row, so it must
        never sit below prompt_width — admitted prompts' KV live at
        slots [0, W) with W <= Pw and would be overwritten."""
        return jax.tree_util.tree_map(
            lambda b: jnp.asarray(f, b.dtype) if b.ndim == 0 else b, cache
        )

    def _reset_device_state(self):
        V = self.model.config.vocab_size
        self._frontier = self.Pw  # decode writes start past prompt KV
        if self.layout == "paged":
            # fresh pool: each dense cache leaf (B, L, ...) becomes
            # (num_blocks, block_size, ...); 0-d write-index scalars
            # stay pinned like the dense layouts'. Host allocator and
            # block tables restart with it.
            bs = self.kv_block_size
            template = init_cache(self.model, 1)
            pool = jax.tree_util.tree_map(
                lambda leaf: (
                    jnp.asarray(self._frontier, leaf.dtype)
                    if leaf.ndim == 0
                    else jnp.zeros(
                        (self._pool.num_blocks, bs) + leaf.shape[2:],
                        leaf.dtype,
                    )
                ),
                template,
            )
            tables = jnp.zeros((self.B, self._nb), jnp.int32)
            cache = (pool, tables)
            self._pool = kv_blocks.BlockPool(self._pool.num_blocks, bs)
            self._row_blocks.clear()
            self._prefix_blocks.clear()
        else:
            cache = self._set_cache_frontier(
                init_cache(self.model, self.B), self._frontier
            )
        self._state = (
            cache,
            jnp.zeros((self.B, self.L), bool),
            jnp.full((self.B, V), -1e9, jnp.float32),
            jnp.zeros((self.B,), jnp.int32),
            jnp.ones((self.B, V), bool),  # per-row allowed-token mask
            jnp.zeros((self.B,), jnp.int32),  # per-row emission budget
            jnp.ones((self.B,), bool),  # empty slots: done (emit pad)
            jnp.zeros((self.B,), jnp.int32),  # per-row write frontier
        )

    # -- host scheduler -------------------------------------------------

    def register_prefix(self, tokens: List[int]) -> int:
        """Register a shared prompt prefix (system prompt). Requests
        submitted with the returned id prefill ONLY their suffix — the
        prefix's KV is computed once per weight version and reused for
        every admission (vLLM's prefix-caching capability). The device
        state is built lazily on first use, so registration is cheap
        and weight swaps just invalidate."""
        if not tokens:
            raise ValueError("empty prefix")
        # the STORED state occupies the prefix's bucket width — a
        # prefix whose bucket rounds up to Pw would register fine yet
        # reject every submit
        if self._bucket_width(len(tokens)) >= self.Pw:
            raise ValueError(
                f"prefix bucket width {self._bucket_width(len(tokens))} "
                f"leaves no room for a suffix within prompt_width "
                f"{self.Pw}"
            )
        pid = self._next_prefix_id
        self._next_prefix_id += 1
        self._prefixes[pid] = list(tokens)
        return pid

    def _prefix_state(self, pid: int) -> tuple:
        """(row cache, last logits, last pos, row kv_valid, bucket
        width) for a registered prefix at the CURRENT weights."""
        if pid not in self._prefix_states:
            prefix = self._prefixes[pid]
            width = self._bucket_width(len(prefix))
            toks, mask = self._pad_rows([prefix], width)
            with self._ctx():
                row = self._prefill_fn(self.params, toks, mask)
            self._prefix_states[pid] = (*row, width)
        return self._prefix_states[pid]

    def unregister_prefix(self, prefix_id: int) -> None:
        """Drop a registered prefix (the gateway's prefix-GC path).
        Refcount-aware: the registry's hold on the prefix's shared
        blocks is released, but blocks still referenced by live rows
        stay allocated until those rows retire. Refuses while QUEUED
        requests still reference the id (their admission would KeyError
        mid-flight); live decoding rows are fine — their KV was built
        at admission and never looks the prefix up again."""
        if prefix_id not in self._prefixes:
            raise KeyError(f"unknown prefix_id {prefix_id}")
        if any(item[4] == prefix_id for item in self._queue):
            raise ValueError(
                f"prefix_id {prefix_id} still referenced by queued "
                f"requests"
            )
        del self._prefixes[prefix_id]
        self._prefix_states.pop(prefix_id, None)
        if self.layout == "paged":
            ids = self._prefix_blocks.pop(prefix_id, None)
            if ids:
                self._pool.free(ids)

    # -- prefill/decode disaggregation ---------------------------------

    def export_prefill(self, tokens: List[int]) -> Dict:
        """PREFILL-role half of disaggregation: run the prompt's
        prefill here and return the row as a JSON-safe hand-off
        payload (see :func:`kv_blocks.pack_row_state`). The decode
        replica admits it via :meth:`submit_prefilled` and pays only
        the insert — long prompts stop stalling its decode rounds."""
        if not tokens:
            raise ValueError("empty prompt")
        if len(tokens) > self.Pw:
            raise ValueError(
                f"prompt length {len(tokens)} > prompt_width {self.Pw}"
            )
        width = self._bucket_width(len(tokens))
        toks, mask = self._pad_rows([tokens], width)
        with self._ctx():
            row = self._prefill_fn(self.params, toks, mask)
        row = jax.device_get(row)
        return kv_blocks.pack_row_state(*row, width, tokens)

    def submit_prefilled(
        self,
        payload: Dict,
        max_new_tokens: Optional[int] = None,
        allowed_tokens: Optional[List[int]] = None,
    ) -> int:
        """DECODE-role half of disaggregation: enqueue a request whose
        prefill already ran on a prefill replica. The payload is shape-
        validated against THIS engine's cache template (mismatched
        model config → ValueError, never a corrupt row) and staged in
        ``self._prefilled`` — admission pays only the insert program.
        A weight swap between staging and admission clears the staged
        row and the request gracefully RE-prefills from its prompt
        tokens at the new weights (the payload carries them)."""
        (row_cache, row_logits, row_pos, row_kv, width, prompt) = (
            kv_blocks.unpack_row_state(
                payload, init_cache(self.model, 1)
            )
        )
        if width > self.Pw or width != self._bucket_width(len(prompt)):
            raise ValueError(
                f"handoff width {width} inconsistent with prompt "
                f"length {len(prompt)} under prompt_width {self.Pw}"
            )
        uid = self.submit(
            prompt, max_new_tokens=max_new_tokens,
            allowed_tokens=allowed_tokens,
        )
        self._prefilled[uid] = (
            row_cache, row_logits, row_pos, row_kv, width
        )
        return uid

    def submit(
        self,
        tokens: List[int],
        max_new_tokens: Optional[int] = None,
        prefix_id: Optional[int] = None,
        allowed_tokens: Optional[List[int]] = None,
    ) -> int:
        """Enqueue a request. ``max_new_tokens`` caps THIS request
        below the engine budget (``sampling.max_new_tokens``, which
        sized the cache) — a capped request retires its slot early.
        With ``prefix_id``, ``tokens`` is the SUFFIX after that
        registered prefix; the combined length must still fit
        ``prompt_width`` (prefix caching saves prefill compute, not
        cache capacity). ``allowed_tokens`` constrains THIS request's
        sampling to the given token ids (RL action spaces / structured
        output): both the sampled tokens and the behavior logprobs
        come from the masked distribution."""
        if prefix_id is not None:
            if prefix_id not in self._prefixes:
                raise ValueError(f"unknown prefix_id {prefix_id}")
            if not tokens:
                raise ValueError("prefix_id needs a non-empty suffix")
            # admission pads BOTH parts to bucket widths — the check
            # must bound the admitted row width, not the raw lengths
            # (a raw-length check admits rows wider than Pw, and
            # decode writes then silently corrupt the suffix KV)
            total = self._bucket_width(
                len(self._prefixes[prefix_id])
            ) + self._bucket_width(len(tokens))
            if total > self.Pw:
                raise ValueError(
                    f"prefix bucket + suffix bucket = {total} > "
                    f"prompt_width {self.Pw}"
                )
        elif len(tokens) > self.Pw:
            raise ValueError(
                f"prompt length {len(tokens)} > prompt_width {self.Pw}"
            )
        cap = self.s.max_new_tokens
        if max_new_tokens is not None:
            if not 1 <= max_new_tokens <= cap:
                raise ValueError(
                    f"max_new_tokens {max_new_tokens} outside [1, {cap}] "
                    f"(the engine's cache budget)"
                )
            cap = max_new_tokens
        if allowed_tokens is not None:
            V = self.model.config.vocab_size
            allowed_tokens = sorted(set(int(t) for t in allowed_tokens))
            if not allowed_tokens:
                raise ValueError("allowed_tokens must not be empty")
            if allowed_tokens[0] < 0 or allowed_tokens[-1] >= V:
                raise ValueError(
                    f"allowed_tokens outside [0, {V})"
                )
        uid = self._uid
        self._uid += 1
        self._queue.append(
            (uid, list(tokens), time.perf_counter(), cap, prefix_id,
             allowed_tokens)
        )
        return uid

    def set_params(self, params) -> float:
        """Hot-swap weights between chunks (same pytree shapes — no
        recompile). Returns the swap latency: the time to make the new
        params device-resident and adopted for the next chunk. Blocks
        the caller for the full H2D transfer — use
        :meth:`set_params_async` to hide the transfer behind ongoing
        decode instead."""
        self.set_params_async(params)
        jax.block_until_ready(self._pending_params)
        self._maybe_adopt_pending()
        return self.swap_latency_s

    def set_params_async(self, params) -> None:
        """Begin a NON-blocking weight swap: ``jax.device_put`` only
        enqueues the H2D transfer, so it proceeds behind ongoing decode
        chunks, and the engine adopts the new weights at the first
        ``step()`` boundary where every leaf has landed — a WeightBus
        push never stalls the rollout loop (the measured transfer is
        ~12 s for 124M params over the tunneled chip; blocking that
        long mid-decode is the exact stall this avoids). A second call
        before adoption supersedes the first (latest weights win).

        A transfer that fails to even enqueue (mismatched payload, a
        dead device) ABORTS the swap: the engine keeps serving the old
        weights, ``swap_pending`` clears, and the failure is surfaced
        via :meth:`stats` — a poisoned push must cost one swap, never
        the serving pipeline."""
        self._pending_t0 = time.perf_counter()
        try:
            faults.inject("serving.swap")
            self._pending_params = _device_put_like(params, self.params)
        except Exception as e:  # noqa: BLE001 — swap aborted, not served
            self._abort_pending_swap(e)

    def _abort_pending_swap(self, err: BaseException) -> None:
        """Drop an in-flight swap and keep the current weights."""
        self._pending_params = None
        self.swap_failures += 1
        self.last_swap_error = repr(err)[:300]
        from ..common.log import logger

        logger.error("weight swap aborted (serving old weights): %r", err)

    def _maybe_adopt_pending(self) -> bool:
        """Adopt a pending async swap if the transfer has completed —
        checked without blocking (``Array.is_ready``). In the
        overlapped scheduler, adoption first DRAINS the pipeline
        (processes any in-flight chunk): the swap lands at a point
        where host bookkeeping matches device state, so no round is
        ever split between parameter versions — the pipeline's drain
        point is the only adoption boundary. An async transfer that
        FAILED in flight (readiness probe raises) aborts the swap: old
        weights stay live, the pipeline keeps stepping."""
        pending = self._pending_params
        if pending is None:
            return False
        try:
            if not _tree_ready(pending):
                return False
        except Exception as e:  # noqa: BLE001 — failed transfer
            self._abort_pending_swap(e)
            return False
        # catch-up tokens are credited to slots/completions; the count
        # is surfaced through the next step()'s return
        self._drained_uncounted += self._drain_inflight()
        self.params = pending
        self._pending_params = None
        # stored prefix KV and eager-prefilled rows encode the OLD
        # weights — rebuild lazily / re-prefill at admission
        self._prefix_states.clear()
        self._prefilled.clear()
        if self.layout == "paged":
            # drop the registry's hold on every prefix's shared blocks:
            # the COW invariant (all sharers of a block agree on its
            # content) would break if post-swap admissions rewrote
            # blocks that pre-swap live rows still gather. Fresh blocks
            # are allocated on next use; live rows keep theirs until
            # retirement (refcounts make the order safe).
            for ids in self._prefix_blocks.values():
                if ids:
                    self._pool.free(ids)
            self._prefix_blocks.clear()
        self.swap_latency_s = time.perf_counter() - self._pending_t0
        return True

    def poll_pending_swap(self) -> bool:
        """Public adoption poll for drivers whose engine may sit IDLE:
        ``step()`` adopts pending async swaps at chunk boundaries, but
        an idle server never steps — without this poll an async swap on
        an idle engine would leave ``swap_pending`` true forever."""
        return self._maybe_adopt_pending()

    def _pad_rows(self, rows: List[List[int]], width: int):
        # generation.left_pad_prompts owns the padding convention
        return left_pad_prompts(rows, pad_id=self.s.pad_id, width=width)

    @staticmethod
    def _insert_row(batch, row, slot):
        """Insert a [1, ...] prefilled row pytree into the batch cache
        at ``slot``; 0-d leaves (shared frontier scalars) stay the
        batch's. Shared by the plain and speculative admit programs."""
        return jax.tree_util.tree_map(
            lambda b, r: (
                b
                if b.ndim == 0
                else jax.lax.dynamic_update_slice(
                    b, r.astype(b.dtype), (slot,) + (0,) * (b.ndim - 1)
                )
            ),
            batch,
            row,
        )

    @staticmethod
    def _align(n: int, unit: int = 16) -> int:
        """Compaction width alignment: bounds the number of distinct
        re-prefill program shapes to L/unit (one compile each, and
        compactions are rare) WITHOUT the overshoot of power-of-two
        bucketing, which could blow the liveness budget (a bucket can
        nearly double the longest history)."""
        return max(unit, ((n + unit - 1) // unit) * unit)

    def _bucket_width(self, n: int) -> int:
        """Bucketed prefill width: a 5-token prompt must not pay a
        [1, Pw] forward on a Pw=256 engine. jit re-specializes per
        shape, so the same program object serves every bucket (at
        most 3 compiles); KV beyond the bucket stays a hole, which
        the decode contract already masks."""
        width = self.Pw
        for b in (max(8, self.Pw // 4), max(8, self.Pw // 2)):
            if n <= b < width:
                width = b
        return width

    def _build_row(
        self, uid: int, prompt: List[int],
        prefix_id: Optional[int] = None,
        allowed_tokens: Optional[List[int]] = None,
    ):
        """Everything an admission needs short of the insert: the
        prefilled row pytree (cache, logits, pos, kv, allow), its
        bucket width, and the full token history (prefix + suffix for
        compaction). Shared by the single and the burst insert."""
        V = self.model.config.vocab_size
        if allowed_tokens is None:
            # cached: rebuilding (and re-transferring) an all-True [V]
            # mask per admission was measurable host time on the
            # admission path the overlapped round now hides
            if not hasattr(self, "_allow_all"):
                self._allow_all = jnp.ones((V,), bool)
            row_allow = self._allow_all
        else:
            row_allow = (
                jnp.zeros((V,), bool)
                .at[jnp.asarray(allowed_tokens, jnp.int32)]
                .set(True)
            )
        with self._ctx():
            if prefix_id is not None:
                # prefix caching: derive the row from the stored prefix
                # state (computed once per weight version) + a
                # suffix-only forward. A warm state is a prefix HIT —
                # the prefix's own prefill is skipped entirely (the
                # affinity signal the fleet gateway routes on).
                if prefix_id in self._prefix_states:
                    self.prefix_hits += 1
                (p_cache, p_logits, p_pos, p_kv, p_width) = (
                    self._prefix_state(prefix_id)
                )
                s_width = self._bucket_width(len(prompt))
                toks, mask = self._pad_rows([prompt], s_width)
                row_cache, row_logits, row_pos, row_kv = (
                    self._continue_fn(
                        self.params, p_cache, toks, mask, p_kv, p_pos,
                        p_width,
                    )
                )
                width = p_width + s_width
                full_prompt = self._prefixes[prefix_id] + prompt
            else:
                pre = self._prefilled.pop(uid, None)
                if pre is not None:
                    # eager prefill already ran (hidden behind an
                    # in-flight chunk): admission is only the insert
                    row_cache, row_logits, row_pos, row_kv, width = pre
                else:
                    width = self._bucket_width(len(prompt))
                    toks, mask = self._pad_rows([prompt], width)
                    row_cache, row_logits, row_pos, row_kv = (
                        self._prefill_fn(self.params, toks, mask)
                    )
                full_prompt = prompt
        row = (row_cache, row_logits, row_pos, row_kv, row_allow)
        return row, width, full_prompt

    def _admit_one(
        self, slot: int, uid: int, prompt: List[int], submit_t: float,
        cap: int, prefix_id: Optional[int] = None,
        allowed_tokens: Optional[List[int]] = None,
        table_ids: Optional[List[int]] = None,
    ):
        row, width, full_prompt = self._build_row(
            uid, prompt, prefix_id, allowed_tokens
        )
        with self._ctx():
            if self.layout == "paged":
                tr = jnp.asarray(
                    kv_blocks.build_table_row(table_ids, self._nb)
                )
                self._state = self._admit_fn(
                    self._state, *row, self._i32(slot),
                    self._i32(width), self._i32(cap), tr,
                )
                self._row_blocks[slot] = list(table_ids)
            else:
                self._state = self._admit_fn(
                    self._state, *row, self._i32(slot),
                    self._i32(width), self._i32(cap),
                )
        # full prefix+suffix history: compaction (frontier layout)
        # rebuilds rows from these tokens
        self._slots[slot] = _Slot(
            uid=uid, prompt=full_prompt, submit_t=submit_t, cap=cap,
            admit_t=time.perf_counter(),
        )

    # -- paged block planning (host side of admission) ------------------

    def _planned_width(
        self, uid: int, prompt: List[int], prefix_id: Optional[int]
    ) -> int:
        """The bucket width _build_row WILL use, computed without
        device work — block planning must reserve exactly what the
        insert covers."""
        pre = self._prefilled.get(uid)
        if pre is not None:
            return pre[4]
        if prefix_id is not None:
            return self._bucket_width(
                len(self._prefixes[prefix_id])
            ) + self._bucket_width(len(prompt))
        return self._bucket_width(len(prompt))

    def _alloc_blocks(self, n: int) -> Optional[List[int]]:
        """Pool alloc with idle-prefix eviction as the backpressure
        valve: registered prefixes whose shared blocks no live row
        holds are evicted LRU-first until the allocation fits (their
        device state survives — the next use just re-allocates)."""
        ids = self._pool.alloc(n)
        while ids is None and self._evict_idle_prefix():
            ids = self._pool.alloc(n)
        return ids

    def _evict_idle_prefix(self) -> bool:
        for pid, ids in self._prefix_blocks.items():  # LRU order
            if all(self._pool.refcount(b) == 1 for b in ids):
                del self._prefix_blocks[pid]
                if ids:
                    self._pool.free(ids)
                self.prefix_evictions += 1
                return True
        return False

    def _prefix_shared_ids(self, pid: int) -> Optional[List[int]]:
        """The prefix's shareable blocks — the ones FULLY covered by
        its bucket width (the partial tail block is per-row private:
        the copy in copy-on-write). Allocated on first paged use and
        held by the registry at refcount 1 so they stay warm between
        rows; idle sets are LRU-evictable under pool pressure."""
        ids = self._prefix_blocks.get(pid)
        if ids is None:
            n = self._bucket_width(
                len(self._prefixes[pid])
            ) // self.kv_block_size
            ids = self._alloc_blocks(n) if n else []
            if ids is None:
                return None
            self._prefix_blocks[pid] = ids
        self._prefix_blocks.move_to_end(pid)
        return ids

    def _plan_blocks(self, uid, prompt, cap, prefix_id):
        """Plan one admission's block table: shared prefix blocks plus
        fresh private blocks covering positions [0, width + cap).
        Returns the table's block ids, or None when the pool cannot
        cover the request — the caller leaves it QUEUED and retries
        as retiring rows free blocks (admission bounded by blocks,
        never an OOM and never a wedge). The ``kv.alloc`` chaos point
        fires here: an injected error is exactly a failed allocation
        and takes the same bounded path."""
        ncov = kv_blocks.blocks_for(
            self._planned_width(uid, prompt, prefix_id) + cap,
            self.kv_block_size,
        )
        try:
            faults.inject(
                "kv.alloc", need=ncov, free=self._pool.blocks_free
            )
        except faults.FaultInjectedError:
            self.alloc_failures += 1
            return None
        shared: List[int] = []
        if prefix_id is not None:
            shared = self._prefix_shared_ids(prefix_id)
            if shared is None:
                self.alloc_failures += 1
                return None
            shared = shared[:ncov]
        priv = self._alloc_blocks(ncov - len(shared))
        if priv is None:
            self.alloc_failures += 1
            return None
        self._pool.share(shared)
        return shared + priv

    def _release_slot_blocks(self, slot: int) -> None:
        """Free a retired row's blocks (shared prefix blocks decref
        back to the registry's hold) and park the slot's table on the
        trash block, so the done row's clamped writes can never touch
        a re-allocated block. Idempotent — the retirement paths
        overlap (finalize + device retire + cancel)."""
        ids = self._row_blocks.pop(slot, None)
        if ids is None:
            return
        self._pool.free(ids)
        (pool, tables), *rest = self._state
        if not hasattr(self, "_trash_row_arr"):
            self._trash_row_arr = jnp.zeros((self._nb,), jnp.int32)
        self._state = (
            (pool, tables.at[slot].set(self._trash_row_arr)), *rest
        )

    def _finalize_slot(self, slot: int):
        """Completion bookkeeping shared by every mode: record the
        Completion (with service metrics) and free the host slot."""
        st = self._slots[slot]
        if st.uid >= 0:
            now = time.perf_counter()
            total_s = max(now - st.admit_t, 0.0)
            self._completions.append(
                Completion(
                    st.uid, st.emitted, st.logprobs,
                    queue_s=max(st.admit_t - st.submit_t, 0.0),
                    ttft_s=max(
                        (st.first_tok_t or now) - st.admit_t, 0.0
                    ),
                    total_s=total_s,
                )
            )
            self._lat_window.append((now, total_s, len(st.emitted)))
            self.completed_total += 1
        self._slots[slot] = _Slot()
        if self.layout == "paged":
            self._release_slot_blocks(slot)

    def _retire(self, slot: int):
        self._finalize_slot(slot)
        self._retire_device_slot(slot)

    def _compact(self):
        """Rebuild the cache from live histories; frontier drops from
        near-L to the longest live history's bucket width."""
        rows = [
            (st.prompt + st.emitted) if st.uid >= 0 else []
            for st in self._slots
        ]
        width = self._align(max((len(r) for r in rows), default=1))
        toks, mask = self._pad_rows(rows, width)
        with self._ctx():
            cache, kv_valid, last_logits, cur_pos = self._compact_for(
                width
            )(self.params, toks, mask)
        _, _, _, _, allow, budget, done, row_f = self._state
        # frontier never drops below Pw: future admissions put prompt
        # KV at [0, W<=Pw) and decode writes must stay clear of it.
        # budget rides through: the device counters already hold each
        # live row's remaining cap (cap minus tokens emitted so far).
        self._frontier = max(width, self.Pw)
        cache = self._set_cache_frontier(cache, self._frontier)
        self._state = (
            cache, kv_valid, last_logits, cur_pos, allow, budget, done,
            row_f,
        )

    # burst insert available (one jitted multi-row admit); the
    # speculative engine overrides admission wholesale and opts out
    _burst_admit = True

    # tpulint: hotpath — admission runs under the in-flight chunk
    def _admit_free_slots(self) -> float:
        """Fill empty slots from the queue while the budget allows;
        returns the seconds spent in the admission device path
        (prefill + admit programs). The caller stamps phases — in the
        overlapped round this whole span runs while a chunk is in
        flight and is accounted as hidden.

        The overlapped round admits a whole burst through ONE
        ``admit_many`` dispatch: a wave of equal-cap slots retires
        together, and per-row insert calls each pay full-state jit
        dispatch — the largest host-serial cost the pipeline had
        left. The synchronous baseline keeps the per-row path it
        always had."""
        # Chaos hook: a delay models a slow admission host path (the
        # overlapped round must hide it); an error surfaces to the
        # driver loop rather than silently corrupting slot state.
        faults.inject("serving.admit", queue_depth=len(self._queue))
        frontier_layout = self.layout == "frontier"
        paged = self.layout == "paged"
        burst = self.overlap and self._burst_admit
        prefill_s = 0.0
        batch = []
        for slot, st in enumerate(self._slots):
            if st.uid >= 0 or not self._queue:
                continue
            # headroom gate uses the HEAD request's own cap: a short
            # request can still slip in near the end of the cache.
            # per_row: a freed slot ALWAYS has room (per-request
            # liveness was checked at construction).
            if frontier_layout and (
                self._frontier + self._queue[0][3] > self.L
            ):
                break  # no room for this request until compaction
            table_ids = None
            if paged:
                # paged admission is bounded by free BLOCKS: plan the
                # head request's block table before popping it, so a
                # request the pool can't cover right now stays QUEUED
                # (retiring rows return blocks) — never half-admitted,
                # never an OOM, never a wedge (submit() proved it fits
                # an empty pool).
                head = self._queue[0]
                table_ids = self._plan_blocks(
                    head[0], head[1], head[3], head[4]
                )
                if table_ids is None:
                    break  # out of blocks — retry next round
            (uid, prompt, submit_t, cap, prefix_id, allowed) = (
                self._queue.pop(0)
            )
            ta = time.perf_counter()
            if not burst:
                # table_ids kwarg only when paged: subclasses override
                # _admit_one without it (they force dense layouts)
                if paged:
                    self._admit_one(
                        slot, uid, prompt, submit_t, cap, prefix_id,
                        allowed, table_ids=table_ids,
                    )
                else:
                    self._admit_one(
                        slot, uid, prompt, submit_t, cap, prefix_id,
                        allowed,
                    )
            else:
                row, width, full_prompt = self._build_row(
                    uid, prompt, prefix_id, allowed
                )
                batch.append(
                    (slot, row, width, cap, uid, full_prompt, submit_t,
                     table_ids)
                )
            prefill_s += time.perf_counter() - ta
        if batch:
            ta = time.perf_counter()
            with self._ctx():
                if paged:
                    self._state = self._admit_many_fn(
                        self._state,
                        tuple(b[1] for b in batch),
                        tuple(self._i32(b[0]) for b in batch),
                        tuple(self._i32(b[2]) for b in batch),
                        tuple(self._i32(b[3]) for b in batch),
                        tuple(
                            jnp.asarray(kv_blocks.build_table_row(
                                b[7], self._nb
                            ))
                            for b in batch
                        ),
                    )
                else:
                    self._state = self._admit_many_fn(
                        self._state,
                        tuple(b[1] for b in batch),
                        tuple(self._i32(b[0]) for b in batch),
                        tuple(self._i32(b[2]) for b in batch),
                        tuple(self._i32(b[3]) for b in batch),
                    )
            now = time.perf_counter()
            for (slot, _row, _w, cap, uid, full_prompt, submit_t,
                 table_ids) in batch:
                if paged:
                    self._row_blocks[slot] = list(table_ids)
                self._slots[slot] = _Slot(
                    uid=uid, prompt=full_prompt, submit_t=submit_t,
                    cap=cap, admit_t=now,
                )
            prefill_s += now - ta
        return prefill_s

    # tpulint: hotpath — drains happen via _drain_inflight, never inline
    def _frontier_housekeeping(self) -> int:
        """Frontier-layout cache management (no-op for per_row):
        idle-reset and compaction. Both are pipeline DRAIN points —
        compaction rebuilds the cache from host-side histories, which
        must first catch up with the device. Returns tokens emitted by
        any drain."""
        emitted = 0
        if self.layout != "frontier":
            return emitted
        if (
            not self._inflight
            and self._queue
            and all(st.uid < 0 for st in self._slots)
            and self._frontier > self.Pw
        ):
            # Nothing live but the frontier has advanced (admission
            # may be budget-blocked): a fresh cache beats dispatching
            # dead all-done chunks until the compaction threshold —
            # each one is a full device round-trip that emits zero
            # tokens.
            self._reset_device_state()
        if self._frontier + self.d > self.L:
            emitted += self._drain_inflight()
            tc = time.perf_counter()
            self._compact()  # a batched re-prefill: device work
            self.phases.add("prefill", time.perf_counter() - tc)
        return emitted

    # tpulint: hotpath — dispatch must never read the device back
    def _dispatch_round(self, rng) -> tuple:
        """Enqueue one decode chunk on the device; returns the
        in-flight record (output futures + done futures + the uid
        snapshot) without reading anything back."""
        with self._ctx():
            chunk_fn = self._chunk_for(self.d)
            if self.layout == "frontier":
                self._state, (toks, emits, logps) = chunk_fn(
                    self.params, self._state,
                    self._i32(self._frontier), rng,
                )
                self._frontier += self.d
            else:
                # frontier arg is unused in per_row (write slots come
                # from the state's per-row frontier); pass a constant
                # so the one compiled program serves every chunk
                self._state, (toks, emits, logps) = chunk_fn(
                    self.params, self._state, self._i32(0), rng
                )
        return (
            toks, emits, logps, self._state[-2],  # -2: the done flags
            [st.uid for st in self._slots],
        )

    def _emit_outputs(self, fetched, uids) -> int:
        """Credit one synced chunk's tokens to its slots and retire
        finished rows — one fused readback drove this, not per-token
        host polls. A slot whose uid changed since dispatch (cancel,
        or cancel + re-admit during the lag window) is skipped: the
        old row's emit mask is the device's own guarantee that a
        re-admitted request never sees a predecessor's tokens.
        Overridden by the speculative subclass (round-shaped
        outputs)."""
        toks, emits, logps, done = fetched
        emitted = 0
        now = time.perf_counter()
        for slot, st in enumerate(self._slots):
            if st.uid < 0 or st.uid != uids[slot]:
                continue
            sel = emits[:, slot]
            if sel.any():
                new = toks[sel, slot].tolist()
                room = st.cap - len(st.emitted)
                if room < len(new):  # belt: device budget enforces cap
                    new = new[:max(room, 0)]
                if new:
                    if not st.emitted:
                        st.first_tok_t = now
                    st.emitted.extend(int(t) for t in new)
                    st.logprobs.extend(
                        float(x)
                        for x in logps[sel, slot][: len(new)]
                    )
                    emitted += len(new)
            st.finished = bool(done[slot])
            if st.finished or len(st.emitted) >= st.cap:
                # the device already done-masked this row (budget/EOS),
                # so only the host slot needs freeing
                self._finalize_slot(slot)
        return emitted

    def _process_oldest(self) -> int:
        """Sync + emit + retire the oldest in-flight chunk. When a
        newer chunk is still in flight behind it, the host work here
        is hidden by device execution — stamped ``overlap_hidden``."""
        entry = self._inflight.pop(0)
        ts = time.perf_counter()
        fetched = jax.device_get(entry[:-1])
        t_sync = time.perf_counter()
        self.phases.add("host_sync", t_sync - ts)
        emitted = self._emit_outputs(fetched, entry[-1])
        self.phases.add(
            "overlap_hidden" if self._inflight else "retirement",
            time.perf_counter() - t_sync,
        )
        return emitted

    def _drain_inflight(self) -> int:
        """Process every dispatched-but-unread chunk (the pipeline
        drain point: host bookkeeping catches up with the device)."""
        emitted = 0
        while self._inflight:
            emitted += self._process_oldest()
        return emitted

    # tpulint: hotpath — runs behind the dispatched chunk
    def _eager_prefill(self) -> None:
        """Prefill queue-head prompts WHILE a chunk is in flight (the
        overlapped round calls this right after dispatch): prompt rows
        are computed into ``self._prefilled`` so the later admission
        pays only the insert program. At most B rows are held (each a
        [1, L] cache); prefix-path requests keep the lazy path (their
        row derives from the stored prefix state). Overridden to a
        no-op by the speculative engine, whose admission prefills two
        models and keeps the classic path."""
        if not self._queue:
            return
        held = 0
        for item in self._queue:
            if held >= self.B:
                break
            held += 1
            uid, prompt, _submit_t, _cap, prefix_id, _allowed = item
            if prefix_id is not None or uid in self._prefilled:
                continue
            width = self._bucket_width(len(prompt))
            toks, mask = self._pad_rows([prompt], width)
            with self._ctx():
                row = self._prefill_fn(self.params, toks, mask)
            self._prefilled[uid] = (*row, width)

    def _oldest_ready(self) -> bool:
        """Non-blocking: has the oldest in-flight chunk already
        finished on the device? (same readiness poll as the async
        weight swap)."""
        return bool(self._inflight) and _tree_ready(
            self._inflight[0][:-1]
        )

    # tpulint: hotpath — the scheduler round; syncs live in _process_oldest
    def step(self, rng):
        """One scheduler iteration. Returns the number of tokens
        emitted this call. Phase boundaries are stamped into
        ``self.phases`` so ``stats()`` (and the bench's attribution
        rung) can report the host/device/hidden split.

        Synchronous round (``overlap=False``): compact if out of
        headroom (frontier layout only), admit into free slots, decode
        one chunk, block on its results, retire finished rows — the
        device idles while the host schedules.

        Overlapped round (default): admit and dispatch chunk N FIRST
        (the device queue stays non-empty), then sync chunk N-1 —
        whose execution already overlapped the previous call's host
        work — and do emission/retirement while chunk N runs. Rows
        stop themselves on the device (cap budget + EOS done-mask), so
        the one-chunk lag cannot over-emit; emission is one fused
        readback of tokens+emit-mask+logps+done. Streams are
        bit-identical with the synchronous round under greedy
        sampling; with temperature > 0 the admission lag shifts which
        rng a refilled slot consumes (either stream is a valid
        sample)."""
        emitted = (
            self._step_overlapped(rng) if self.overlap
            else self._step_sync(rng)
        )
        emitted += self._drained_uncounted
        self._drained_uncounted = 0
        if self._tuner is not None:
            self._tuner.maybe_retune()
        return emitted

    # tpulint: hotpath
    def _step_sync(self, rng):
        """The host-serial round (pre-pipeline behavior, kept as the
        measured A/B baseline): dispatch, block, emit, retire."""
        t0 = time.perf_counter()
        # a completed async weight swap lands here, between chunks —
        # the non-blocking check costs ~nothing when none is pending
        self._maybe_adopt_pending()
        t_adopt = time.perf_counter()
        # housekeeping stamps its own compaction span as "prefill" —
        # exclude it from the admission bucket (double-counting it
        # would inflate serving_host_frac, the metric under test)
        self._frontier_housekeeping()
        t_hk = time.perf_counter()
        prefill_s = self._admit_free_slots()
        t_admit = time.perf_counter()
        self.phases.add("prefill", prefill_s)
        self.phases.add(
            "admission",
            (t_adopt - t0) + (t_admit - t_hk - prefill_s),
        )

        entry = self._dispatch_round(rng)
        t_disp = time.perf_counter()
        self.phases.add("decode_dispatch", t_disp - t_admit)
        # tpulint: ignore[host-sync] the sync round IS the measured
        # A/B baseline the overlapped pipeline is compared against
        fetched = jax.device_get(entry[:-1])
        t_sync = time.perf_counter()
        self.phases.add("host_sync", t_sync - t_disp)
        emitted = self._emit_outputs_sync(fetched, entry[-1])
        self.phases.add("retirement", time.perf_counter() - t_sync)
        self.phases.rounds += 1
        return emitted

    def _emit_outputs_sync(self, fetched, uids) -> int:
        """The synchronous round's per-token host loop, kept verbatim
        as the measured baseline the overlapped round's fused emission
        is A/B'd against (greedy equality between the two paths is
        under test)."""
        toks, emits, logps, done = fetched
        emitted = 0
        for slot, st in enumerate(self._slots):
            if st.uid < 0:
                continue
            for t in range(toks.shape[0]):
                if len(st.emitted) >= st.cap:
                    break
                if emits[t, slot]:
                    if not st.emitted:
                        st.first_tok_t = time.perf_counter()
                    st.emitted.append(int(toks[t, slot]))
                    st.logprobs.append(float(logps[t, slot]))
                    emitted += 1
            st.finished = bool(done[slot])
            if st.finished or len(st.emitted) >= st.cap:
                self._retire(slot)
        return emitted

    # tpulint: hotpath — every host span here runs under a chunk
    def _step_overlapped(self, rng):
        """The double-buffered round: dispatch chunk N before reading
        chunk N-1, so every host span between two dispatches runs
        under an executing chunk."""
        emitted = 0
        # adoption drains the pipeline first (_maybe_adopt_pending):
        # a landed WeightBus push costs one catch-up, never a split
        # round
        self._maybe_adopt_pending()
        emitted += self._frontier_housekeeping()
        # Zero-lag retirement: when the device already finished the
        # oldest chunk (it outran the host — the host-bound regime
        # this pipeline targets), process it BEFORE dispatching, so
        # slots it freed refill in THIS round's admission instead of
        # one chunk later. When the device is still busy, keep the
        # dispatch-first order — the queue must never drain.
        if self._oldest_ready():
            emitted += self._process_oldest()
        # admission overlaps the in-flight chunk: the prefill + admit
        # programs enqueue behind it and the host-side cost is hidden
        hidden = bool(self._inflight)
        ta = time.perf_counter()
        prefill_s = self._admit_free_slots()
        t_admit = time.perf_counter()
        if hidden:
            self.phases.add("overlap_hidden", t_admit - ta)
        else:
            self.phases.add("prefill", prefill_s)
            self.phases.add("admission", t_admit - ta - prefill_s)

        dispatched = False
        if any(st.uid >= 0 for st in self._slots):
            self._inflight.append(self._dispatch_round(rng))
            self.phases.add(
                "decode_dispatch", time.perf_counter() - t_admit
            )
            dispatched = True
            # queued requests' prompt rows prefill NOW, behind the
            # chunk just dispatched — their admission later is only
            # the insert
            tp = time.perf_counter()
            self._eager_prefill()
            self.phases.add(
                "overlap_hidden", time.perf_counter() - tp
            )
        # keep pipeline depth at one: process the previous chunk while
        # the new one runs; with nothing dispatched, drain the tail
        if len(self._inflight) > (1 if dispatched else 0):
            emitted += self._process_oldest()
        self.phases.rounds += 1
        return emitted

    @property
    def pending(self) -> bool:
        """True while any request is queued or decoding, or a
        dispatched chunk's results are still unread (the overlapped
        round's tail) — the public drain condition for callers driving
        step() themselves (e.g. to land a weight swap mid-stream)."""
        return (
            bool(self._queue)
            or any(st.uid >= 0 for st in self._slots)
            or bool(self._inflight)
        )

    def _latency_stats(self) -> Dict:
        """p50/p95 completion latency and rolling tokens/s over the
        retirement window — the latency signal the fleet gateway's
        least-loaded routing and the autoscaler consume. Snapshot
        first (one C-level copy): /healthz readers call this from
        handler threads while the driver retires slots."""
        window = list(self._lat_window)
        if not window:
            return {
                "latency_p50_s": None,
                "latency_p95_s": None,
                "tokens_per_s": None,
                "completed_total": self.completed_total,
            }
        lats = sorted(t for _, t, _ in window)
        span = max(
            time.perf_counter() - window[0][0],
            # a single just-retired request: its own service time is
            # the only defensible span (avoids an absurd rate spike)
            lats[-1],
            1e-6,
        )
        return {
            "latency_p50_s": round(lats[len(lats) // 2], 4),
            "latency_p95_s": round(
                lats[min(int(len(lats) * 0.95), len(lats) - 1)], 4
            ),
            "tokens_per_s": round(
                sum(n for _, _, n in window) / span, 2
            ),
            "completed_total": self.completed_total,
        }

    def stats(self) -> Dict:
        """Operational snapshot (served over /healthz by tpurun-serve):
        live occupancy, queue depth, per-request latency percentiles,
        and the cache configuration that determines admission
        behavior."""
        return {
            **self._latency_stats(),
            "cache_layout": self.layout,
            "overlap": self.overlap,
            "inflight_chunks": len(self._inflight),
            "decode_chunk": self.d,
            "auto_chunk_retunes": (
                self._tuner.retunes if self._tuner is not None else None
            ),
            "busy_slots": sum(1 for st in self._slots if st.uid >= 0),
            "queue_depth": len(self._queue),
            "registered_prefixes": len(self._prefixes),
            "prefix_states_cached": len(self._prefix_states),
            # paged-pool occupancy + prefix locality: the gateway's
            # affinity-routing and the autoscaler's admission signal
            # (None fields when the layout is slot-dense)
            "prefix_hits": self.prefix_hits,
            "resident_prefixes": sorted(self._prefix_states)[:64],
            "kv_block_size": (
                self.kv_block_size if self.layout == "paged" else None
            ),
            "blocks_total": (
                self._pool.blocks_total if self.layout == "paged"
                else None
            ),
            "blocks_free": (
                self._pool.blocks_free if self.layout == "paged"
                else None
            ),
            "alloc_failures": self.alloc_failures,
            "prefix_evictions": self.prefix_evictions,
            "kv_cache_int8": bool(
                getattr(self.model.config, "kv_cache_int8", False)
            ),
            "last_swap_latency_s": self.swap_latency_s,
            "swap_pending": self._pending_params is not None,
            "swap_failures": self.swap_failures,
            "last_swap_error": self.last_swap_error,
            # host/device attribution (attribution.phases): host_frac
            # plus per-phase totals, compact enough for /healthz and
            # the bench line budget
            "phase_split": self.phases.split().summary(),
        }

    def partial(self, uid: int):
        """Tokens emitted so far for a live uid, or None if the uid is
        not currently decoding (queued, finished, or unknown). Safe to
        call from other threads: emission extends the list in one
        GIL-atomic C call, so a torn read only under-reports by at
        most one chunk's tokens, which the caller's next poll
        delivers. In the overlapped round the view additionally lags
        the device by one in-flight chunk. The streaming read API —
        external callers must not reach into slot internals."""
        for st in self._slots:
            if st.uid == uid:
                return list(st.emitted)
        return None

    def cancel(self, uid: int) -> bool:
        """Abort a request (client disconnect / timeout): a queued
        request is dropped; a decoding request's slot is freed for the
        next admission (its device row keeps stepping until then —
        static shapes — but emits to nobody). No Completion is
        recorded. Returns whether the uid was found live."""
        for i, item in enumerate(self._queue):
            if item[0] == uid:
                del self._queue[i]
                self._prefilled.pop(uid, None)
                return True
        for slot, st in enumerate(self._slots):
            if st.uid == uid:
                self._slots[slot] = _Slot()
                self._retire_device_slot(slot)
                return True
        return False

    def _retire_device_slot(self, slot: int) -> None:
        """Silence a freed slot on the device until the next admission
        (the done bit makes it emit pad)."""
        state = self._state
        done_idx = len(state) - 2  # done is always second-to-last
        done = state[done_idx].at[slot].set(True)
        self._state = (
            *state[:done_idx], done, *state[done_idx + 1:]
        )
        if self.layout == "paged":
            # cancel path reaches here without _finalize_slot; the
            # release is idempotent so the retire paths can overlap
            self._release_slot_blocks(slot)

    def drain_completions(self) -> List[Completion]:
        """Hand over (and clear) finished requests, uid-ordered."""
        out, self._completions = self._completions, []
        return sorted(out, key=lambda c: c.uid)

    def run(self, prompts=None, rng=None) -> List[Completion]:
        """Drive the scheduler until every queued request completes.
        Step keys are pre-split in blocks: one ``jax.random.split``
        dispatch per 64 rounds instead of per round (the per-round
        split was measurable host-serial time on both scheduler
        paths). The keys differ from chained per-round splitting but
        are an equally valid independent stream; greedy output is
        key-independent either way."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        for p in prompts or []:
            self.submit(p)
        keys: List = []
        while self.pending:
            if not keys:
                rng, *block = jax.random.split(rng, 65)
                keys = list(block)
            self.step(keys.pop(0))
        out, self._completions = self._completions, []
        return sorted(out, key=lambda c: c.uid)


class SpeculativeBatchingEngine(ContinuousBatchingEngine):
    """Continuous batching WITH in-scheduler speculative decoding.

    vLLM-grade composition: the request-queue scheduler admits and
    retires mixed-length prompts into decode slots (per-row cache
    layout), and every device round runs speculation — the draft
    proposes ``k`` tokens per live row, the target verifies the whole
    window in ONE forward (a per-row [B, k+1] cache_slots write), and
    each row emits 1..k+1 tokens per round. Greedy only: the accepted
    prefix is provably the plain greedy output for ANY draft, so the
    stream stays token-exact with :class:`ContinuousBatchingEngine`
    (general-temperature rejection sampling lives in the one-shot
    engine, models/speculative.py).

    Never-rewind slots (speculative.py's design, applied per row):
    every round claims k+1 slots at the row's frontier; rejected
    proposals become kv_valid=False holes, and positions count only
    valid slots so RoPE/posembs stay exact. Liveness therefore needs
    ``prompt_width + (k+1) * max_new_tokens + k <= max_seq_len``.

    The draft shares the target's slot layout (its cache is written at
    the same per-row slots, one validity mask serves both); admission
    prefills BOTH models on the prompt. Prefix caching is not offered
    in this mode yet (it would need dual prefix states) — submit with
    ``prefix_id`` raises.
    """

    def __init__(
        self,
        model,
        params,
        *args,
        sampling: Optional[SamplingConfig] = None,
        batch_size: Optional[int] = None,
        prompt_width: Optional[int] = None,
        draft_model=None,
        draft_params=None,
        num_draft: int = 4,
        decode_chunk: int = 1,
        mesh=None,
        rules=None,
        overlap: bool = True,
    ):
        """Two positional shapes are accepted:

        - ``(model, params, sampling, ...)`` — self-drafting (classic);
        - ``(model, params, draft_model, draft_params, sampling, ...)``
          — the draft pair rides directly after the target pair, so a
          separate-draft engine reads like its arguments mean.

        ``draft_model``/``draft_params`` also work as keywords in
        either shape. ``decode_chunk`` is accepted for constructor
        parity with :class:`ContinuousBatchingEngine` and ignored: a
        speculative round IS the dispatch unit (each round emits 1..k+1
        tokens per row in one draft+verify exchange)."""
        def _take(name, current, value):
            # positional/keyword double-supply must raise like a
            # normal signature would, never silently prefer one
            if current is not None:
                raise TypeError(f"got multiple values for {name!r}")
            return value

        if args:
            if isinstance(args[0], SamplingConfig):
                # base-class parity: (sampling[, batch_size[,
                # prompt_width]]) positionally, like
                # ContinuousBatchingEngine
                if len(args) > 3:
                    raise TypeError(
                        "too many positional args after sampling"
                    )
                tail = args
            else:
                if draft_model is not None or draft_params is not None:
                    raise TypeError(
                        "don't mix the positional draft pair with "
                        "draft_model/draft_params keywords"
                    )
                if len(args) < 2 or len(args) > 5:
                    raise TypeError(
                        "expected (model, params, sampling, ...) or "
                        "(model, params, draft_model, draft_params, "
                        "sampling[, batch_size[, prompt_width]], ...)"
                    )
                draft_model, draft_params = args[0], args[1]
                tail = args[2:]
            if len(tail) >= 1:
                sampling = _take("sampling", sampling, tail[0])
            if len(tail) >= 2:
                batch_size = _take("batch_size", batch_size, tail[1])
            if len(tail) >= 3:
                prompt_width = _take(
                    "prompt_width", prompt_width, tail[2]
                )
        if sampling is None or batch_size is None or prompt_width is None:
            raise TypeError(
                "sampling, batch_size and prompt_width are required"
            )
        if sampling.temperature != 0.0:
            raise ValueError(
                "SpeculativeBatchingEngine is greedy-only "
                "(temperature=0); sampled speculation lives in the "
                "one-shot engine (models/speculative.py)"
            )
        self.draft_model = draft_model if draft_model is not None else model
        self._pending_draft = None  # in-flight async DRAFT swap
        self.k = int(num_draft)
        if self.k < 1:
            raise ValueError(f"num_draft {num_draft} must be >= 1")
        L = model.config.max_seq_len
        dcfg = self.draft_model.config
        if dcfg.max_seq_len != L:
            raise ValueError("draft and target must share max_seq_len")
        if dcfg.vocab_size != model.config.vocab_size:
            raise ValueError("draft and target must share the vocabulary")
        need = prompt_width + (self.k + 1) * sampling.max_new_tokens + self.k
        if need > L:
            raise ValueError(
                f"speculative serving liveness: prompt_width + "
                f"(k+1)*max_new_tokens + k = {need} > max_seq_len {L}"
            )
        super().__init__(
            model, params, sampling, batch_size, prompt_width,
            decode_chunk=1, mesh=mesh, rules=rules,
            cache_layout="per_row", overlap=overlap,
        )
        self.draft_params = (
            draft_params if draft_params is not None else self.params
        )
        # acceptance accounting (stats()/bench): drafted vs accepted
        self.rounds = 0
        self.drafted_total = 0
        self.accepted_total = 0

    # -- device programs ------------------------------------------------

    def _build_programs(self):
        super()._build_programs()
        model, draft = self.model, self.draft_model
        s, L, k = self.s, self.L, self.k

        def prefill_spec(t_params, d_params, toks, mask):
            """Prefill BOTH models on one [1, W] prompt; the window
            slots are shared, so one row kv_valid serves both caches."""
            t_cache, last_logits, last_pos, kv_valid = prefill_prompt(
                model, t_params, toks, mask
            )
            d_cache = init_cache(draft, toks.shape[0])
            positions = jnp.maximum(
                jnp.cumsum(mask.astype(jnp.int32), axis=1) - 1, 0
            )
            _, d_cache = decode_apply(
                draft, d_params, d_cache, toks, positions, kv_valid
            )
            return (
                t_cache, d_cache, last_logits[0], last_pos[0], kv_valid[0]
            )

        def admit_spec(
            state, t_row, d_row, row_logits, row_pos, row_kv, slot,
            next_slot, cap,
        ):
            (t_cache, d_cache, kv_valid, last_logits, cur_pos, budget,
             done, row_f) = state
            insert = ContinuousBatchingEngine._insert_row
            return (
                insert(t_cache, t_row, slot),
                insert(d_cache, d_row, slot),
                kv_valid.at[slot].set(row_kv),
                last_logits.at[slot].set(row_logits),
                cur_pos.at[slot].set(row_pos),
                budget.at[slot].set(cap),
                done.at[slot].set(False),
                row_f.at[slot].set(next_slot),
            )

        def spec_round(t_params, d_params, state):
            """One speculation round for the whole batch. Returns the
            advanced state plus (window tokens [B, k+1], accepted draft
            count [B], per-token target logprobs [B, k+1]) — the host
            emits window[:1 + accepted] per live row.

            Greedy: tok0 = argmax(pending logits) leads the window;
            the draft proposes k continuations; the target scores the
            window once; the accepted prefix is exactly what plain
            greedy decode would have produced, and the logits after
            the last accepted token become the next round's pending
            logits (the "bonus" position).

            Device-side cap: each row's remaining-emission budget
            clamps the accepted count so a round never emits past the
            request cap, and exhausting it done-masks the row — the
            overlapped scheduler's one-round lag cannot over-emit or
            claim window slots for a finished request."""
            (t_cache, d_cache, kv_valid, last_logits, cur_pos, budget,
             done, row_f) = state
            tok0 = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            tok0 = jnp.where(done, s.pad_id, tok0)
            lp_all = jax.nn.log_softmax(last_logits, axis=-1)
            lp0 = jnp.take_along_axis(lp_all, tok0[:, None], axis=-1)[:, 0]

            base = jnp.minimum(row_f, L - 1 - k)  # clamp: parked rows
            # draft proposes k tokens, feeding its own cache per step
            kv = kv_valid | (
                jnp.arange(L)[None, :] == base[:, None]
            )
            cur = tok0
            pos = cur_pos + 1
            d_toks = []
            dc = d_cache
            for j in range(k):
                d_logits, dc = decode_apply(
                    draft, d_params, dc, cur[:, None], pos[:, None], kv,
                    cache_slots=jnp.minimum(base + j, L - 1),
                )
                nxt = jnp.argmax(
                    d_logits[:, 0].astype(jnp.float32), axis=-1
                ).astype(jnp.int32)
                d_toks.append(nxt)
                kv = kv | (
                    jnp.arange(L)[None, :] == (base + 1 + j)[:, None]
                )
                cur = nxt
                pos = pos + 1
            # align the draft cache: write the last proposal's KV too,
            # so both caches cover slots [base, base+k]
            _, dc = decode_apply(
                draft, d_params, dc, cur[:, None], pos[:, None], kv,
                cache_slots=jnp.minimum(base + k, L - 1),
            )
            drafted = jnp.stack(d_toks, axis=1)  # [B, k]

            # target verifies [tok0, d_1..d_k] in one per-row window
            win = jnp.concatenate([tok0[:, None], drafted], axis=1)
            win_pos = (cur_pos + 1)[:, None] + jnp.arange(k + 1)[None, :]
            win_slots = jnp.minimum(
                base[:, None] + jnp.arange(k + 1)[None, :], L - 1
            )
            t_logits, tc = decode_apply(
                model, t_params, t_cache, win, win_pos, kv,
                cache_slots=win_slots,
            )
            t_logits = t_logits.astype(jnp.float32)

            ok = drafted == jnp.argmax(t_logits[:, :k], axis=-1)
            a = jnp.where(
                ok.all(axis=1), k,
                jnp.argmin(ok.astype(jnp.int32), axis=1),
            )
            a = jnp.where(done, 0, a)
            # device-side cap: a live row has budget >= 1; accept at
            # most budget-1 drafts so tok0 + accepted <= budget
            a = jnp.minimum(a, jnp.maximum(budget - 1, 0))
            n_emit = jnp.where(done, 0, a + 1)

            # logprobs for the emitted tokens: tok0 under the pending
            # dist, d_j under the verify dist at position j-1
            lp_win = jnp.take_along_axis(
                jax.nn.log_softmax(t_logits[:, :k], axis=-1),
                drafted[:, :, None],
                axis=-1,
            )[:, :, 0]
            logps = jnp.concatenate([lp0[:, None], lp_win], axis=1)

            # eos among the emitted prefix finishes the row
            emit_idx = jnp.arange(k + 1)[None, :]
            emitted_mask = (emit_idx <= a[:, None]) & ~done[:, None]
            if s.eos_id >= 0:
                eos_hits = (win == s.eos_id) & emitted_mask
                done = done | eos_hits.any(axis=1)

            # keep kv bits only for the accepted window prefix: slots
            # base..base+a stay valid, rejected slots become holes
            arange_l = jnp.arange(L)[None, :]
            rejected = (arange_l > (base + a)[:, None]) & (
                arange_l <= (base + k)[:, None]
            )
            kv = kv & ~rejected

            # pending logits = after the last accepted token
            nxt_logits = jnp.take_along_axis(
                t_logits, a[:, None, None], axis=1
            )[:, 0]
            # budget burn-down AFTER the eos update: an eos'd row is
            # already done, so its residual budget is irrelevant
            budget = jnp.maximum(budget - n_emit, 0)
            done = done | (budget <= 0)
            return (
                tc, dc, kv, nxt_logits, cur_pos + 1 + a, budget, done,
                row_f + k + 1,
            ), (win, a, logps)

        self._prefill_spec_fn = jax.jit(prefill_spec)
        self._admit_spec_fn = jax.jit(admit_spec)
        self._round_fn = jax.jit(spec_round)

    def _reset_device_state(self):
        V = self.model.config.vocab_size
        self._frontier = self.Pw  # unused (per-row), kept for stats
        self._state = (
            init_cache(self.model, self.B),
            init_cache(self.draft_model, self.B),
            jnp.zeros((self.B, self.L), bool),
            jnp.full((self.B, V), -1e9, jnp.float32),
            jnp.zeros((self.B,), jnp.int32),
            jnp.zeros((self.B,), jnp.int32),  # per-row emission budget
            jnp.ones((self.B,), bool),
            jnp.zeros((self.B,), jnp.int32),
        )

    # -- host scheduler -------------------------------------------------

    _NO_PREFIX = (
        "prefix caching is not available in speculative serving"
    )

    def register_prefix(self, tokens):
        # fail at REGISTRATION (a ValueError maps to HTTP 400), not on
        # every later completion
        raise ValueError(self._NO_PREFIX)

    def submit(self, tokens, max_new_tokens=None, prefix_id=None,
               allowed_tokens=None):
        if prefix_id is not None:
            raise ValueError(self._NO_PREFIX)
        if allowed_tokens is not None:
            raise ValueError(
                "allowed_tokens is not available in speculative serving"
            )
        return super().submit(tokens, max_new_tokens=max_new_tokens)

    def set_params(self, params, draft_params=None) -> float:
        """Swap target weights (and optionally the draft's). A self-
        drafting engine whose draft_params were the target's follows
        the target automatically."""
        self.set_params_async(params, draft_params=draft_params)
        jax.block_until_ready(self._pending_params)
        if self._pending_draft is not None:
            jax.block_until_ready(self._pending_draft)
        self._maybe_adopt_pending()
        return self.swap_latency_s

    def set_params_async(self, params, draft_params=None) -> None:
        """Non-blocking swap of the target AND (optionally) the draft:
        both transfers are enqueued now, and adoption is ATOMIC at a
        round boundary — the engine never runs a round with a new
        target against an old explicit draft (their logits disagree and
        acceptance collapses for that round). A self-following draft
        (draft_params is params) keeps following without a transfer.
        Superseding pushes compose per component: a later target-only
        call keeps the latest draft push pending, so target and draft
        still land together.

        Like every engine method, this must be called from the one
        driver thread that owns the engine (the serving daemon routes
        all swaps through its inbox). The draft is staged BEFORE the
        target as cheap defense in depth: adoption gates on the target
        being pending, so an out-of-contract concurrent poll between
        the two stores sees draft-without-target and adopts nothing,
        rather than target-without-draft."""
        if draft_params is not None:
            try:
                self._pending_draft = _device_put_like(
                    draft_params, self.draft_params
                )
            except Exception as e:  # noqa: BLE001 — swap aborted
                self._abort_pending_swap(e)
                return
        super().set_params_async(params)

    def _abort_pending_swap(self, err: BaseException) -> None:
        # The pair aborts together: a new draft adopted against the old
        # target (or vice versa) collapses acceptance — exactly the
        # mismatch atomic adoption exists to prevent. This covers every
        # abort source, including a target transfer that fails in
        # flight under _maybe_adopt_pending.
        self._pending_draft = None
        super()._abort_pending_swap(err)

    def _maybe_adopt_pending(self) -> bool:
        """Atomic target+draft adoption: when an explicit draft swap is
        in flight, adoption waits until BOTH pytrees have landed; a
        self-following draft re-aliases to the new target at the same
        boundary."""
        pending_draft = self._pending_draft
        if pending_draft is not None and self._pending_params is not None:
            try:
                if not _tree_ready(pending_draft):
                    return False
            except Exception as e:  # noqa: BLE001 — failed draft transfer
                self._abort_pending_swap(e)
                return False
        follow = self.draft_params is self.params
        if super()._maybe_adopt_pending():
            if pending_draft is not None:
                self.draft_params = pending_draft
                self._pending_draft = None
            elif follow:
                self.draft_params = self.params
            return True
        return False

    def _admit_one(
        self, slot, uid, prompt, submit_t, cap, prefix_id=None,
        allowed_tokens=None,
    ):
        width = self._bucket_width(len(prompt))
        toks, mask = self._pad_rows([prompt], width)
        with self._ctx():
            t_row, d_row, row_logits, row_pos, row_kv = (
                self._prefill_spec_fn(
                    self.params, self.draft_params, toks, mask
                )
            )
            self._state = self._admit_spec_fn(
                self._state, t_row, d_row, row_logits, row_pos, row_kv,
                self._i32(slot), self._i32(width), self._i32(cap),
            )
        self._slots[slot] = _Slot(
            uid=uid, prompt=prompt, submit_t=submit_t, cap=cap,
            admit_t=time.perf_counter(),
        )

    # tpulint: hotpath — dispatch must never read the device back
    def _dispatch_round(self, rng) -> tuple:
        """One speculation round enqueued on the device (draft k,
        verify once); nothing read back. ``rng`` is accepted for API
        parity (greedy rounds are deterministic). The base class's
        step() drives this for both the synchronous and the
        overlapped scheduler — a speculative ROUND is this engine's
        pipeline unit, and async weight adoption (target AND draft,
        atomically) happens only at a drained pipeline, exactly like
        the plain engine's chunk."""
        with self._ctx():
            self._state, (win, accept, logps) = self._round_fn(
                self.params, self.draft_params, self._state
            )
        return (
            win, accept, logps, self._state[-2],  # -2: the done flags
            [st.uid for st in self._slots],
        )

    def _emit_outputs(self, fetched, uids) -> int:
        """Emit one synced round: window[:1+accepted] per row whose
        uid still matches the dispatch snapshot (a slot cancelled —
        or cancelled and re-admitted — during the one-round lag gets
        nothing), with eos/cap truncation on the host exactly as the
        synchronous round did. Acceptance accounting happens here, per
        PROCESSED round, so stats stay exact in both modes."""
        win, accept, logps, done = fetched
        emitted = 0
        self.rounds += 1
        live = [
            st.uid >= 0 and st.uid == uids[i]
            for i, st in enumerate(self._slots)
        ]
        self.drafted_total += self.k * sum(live)
        self.accepted_total += int(
            sum(int(accept[i]) for i, l in enumerate(live) if l)
        )
        for slot, st in enumerate(self._slots):
            if not live[slot]:
                continue
            for t in range(1 + int(accept[slot])):
                if len(st.emitted) >= st.cap:
                    break
                tok = int(win[slot, t])
                if not st.emitted:
                    st.first_tok_t = time.perf_counter()
                st.emitted.append(tok)
                st.logprobs.append(float(logps[slot, t]))
                emitted += 1
                if self.s.eos_id >= 0 and tok == self.s.eos_id:
                    break
            st.finished = bool(done[slot])
            if st.finished or len(st.emitted) >= st.cap:
                # the device already done-masked the row (budget/EOS)
                self._finalize_slot(slot)
        return emitted

    # the speculative round's emission is identical in both modes (it
    # was already window-fused); the sync path reuses it
    _emit_outputs_sync = _emit_outputs

    # speculative admission inserts into BOTH caches through its own
    # program — the plain engine's burst insert does not apply
    _burst_admit = False

    def _eager_prefill(self) -> None:
        """No-op: speculative admission prefills BOTH models through
        its own program; the plain engine's eager rows don't apply."""

    def stats(self) -> Dict:
        out = super().stats()
        out["speculative_num_draft"] = self.k
        out["self_drafting"] = self.draft_params is self.params
        out["spec_rounds"] = self.rounds
        out["spec_acceptance"] = round(
            self.accepted_total / max(self.drafted_total, 1), 3
        )
        return out
