"""Small MLP classifier — the elastic smoke-test workload.

Reference parity: ``examples/pytorch/mnist`` is the reference's chaos-test
job (fault_tolerance_exps.md). The same role here: a tiny model to drive
end-to-end elastic runs and tests cheaply.
"""

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import partitioning as nn_partitioning

param_with_axes = nn_partitioning.param_with_axes


@dataclass(frozen=True)
class MlpConfig:
    input_dim: int = 784
    hidden_dim: int = 512
    num_classes: int = 10
    dtype: Any = jnp.float32


class MnistMlp(nn.Module):
    config: MlpConfig = MlpConfig()

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        x = x.reshape((x.shape[0], -1)).astype(cfg.dtype)
        w1 = param_with_axes(
            "w1",
            nn.initializers.lecun_normal(),
            (cfg.input_dim, cfg.hidden_dim),
            cfg.dtype,
            axes=("embed", "mlp"),
        )
        b1 = param_with_axes(
            "b1", nn.initializers.zeros, (cfg.hidden_dim,), cfg.dtype, axes=("mlp",)
        )
        w2 = param_with_axes(
            "w2",
            nn.initializers.lecun_normal(),
            (cfg.hidden_dim, cfg.num_classes),
            cfg.dtype,
            axes=("mlp", None),
        )
        b2 = param_with_axes(
            "b2", nn.initializers.zeros, (cfg.num_classes,), cfg.dtype, axes=(None,)
        )
        h = jax.nn.relu(jnp.dot(x, w1) + b1)
        return jnp.dot(h, w2) + b2


def classification_loss(logits, labels):
    logps = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logps, labels[:, None], axis=-1))
