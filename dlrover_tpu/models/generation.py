"""Autoregressive generation over the training parameters, TPU-first.

The rollout half of an RL job. The reference delegates generation to
vLLM actors (its PPO example wires `vllm_*` engine args straight into
the rollout role — examples/unified/rl/openrlhf/ppo/main.py:26-60); in
this framework generation is a first-class jit-compiled path over the
same flax parameters the trainer optimizes, so a rollout role needs no
second inference stack, no weight format conversion, and re-syncs
weights by just receiving the new param pytree.

Design (all shapes static, everything under one ``jit``):

- **Left-padded prompts.** Every batch row ends at the same cache slot,
  so the prefill and every decode step write the KV cache with a single
  ``dynamic_update_slice`` — never a per-row scatter. Per-row absolute
  positions (for RoPE / learned positional embeddings) and a per-slot
  validity mask carry the variable prompt lengths instead.
- **Prefill** runs the whole prompt through the model once in decode
  mode (one MXU-friendly pass, T0 wide), filling cache slots [0, T0).
- **Decode** is a ``lax.scan`` over single-token steps: sample, write
  slot T0+t, advance. Rows that hit EOS keep stepping on a pad token
  (static shapes) and are masked out of the result.
- **Sampling**: temperature / top-k / top-p composed in fp32, then
  ``jax.random.categorical``. Chosen-token logprobs (under the raw,
  unfiltered distribution) are returned for RL objectives.
"""

from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "SamplingConfig",
    "build_generate_fn",
    "decode_apply",
    "filter_logits",
    "generate",
    "init_cache",
    "left_pad_prompts",
    "prefill_prompt",
    "sample_logits",
    "sample_step",
]


@dataclass(frozen=True)
class SamplingConfig:
    max_new_tokens: int = 32
    temperature: float = 1.0
    top_k: int = 0  # 0 = off
    top_p: float = 1.0  # 1.0 = off
    eos_id: int = -1  # -1 = never stop early
    pad_id: int = 0


def left_pad_prompts(prompts: list, pad_id: int = 0, width: int = 0):
    """Pack variable-length token lists into LEFT-padded [B, T0] arrays.

    Returns (tokens, mask) with mask True on real tokens. Left padding
    is the generation-engine convention (see module docstring): all rows
    end at the same slot so the decode loop writes one static slice.
    """
    width = max(width, max(len(p) for p in prompts))
    tokens = np.full((len(prompts), width), pad_id, dtype=np.int32)
    mask = np.zeros((len(prompts), width), dtype=bool)
    for i, p in enumerate(prompts):
        if len(p):
            tokens[i, width - len(p) :] = np.asarray(p, dtype=np.int32)
            mask[i, width - len(p) :] = True
    return jnp.asarray(tokens), jnp.asarray(mask)


def init_cache(model, batch_size: int):
    """Zero decode-cache pytree for ``model`` at the given batch size.

    Shapes come from ``jax.eval_shape`` over ``model.init`` in decode
    mode — nothing is computed, no params are materialized. The cache
    spans ``cfg.max_seq_len`` slots per layer (KVH-wide for GQA models).
    """
    cfg = model.config
    dummy = jnp.zeros((batch_size, 1), jnp.int32)
    pos = jnp.zeros((batch_size, 1), jnp.int32)
    valid = jnp.zeros((batch_size, cfg.max_seq_len), bool)

    def _init():
        return model.init(
            jax.random.PRNGKey(0),
            dummy,
            decode=True,
            positions=pos,
            kv_valid=valid,
        )

    shapes = jax.eval_shape(_init)["cache"]
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )


def filter_logits(logits, top_k: int = 0, top_p: float = 1.0):
    """Mask [..., V] fp32 logits to the top-k/top-p support (-inf out).

    top-k keeps the k largest; top-p keeps the smallest prefix of the
    sorted distribution whose mass reaches p (always at least the
    argmax). Filters compose: k first, then p, the common serving
    convention. Shared by direct sampling and the speculative path
    (whose acceptance math must target the SAME filtered
    distribution).
    """
    if top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sort_idx = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose cumulative mass BEFORE them is < top_p
        keep_sorted = (cum - probs) < top_p
        inv = jnp.argsort(sort_idx, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


def sample_logits(
    logits,
    rng,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
):
    """Sample token ids from [B, V] logits. Static sampling params.

    temperature==0 is greedy argmax; see :func:`filter_logits` for the
    top-k/top-p semantics.
    """
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = filter_logits(logits / max(temperature, 1e-6), top_k, top_p)
    return jax.random.categorical(rng, logits, axis=-1)


def decode_apply(
    model, params, cache, tokens, positions, kv_valid, cache_slots=None
):
    """One decode-mode model application over an explicit cache pytree.

    Returns (raw logits, updated cache). The single place the decode
    contract (``decode=True, positions, kv_valid, mutable=["cache"]``)
    is spelled, shared by the one-shot engine and the continuous-
    batching scheduler — their token-exactness guarantee depends on
    applying the model identically. ``cache_slots`` selects the
    per-row write-slot mode: [B] for single-token decode (continuous
    batching's per-row cache layout) or [B, T] for a T-token window
    written at per-row slots (the in-scheduler speculative verify);
    see gpt._update_decode_cache.
    """
    logits, mut = model.apply(
        {"params": params, "cache": cache},
        tokens,
        decode=True,
        positions=positions,
        kv_valid=kv_valid,
        cache_slots=cache_slots,
        mutable=["cache"],
    )
    return logits, mut["cache"]


def sample_step(last_logits, done, rng, s: SamplingConfig):
    """One sampling decision: (token, emit mask, logprob, done').

    ``done`` rows emit pad and are masked; an eos sample is emitted
    (the eos token is kept) and marks the row done afterwards.
    Logprobs are computed from the logits AS GIVEN (RL behavior
    logprobs): the one-shot engine passes raw model logits; the
    continuous engine may pass per-row MASKED logits (allowed_tokens
    constrained decoding), in which case the logprobs are under the
    masked distribution — exactly what the policy could emit. Shared
    by the one-shot and continuous engines.
    """
    tok = sample_logits(last_logits, rng, s.temperature, s.top_k, s.top_p)
    logp = jax.nn.log_softmax(last_logits, axis=-1)
    tok_logp = jnp.take_along_axis(logp, tok[:, None], axis=-1)[:, 0]
    tok = jnp.where(done, s.pad_id, tok)
    emit_mask = ~done
    if s.eos_id >= 0:
        done = done | (tok == s.eos_id)
    return tok, emit_mask, tok_logp, done


def prefill_prompt(model, params, tokens, mask):
    """Run a LEFT-padded [B, W] prompt through the model in decode mode
    (one MXU-friendly pass), filling a FRESH cache's slots [0, W).

    Returns ``(cache, last_logits[B,V] fp32, last_pos[B],
    kv_valid[B,L])`` — everything a decode loop needs to start.
    """
    B, W = tokens.shape
    L = model.config.max_seq_len
    cache = init_cache(model, B)
    positions = jnp.maximum(
        jnp.cumsum(mask.astype(jnp.int32), axis=1) - 1, 0
    )
    kv_valid = jnp.zeros((B, L), bool).at[:, :W].set(mask)
    logits, cache = decode_apply(
        model, params, cache, tokens, positions, kv_valid
    )
    return (
        cache,
        logits[:, -1].astype(jnp.float32),
        positions[:, -1],
        kv_valid,
    )


def build_generate_fn(
    model,
    sampling: SamplingConfig,
    prompt_width: int,
    mesh=None,
    param_shardings=None,
    rules=None,
) -> Callable:
    """Compile a generation function for fixed (prompt width, sampling).

    Returns ``fn(params, prompt_tokens[B,T0], prompt_mask[B,T0], rng) ->
    (tokens[B,N], mask[B,N], logprobs[B,N])`` — completions, a validity
    mask that cuts off after the first EOS (the EOS token itself is
    kept), and per-token logprobs under the raw model distribution
    (what an RL objective wants as behavior logprobs). Build once per
    rollout role; every call reuses the compiled executable.

    With ``mesh`` (+ optionally the params' ``NamedSharding`` tree and
    logical-axis ``rules``), the whole prefill+decode program runs SPMD
    over the mesh: params stay tp/fsdp-sharded exactly as the trainer
    holds them, prompts shard over the data axes, and XLA inserts the
    decode collectives — a rollout role serves a model bigger than one
    chip with the same compiled path (the reference needs a separate
    vLLM deployment for this; SURVEY.md §2.13).
    """
    cfg = model.config
    s = sampling
    max_len = cfg.max_seq_len
    if prompt_width + s.max_new_tokens > max_len:
        raise ValueError(
            f"prompt width {prompt_width} + max_new {s.max_new_tokens} "
            f"exceeds max_seq_len {max_len}"
        )

    def _generate(params, prompt_tokens, prompt_mask, rng):
        B, T0 = prompt_tokens.shape
        if T0 != prompt_width:
            # the build-time overflow guard validated prompt_width; a
            # wider input would overflow the cache SILENTLY (clamped
            # dynamic_update_slice writes + never-matching kv_valid)
            raise ValueError(
                f"prompt_tokens width {T0} != built prompt_width "
                f"{prompt_width}"
            )
        cache, last_logits, cur_pos, kv_valid = prefill_prompt(
            model, params, prompt_tokens, prompt_mask
        )

        # N tokens need N-1 incremental forwards (the prefill supplied
        # the first logits, the last sampled token is never fed back) —
        # the scan covers tokens 0..N-2, the final sample happens after.
        def step(carry, t):
            cache, kv_valid, last_logits, cur_pos, done, rng = carry
            rng, sub = jax.random.split(rng)
            tok, emit_mask, tok_logp, done = sample_step(
                last_logits, done, sub, s
            )

            slot = T0 + t
            kv_valid = kv_valid | (
                jnp.arange(max_len)[None, :] == slot
            )
            pos = cur_pos + 1
            logits, cache = decode_apply(
                model,
                params,
                cache,
                tok[:, None],
                pos[:, None],
                kv_valid,
            )
            carry = (
                cache,
                kv_valid,
                logits[:, 0].astype(jnp.float32),
                pos,
                done,
                rng,
            )
            return carry, (tok, emit_mask, tok_logp)

        done0 = jnp.zeros((B,), bool)
        carry = (cache, kv_valid, last_logits, cur_pos, done0, rng)
        carry, (toks, masks, logps) = jax.lax.scan(
            step, carry, jnp.arange(s.max_new_tokens - 1)
        )
        _, _, last_logits, _, done, rng = carry
        tok_n, emit_n, logp_n, _ = sample_step(
            last_logits, done, jax.random.split(rng)[1], s
        )
        # scan stacks on axis 0 → [N-1, B]; append the final sample
        toks = jnp.concatenate([toks.T, tok_n[:, None]], axis=1)
        masks = jnp.concatenate([masks.T, emit_n[:, None]], axis=1)
        logps = jnp.concatenate([logps.T, logp_n[:, None]], axis=1)
        return toks, masks, logps

    if mesh is None:
        return jax.jit(_generate)

    from ..parallel.sharding import sharded_generate_jit

    return sharded_generate_jit(
        _generate, mesh, (param_shardings,), n_data_args=2, rules=rules
    )


def generate(
    model,
    params,
    prompt_tokens,
    prompt_mask,
    rng,
    sampling: Optional[SamplingConfig] = None,
):
    """One-shot convenience wrapper around :func:`build_generate_fn`."""
    sampling = sampling or SamplingConfig()
    fn = build_generate_fn(model, sampling, prompt_tokens.shape[1])
    return fn(params, prompt_tokens, prompt_mask, rng)
