"""Priority-inversion drill: the cluster scheduler's end-to-end proof.

One process, four tenants on one chip pool, strictly prioritized:

    fleet_hi  (serve, priority 0)  — the SLO-critical fleet
    train_hi  (train, priority 10) — the protected trainer
    fleet_lo  (serve, priority 20) — a best-effort fleet
    train_lo  (train, priority 30) — the preemptible trainer

The script:

1. **calibrate** — both trainers step through real
   :class:`~dlrover_tpu.pool.tenants.LoopTrainingController` loops
   (synthetic numpy programs, rung-planned per world by a live
   :class:`~dlrover_tpu.parallel.replan.ElasticReplanner`), both
   fleets serve genuine HTTP through supervisor + gateway;
2. **spike** — flood the HIGH-priority gateway until its SLO
   breaches; the scheduler's preemption cascade must revoke from the
   LOWEST-priority tenant first (``train_lo`` checkpoints and shrinks;
   ``train_hi`` and ``fleet_lo`` are untouched) and grant the freed
   unit to ``fleet_hi`` — with zero failed requests on the
   high-priority fleet, and the whole cascade stitched into ONE
   ``tpurun-trace`` incident (breach → decision → revoke → grant);
3. **brain** — seed the datastore with each trainer's scaling curve,
   run one :class:`~dlrover_tpu.cluster.brain_loop.BrainFeedback`
   round: ``ClusterResourceArbiter.allocate`` splits the training
   budget by marginal gain (the linear-scaling ``train_hi`` wins the
   spare units; the saturated ``train_lo`` is sized down to its knee)
   and the emitted targets — NOT static knobs — drive the next
   cascade; ``cluster_brain_adopt_s`` is target-set to
   target-world-reached wall time;
4. **calm** — stop the flood; after the handback hysteresis
   ``fleet_hi`` returns the surge unit and the pool resettles.

Measured verdicts (docs/cluster.md, ``cluster_*`` bench keys):
``availability`` (1.0 on the high-priority fleet is the bar),
``preempt_cascade_s``, ``brain_adopt_s``, ``first_victim``
(must be ``train_lo``), ``cascade_one_trace``.
"""

import json
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ..brain.datastore import BrainDataStore, JobMetricSample
from ..common.events import TextFileExporter
from ..common.log import logger
from ..fleet import FleetConfig, Gateway, ReplicaSupervisor
from ..fleet.autoscaler import fleet_signals
from ..observability import trace
from ..observability.trace_merge import summarize
from ..parallel.replan import CostModel, ElasticReplanner, Rung
from ..pool.drill import (
    ScriptedReplica,
    _no_persistent_compile_cache,
    _synthetic_training,
)
from ..pool.tenants import (
    LoopTrainingController,
    ServingTenant,
    TrainingTenant,
)
from .brain_loop import BrainFeedback
from .config import ClusterConfig
from .registry import TenantRegistry, TenantSpec
from .scheduler import ClusterScheduler

__all__ = ["run_priority_inversion_drill"]


def _make_trainer(
    workdir: str,
    name: str,
    max_units: int,
    start_world: int,
    rung_log: List[Dict],
    step_s: float = 0.02,
):
    """One synthetic training world whose per-world program is chosen
    by a live rung replanner — shrink/grow routes through the same
    DP/PP trade machinery the elastic runtime uses, so the drill's
    reconfigs carry rung labels, not just world counts."""
    engine, build_step, state, data_fn = _synthetic_training(
        os.path.join(workdir, name), max_units, step_s=step_s
    )
    replanner = ElasticReplanner(
        CostModel(
            param_bytes=1 << 20,
            opt_bytes=2 << 20,
            step_time_s=step_s,
            reference=Rung(dp=max_units),
        ),
        full_dp=max_units,
        current=Rung(dp=max_units),
        max_pp=2,
        num_layers=2,
    )

    def build(world: int):
        plan = replanner.plan(world)
        replanner.adopt(plan.rung)
        rung_log.append(
            {
                "tenant": name,
                "world": world,
                "rung": plan.rung.label(),
                "accum": plan.rung.accum,
            }
        )
        return build_step(world)

    controller = LoopTrainingController(
        engine,
        build,
        state,
        data_fn,
        max_units=max_units,
        start_world=start_world,
        compile_ahead=False,  # synthetic programs build instantly
        # NO disk persistence: two in-process engines share one agent
        # saver; the second trainer's queued step-0 disk save starves
        # behind the first's event loop, and its loop-exit
        # wait_saving() would then wedge the revoke drain past the
        # lease deadline. Shrink handoff rides shm staging alone.
        storage_every=0,
    )
    return engine, controller


def _make_fleet(replicas: int, max_replicas: int, script: Dict):
    def replica_factory(rid, port):
        return ScriptedReplica(rid, port, script=script)

    fleet_cfg = FleetConfig(
        replicas=replicas,
        min_replicas=1,
        max_replicas=max_replicas,
        health_interval_s=0.1,
        health_fails=100,
        health_timeout_s=15.0,
        start_timeout_s=120.0,
        relaunch_budget=2,
        queue_limit=256,
        drain_timeout_s=30.0,
    )
    supervisor = ReplicaSupervisor(replica_factory, fleet_cfg)
    return supervisor, Gateway(supervisor, fleet_cfg)


def _seed_scaling_curves(store: BrainDataStore, max_units: int):
    """Prior-run scaling profiles, in the SAME steps/s scale the live
    synthetic loops report (1 / (accum × step_s), step_s=0.02):
    ``train_hi`` scales linearly to the pool edge, ``train_lo`` is a
    small model saturated from one host — so the arbiter's marginal-
    gain greedy has a real decision to make."""
    for w in range(1, max_units + 1):
        store.add_metric(
            JobMetricSample(
                job_uuid="train_hi",
                world_size=w,
                steps_per_second=round(50.0 * w / max_units, 2),
            )
        )
    for w, sps in ((1, 16.0), (2, 16.5), (3, 16.8), (4, 17.0)):
        store.add_metric(
            JobMetricSample(
                job_uuid="train_lo", world_size=w, steps_per_second=sps
            )
        )


def run_priority_inversion_drill(
    workdir: Optional[str] = None,
    total_units: int = 8,
    spike_clients: int = 6,
    spike_hold_s: float = 0.5,
    eval_interval_s: float = 0.2,
    queue_high: float = 2.0,
    handback_evals: int = 3,
    revoke_deadline_s: float = 60.0,
    calibration_steps: int = 4,
    timeout_s: float = 240.0,
    config: Optional[ClusterConfig] = None,
) -> Dict:
    """Run the 4-tenant spike → cascade → brain → calm drill.

    Returns a JSON-able verdict dict; ``ok`` is the overall pass. The
    chaos scenario (``priority_inversion_storm``), the bench
    ``cluster`` section, ``tpurun-cluster drill``, and the e2e test
    all run THIS function — the docs/cluster.md numbers are
    reproducible from any of them."""
    from ..analysis.witness import maybe_install

    maybe_install()
    workdir = workdir or tempfile.mkdtemp(prefix="cluster_drill_")
    events_dir = os.path.join(workdir, "events")
    t_drill0 = time.monotonic()
    deadline = t_drill0 + timeout_s
    out: Dict = {"drill": "priority_inversion_storm", "ok": False}
    rung_log: List[Dict] = []

    def remaining() -> float:
        return max(0.0, deadline - time.monotonic())

    trainer_units = 6  # each trainer's own ladder ceiling
    # default "events" prefix: tpurun-trace's load_dir globs for it
    exporter = TextFileExporter(events_dir)
    with _no_persistent_compile_cache():
        script_hi: Dict = {}
        script_lo: Dict = {}
        sup_hi, gw_hi = _make_fleet(1, 4, script_hi)
        sup_lo, gw_lo = _make_fleet(1, 2, script_lo)
        engine_hi, ctl_hi = _make_trainer(
            workdir, "train_hi", trainer_units, 3, rung_log
        )
        engine_lo, ctl_lo = _make_trainer(
            workdir, "train_lo", trainer_units, 3, rung_log
        )

        registry = TenantRegistry()
        registry.register(
            TenantSpec("fleet_hi", "serve", priority=0, floor=1,
                       ceiling=4),
            ServingTenant(sup_hi, name="fleet_hi"),
        )
        registry.register(
            TenantSpec("train_hi", "train", priority=10, floor=1,
                       ceiling=trainer_units),
            TrainingTenant(ctl_hi, floor_units=1, name="train_hi"),
        )
        registry.register(
            TenantSpec("fleet_lo", "serve", priority=20, floor=1,
                       ceiling=2),
            ServingTenant(sup_lo, name="fleet_lo"),
        )
        registry.register(
            TenantSpec("train_lo", "train", priority=30, floor=1,
                       ceiling=trainer_units),
            TrainingTenant(ctl_lo, floor_units=1, name="train_lo"),
        )

        cfg = config or ClusterConfig(
            total_units=total_units,
            queue_high=queue_high,
            handback_evals=handback_evals,
            revoke_deadline_s=revoke_deadline_s,
            spike_units=1,
            journal_path=os.path.join(
                workdir, "cluster_journal.jsonl"
            ),
        )

        results = {"ok": 0, "failed": 0}
        res_mu = threading.Lock()
        spike_on = threading.Event()
        pump_stop = threading.Event()

        def client_loop(i: int):
            while spike_on.is_set() and not pump_stop.is_set():
                try:
                    got = gw_hi.complete(
                        {"prompt": [5, 9, (i % 50) + 1]}
                    )
                    assert got["tokens"]
                    with res_mu:
                        results["ok"] += 1
                except Exception:  # noqa: BLE001 — counted, judged below
                    with res_mu:
                        results["failed"] += 1

        scheduler = None
        try:
            sup_hi.start()
            sup_lo.start()
            ctl_hi.start()
            ctl_lo.start()
            if not sup_hi.wait_ready(1, timeout=remaining()):
                out["error"] = "fleet_hi never came READY"
                return out
            if not sup_lo.wait_ready(1, timeout=remaining()):
                out["error"] = "fleet_lo never came READY"
                return out

            scheduler = ClusterScheduler(
                registry, cfg, trace_incidents=True, exporter=exporter
            )
            store = BrainDataStore(":memory:")
            brain = BrainFeedback(scheduler, store=store)
            brain.add_training_job(
                "train_hi", ctl_hi, model_signature="gpt-linear-6u"
            )
            brain.add_training_job(
                "train_lo", ctl_lo, model_signature="tiny-saturated"
            )
            brain.add_fleet(
                "fleet_hi", lambda: fleet_signals(sup_hi)
            )
            brain.add_fleet(
                "fleet_lo", lambda: fleet_signals(sup_lo)
            )

            # -- calibrate ------------------------------------------------
            for name, ctl in (("train_hi", ctl_hi), ("train_lo", ctl_lo)):
                while ctl.steps_total < calibration_steps:
                    if ctl.wait_finished(0):
                        out["error"] = f"{name} died during calibration"
                        return out
                    if remaining() <= 0:
                        out["error"] = f"{name} never calibrated"
                        return out
                    time.sleep(0.05)
            for gw in (gw_hi, gw_lo):
                try:
                    gw.complete({"prompt": [3, 7, 11]})
                except Exception as e:  # noqa: BLE001
                    out["error"] = f"warm request failed: {e!r}"
                    return out

            # -- spike on the HIGH-priority fleet -------------------------
            spike_on.set()
            script_hi["queue_depth"] = 8
            pumps = [
                threading.Thread(target=client_loop, args=(i,))
                for i in range(spike_clients)
            ]
            for p in pumps:
                p.start()

            t_breach = None
            t_ready = None
            while remaining() > 0:
                for name, ctl in (
                    ("train_hi", ctl_hi), ("train_lo", ctl_lo)
                ):
                    if ctl.wait_finished(0):
                        out["error"] = f"{name} died during spike"
                        out["journal"] = scheduler.journal()
                        return out
                scheduler.step()
                if t_breach is None and any(
                    e["event"] == "revoke"
                    for e in scheduler.journal()
                ):
                    t_breach = time.monotonic()
                    # ONE cascade is the experiment: quiet the scripted
                    # breach the moment the revoke lands (the flood
                    # keeps running — availability is judged over the
                    # whole window). While the surge replica boots,
                    # re-firing rounds would cascade train_lo to its
                    # floor and leave the brain phase no surplus to
                    # re-split.
                    script_hi["queue_depth"] = 0
                if (
                    t_breach is not None
                    and len(sup_hi.ready_replicas()) >= 2
                ):
                    t_ready = time.monotonic()
                    break
                time.sleep(eval_interval_s)
            if t_ready is None:
                out["error"] = "cascade never delivered the surge unit"
                out["journal"] = scheduler.journal()
                return out
            out["preempt_cascade_s"] = round(t_ready - t_breach, 3)

            # hold the flood a beat past READY so availability covers
            # the post-grant window too, then drain the clients
            time.sleep(spike_hold_s)
            spike_on.clear()
            for p in pumps:
                p.join(timeout=max(1.0, remaining()))

            revokes = [
                e for e in scheduler.journal()
                if e["event"] == "revoke"
            ]
            out["cascade_order"] = [e["tenant"] for e in revokes]
            out["first_victim"] = (
                revokes[0]["tenant"] if revokes else None
            )
            out["world_during_spike"] = {
                "train_hi": ctl_hi.world(),
                "train_lo": ctl_lo.world(),
            }
            if not scheduler.wait_idle(timeout=remaining()):
                out["error"] = "spike cascade never settled"
                out["journal"] = scheduler.journal()
                return out

            # -- brain round: targets from the datastore, not knobs -------
            trace.reset()  # the spike incident is closed; the brain-
            # driven cascade gets its own trace_id
            _seed_scaling_curves(store, trainer_units)
            brain.poll_once()
            targets = brain.evaluate_once()
            out["brain_targets"] = dict(targets)
            if targets.get("train_hi", 0) <= ctl_hi.world():
                out["error"] = (
                    f"brain emitted no grow target for train_hi: "
                    f"{targets}"
                )
                return out
            while remaining() > 0:
                scheduler.step()
                if (
                    scheduler.allocations().get("train_hi", 0)
                    >= targets["train_hi"]
                ):
                    break
                time.sleep(eval_interval_s)
            if not scheduler.wait_idle(timeout=remaining()):
                out["error"] = "brain-target cascade never settled"
                out["journal"] = scheduler.journal()
                return out
            out["brain_adopt_s"] = scheduler.last_adopt_s
            out["adoptions"] = scheduler.adoptions

            # -- calm: the surge unit drains back -------------------------
            handback = False
            while remaining() > 0:
                scheduler.step()
                alloc = scheduler.allocations()
                if (
                    alloc.get("fleet_hi", 0) == 1
                    and len(sup_hi.replicas()) == 1
                    and not scheduler.pending_leases()
                ):
                    handback = True
                    break
                time.sleep(eval_interval_s)
            out["handback"] = handback

            with res_mu:
                ok_n, failed_n = results["ok"], results["failed"]
            total_req = ok_n + failed_n
            out["requests_ok"] = ok_n
            out["requests_failed"] = failed_n
            out["availability"] = (
                round(ok_n / total_req, 4) if total_req else None
            )
            out["allocations"] = scheduler.allocations()
            out["revokes"] = scheduler.revokes
            out["grants"] = scheduler.grants
            out["escalations"] = scheduler.escalations
            out["phase_split"] = scheduler.phases.split().summary()
            out["rungs"] = rung_log
            out["journal"] = scheduler.journal()
            out["train_reports"] = {
                "train_hi": ctl_hi.report(),
                "train_lo": ctl_lo.report(),
            }

            # -- trace: the whole cascade under ONE trace_id --------------
            exporter.close()
            summary = summarize(events_dir)
            out["trace"] = {
                k: summary.get(k)
                for k in ("events", "incidents", "mttr_s")
            }
            cascade_incidents = [
                i
                for i in summary.get("incidents", [])
                if i.get("reshard_transitions")
            ]
            out["cascade_one_trace"] = bool(cascade_incidents) and all(
                i["events"] >= 4 for i in cascade_incidents
            )

            out["elapsed_s"] = round(time.monotonic() - t_drill0, 2)
            out["ok"] = (
                out["first_victim"] == "train_lo"
                and out["world_during_spike"]["train_hi"] == 3
                and failed_n == 0
                and total_req > 0
                and scheduler.escalations == 0
                and out["adoptions"] >= 1
                and out["brain_adopt_s"] is not None
                and handback
                and out["cascade_one_trace"]
            )
            return out
        finally:
            pump_stop.set()
            spike_on.clear()
            trace.reset()
            if scheduler is not None:
                scheduler.stop()
            for name, ctl in (("hi", ctl_hi), ("lo", ctl_lo)):
                try:
                    ctl.stop(timeout=30.0)
                except Exception as e:  # noqa: BLE001 — teardown
                    logger.warning(
                        "cluster drill: ctl_%s stop: %r", name, e
                    )
            sup_hi.stop()
            sup_lo.stop()
            for eng in (engine_hi, engine_lo):
                try:
                    eng.shm.unlink()
                    eng.close()
                except Exception as e:  # noqa: BLE001 — teardown
                    logger.warning(
                        "cluster drill: engine close: %r", e
                    )
            exporter.close()


def main(argv=None) -> int:
    """``python -m dlrover_tpu.cluster.drill`` — run and print."""
    import argparse

    ap = argparse.ArgumentParser(prog="cluster-drill")
    ap.add_argument("--workdir", default=None)
    ns = ap.parse_args(argv)
    result = run_priority_inversion_drill(workdir=ns.workdir)
    print(json.dumps(result, indent=1))
    return 0 if result.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
