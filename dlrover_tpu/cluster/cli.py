"""``tpurun-cluster`` — run the multi-tenant cluster scheduler.

Two subcommands:

- ``tpurun-cluster drill`` runs the scripted 4-tenant priority-
  inversion drill (cluster/drill.py — the same code path behind the
  docs/cluster.md numbers and the bench ``cluster`` section) and
  prints the measured verdict JSON; exit 0 only when the drill passed.
- ``tpurun-cluster serve`` runs a scheduler over tenants declared in
  ``DLROVER_CLUSTER_TENANTS`` (serve tenants get a subprocess fleet
  each; train tenants attach later through the registry), with the
  scheduler's status endpoint on ``--port`` (``/cluster/status``,
  ``/cluster/journal``, ``/healthz`` read state; POST
  ``/cluster/step`` forces one evaluation and POST
  ``/cluster/target`` feeds an explicit per-tenant target world —
  same JSON conventions as ``/pool/status``).
"""

import argparse
import json
import signal
import threading
from http.server import ThreadingHTTPServer
from typing import List, Optional

from ..common.log import logger
from .config import ClusterConfig
from .registry import SERVE, TenantRegistry
from .scheduler import ClusterScheduler

__all__ = ["main", "serve_status"]


def _make_handler(scheduler: ClusterScheduler):
    from ..common.http import JsonRequestHandler

    class Handler(JsonRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug("cluster: " + fmt, *args)

        def do_GET(self):
            if self.path in ("/cluster/status", "/healthz"):
                self._send(200, scheduler.status())
            elif self.path == "/cluster/journal":
                self._send(200, {"journal": scheduler.journal()})
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            if self.path == "/cluster/step":
                # manual evaluation (eval_interval_s=0 deployments)
                self._send(200, scheduler.step())
            elif self.path == "/cluster/target":
                try:
                    body = self._body()
                    scheduler.set_target(
                        body["tenant"],
                        int(body["units"]),
                        source=body.get("source", "operator"),
                    )
                except (KeyError, TypeError, ValueError) as e:
                    self._send(400, {"error": repr(e)[:200]})
                    return
                self._send(200, {"targets": scheduler.targets()})
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

    return Handler


def serve_status(
    scheduler: ClusterScheduler, port: int = 0
) -> ThreadingHTTPServer:
    """Bind the scheduler's status endpoint (caller runs serve_forever
    or wraps it in a daemon thread)."""
    return ThreadingHTTPServer(
        ("0.0.0.0", port), _make_handler(scheduler)
    )


def _cmd_drill(ns) -> int:
    from .drill import run_priority_inversion_drill

    result = run_priority_inversion_drill(
        workdir=ns.workdir, timeout_s=ns.timeout
    )
    print(json.dumps(result, indent=1))
    return 0 if result.get("ok") else 1


def _cmd_serve(ns, overrides) -> int:
    from ..fleet.config import FleetConfig
    from ..fleet.replica import SubprocessReplica
    from ..fleet.supervisor import ReplicaSupervisor
    from ..pool.tenants import ServingTenant

    cfg = ClusterConfig.from_env(**overrides)
    registry = TenantRegistry.from_config(cfg)
    if not len(registry):
        logger.error(
            "tpurun-cluster serve: no tenants declared — set "
            "DLROVER_CLUSTER_TENANTS (name:kind:priority[:floor"
            "[:ceiling[:node_unit]]];...)"
        )
        return 2

    serve_args = list(ns.serve_args)
    if serve_args and serve_args[0] == "--":
        serve_args = serve_args[1:]
    if ns.cpu and "--cpu" not in serve_args:
        serve_args.append("--cpu")

    supervisors = []
    for spec in registry.specs():
        if spec.kind != SERVE:
            # train tenants attach through the embedding job's
            # controller (MasterTrainingController beside its master);
            # the CLI can only materialize fleets
            continue
        base = FleetConfig.from_env()
        ceiling = registry.ceiling(spec.name, cfg.total_units)
        fleet_cfg = FleetConfig.from_env(
            replicas=max(1, spec.floor),
            max_replicas=max(base.max_replicas, ceiling),
        )

        def factory(rid: int, port: int) -> SubprocessReplica:
            return SubprocessReplica(rid, port, serve_args=serve_args)

        sup = ReplicaSupervisor(factory, fleet_cfg)
        supervisors.append(sup)
        registry.attach(spec.name, ServingTenant(sup, name=spec.name))

    def _sigterm(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _sigterm)

    for sup in supervisors:
        sup.start()
    scheduler = ClusterScheduler(registry, cfg).start()
    httpd = serve_status(scheduler, ns.port)
    logger.info(
        "tpurun-cluster: %s units across %s tenants, status on :%s",
        cfg.total_units,
        len(registry),
        httpd.server_address[1],
    )
    status_thread = threading.Thread(
        target=httpd.serve_forever, name="cluster-status", daemon=True
    )
    status_thread.start()
    try:
        threading.Event().wait()  # scheduler + fleets run on threads
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        httpd.server_close()
        scheduler.stop()
        for sup in supervisors:
            sup.stop()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    from ..analysis.witness import maybe_install

    maybe_install()  # DLROVER_LOCK_WITNESS=1 -> sanitize lock order
    ap = argparse.ArgumentParser(
        prog="tpurun-cluster",
        description="multi-tenant cluster scheduler: N prioritized "
        "tenants (training jobs + serving fleets) on one chip pool, "
        "brain-driven targets closed-loop",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    d = sub.add_parser(
        "drill", help="run the 4-tenant priority-inversion drill"
    )
    d.add_argument("--workdir", default=None)
    d.add_argument("--timeout", type=float, default=240.0)

    s = sub.add_parser(
        "serve", help="tenant fleets + scheduler + status endpoint"
    )
    s.add_argument("--port", type=int, default=8600,
                   help="scheduler status endpoint port")
    s.add_argument("--units", type=int, default=None,
                   help="pool inventory (DLROVER_CLUSTER_TOTAL_UNITS)")
    s.add_argument("--tenants", default=None,
                   help="tenant declarations (DLROVER_CLUSTER_TENANTS)")
    s.add_argument("--eval-interval", type=float, default=None,
                   help="scheduler period "
                   "(DLROVER_CLUSTER_EVAL_INTERVAL_S)")
    s.add_argument("--cpu", action="store_true",
                   help="forward --cpu to every replica (local smoke)")
    s.add_argument(
        "serve_args", nargs=argparse.REMAINDER,
        help="args after -- are forwarded to every tpurun-serve replica",
    )

    ns = ap.parse_args(argv)
    if ns.cmd == "drill":
        return _cmd_drill(ns)
    overrides = {}
    if ns.units is not None:
        overrides["total_units"] = ns.units
    if ns.tenants is not None:
        overrides["tenants"] = ns.tenants
    if ns.eval_interval is not None:
        overrides["eval_interval_s"] = ns.eval_interval
    return _cmd_serve(ns, overrides)


if __name__ == "__main__":
    raise SystemExit(main())
