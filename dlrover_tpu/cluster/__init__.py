"""Multi-tenant cluster scheduler: N prioritized tenants on one pool.

Generalizes the two-tenant ``pool/`` arbiter (PR 8) to N tenants with
priority classes, per-tenant floors/ceilings, gang-scheduled leases on
the node_unit grid, preemption cascades (a high-priority breach
revokes from the lowest-priority tenant above floor first), and a
closed brain loop that turns the PR 12 metrics plane into per-tenant
target worlds (``brain_loop.BrainFeedback`` — the live caller of
``brain/algorithms.py::ClusterResourceArbiter.allocate``).

Layout (docs/cluster.md):

- :mod:`~dlrover_tpu.cluster.config` — ``ClusterConfig`` and the
  ``DLROVER_CLUSTER_*`` knob surface
- :mod:`~dlrover_tpu.cluster.registry` — ``TenantSpec`` priority
  classes and the ``TenantRegistry`` over pool tenant adapters
- :mod:`~dlrover_tpu.cluster.scheduler` — pure ``schedule()`` policy
  + the ``ClusterScheduler`` ledger/lease executor
- :mod:`~dlrover_tpu.cluster.brain_loop` — ``BrainFeedback`` metrics
  ingestion and target emission
- :mod:`~dlrover_tpu.cluster.drill` / :mod:`~dlrover_tpu.cluster.cli`
  — the 4-tenant priority-inversion drill and ``tpurun-cluster``
"""

from .brain_loop import BrainFeedback
from .config import ClusterConfig
from .registry import TenantRegistry, TenantSpec, parse_priority_classes
from .scheduler import ClusterScheduler, schedule

__all__ = [
    "BrainFeedback",
    "ClusterConfig",
    "ClusterScheduler",
    "TenantRegistry",
    "TenantSpec",
    "parse_priority_classes",
    "schedule",
]
