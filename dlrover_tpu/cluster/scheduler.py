"""ClusterScheduler: N prioritized tenants on one chip pool.

Generalization of the PR 8 two-tenant ``pool/arbiter.py`` to a
registry of N tenants with priority classes. The split is the same —
a **pure policy function** (:func:`schedule`, the N-tenant analogue of
``pool/arbiter.py::decide``, unit-testable on plain dicts) and a
**ledger executor** (:class:`ClusterScheduler`) that owns the unit
inventory, issues revocable leases, and keeps every transition
journaled — but the policy is now a *preemption cascade*:

- **Demand resolution**: each tenant's effective target comes from its
  live signals (serving breach/calm, the pool's SLO rules per tenant)
  and from **brain-emitted targets** (``set_target``, fed by
  ``brain_loop.BrainFeedback``) — not from static knobs. Targets are
  clamped to [floor, ceiling] and snapped to the tenant's gang grid.
- **Cascade order**: the highest-priority tenant in deficit claims
  first; capacity comes from the free pool, then from **voluntary
  surplus** (tenants whose own target is below their holding — calm
  handback), then by **involuntary preemption strictly ordered from
  the lowest-priority tenant above floor upward**. A tenant never
  involuntarily preempts an equal- or higher-priority tenant.
- **One move in flight per tenant**: a tenant with a pending lease
  (outbound revoke or inbound grant) is excluded from this round —
  the cascade advances lease by lease, every step attributable.
- Deadline escalation, ledger honesty (only actually-freed units move
  the ledger; failed grants roll back), and the journal discipline
  are reused from PR 8 via :class:`common.journal.DecisionJournal`.

Locking discipline (inherited from the pool): ``_mu`` guards the
ledger/journal only; every tenant call and fault-injection hook runs
outside it. ``_step_mu`` serializes whole evaluations.

Observability: with ``trace_incidents=True`` the scheduler opens one
incident trace per cascade (``cluster_breach`` → ``cluster_decision``
→ per-victim ``cluster_revoke`` spans → ``cluster_grant``), which
``tpurun-trace`` tiles into per-phase costs (docs/observability.md).
"""

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..attribution.phases import PhaseAccumulator
from ..chaos import faults
from ..common.events import EventEmitter
from ..common.journal import DecisionJournal
from ..common.log import logger
from ..observability import trace
from .config import ClusterConfig
from .registry import SERVE, TenantRegistry

__all__ = ["ClusterScheduler", "ClusterLease", "schedule"]


class LeaseState:
    REVOKING = "revoking"
    RELEASED = "released"
    ESCALATED = "escalated"


@dataclass
class ClusterLease:
    """One in-flight revocation (the pool's Lease, plus the span that
    makes the drain visible inside the cascade trace)."""

    lease_id: int
    tenant: str
    units: int
    deadline_t: float
    grant_to: str = ""
    reason: str = ""
    state: str = LeaseState.REVOKING
    created_t: float = field(default_factory=time.monotonic)
    released_units: int = 0
    span: Any = None  # cluster_revoke DurationSpan (or None)

    def snapshot(self) -> Dict:
        return {
            "lease_id": self.lease_id,
            "tenant": self.tenant,
            "units": self.units,
            "state": self.state,
            "grant_to": self.grant_to,
            "reason": self.reason,
            "age_s": round(time.monotonic() - self.created_t, 3),
            "deadline_in_s": round(
                self.deadline_t - time.monotonic(), 3
            ),
        }


def _snap_down(units: int, grid: int) -> int:
    return (units // grid) * grid


def _snap_up(units: int, grid: int) -> int:
    return -(-units // grid) * grid


def _serve_demand(t: Dict, cfg: ClusterConfig):
    """(target, calm_streak, reason) for one serving tenant view."""
    held = t["held"]
    sig = t.get("signals")
    queue_high = t.get("queue_high")
    if queue_high is None:
        queue_high = cfg.queue_high
    p95_target = t.get("p95_target_s")
    if p95_target is None:
        p95_target = cfg.p95_target_s
    if sig is None or sig.get("ready", 0) == 0:
        # nothing healthy to measure: never arbitrate blind (the
        # fleet autoscaler's rule, applied cluster-wide)
        return held, 0, "no serving signal"
    queue_mean = sig.get("queue_mean") or 0.0
    p95 = sig.get("p95_worst_s")
    over_queue = queue_mean >= queue_high
    over_latency = (
        p95_target > 0 and p95 is not None and p95 > p95_target
    )
    brain = t.get("target")
    if over_queue or over_latency:
        want = held + cfg.spike_units
        if brain is not None:
            # a live breach outranks a stale brain opinion, but a
            # brain target ABOVE the spike step is adopted whole
            want = max(want, brain)
        reason = (
            f"queue_mean={queue_mean:.2f}"
            if over_queue
            else f"p95={p95:.3f}s>{p95_target:.3f}s"
        )
        return want, 0, reason
    calm_now = (
        queue_mean == 0
        and sig.get("busy_total", 0) == 0
        and (p95_target <= 0 or p95 is None or p95 < p95_target / 2)
    )
    if brain is not None:
        # brain opinion with no breach: adopt it as the demand; the
        # calm streak keeps its own clock for the hysteresis fallback
        streak = t.get("calm_streak", 0) + 1 if calm_now else 0
        return brain, streak, "brain target"
    if not calm_now:
        return held, 0, "active, within SLO"
    streak = t.get("calm_streak", 0) + 1
    surge = held - max(t["floor"], t.get("baseline", 0))
    if streak >= cfg.handback_evals and surge > 0:
        want = held - min(cfg.spike_units, surge)
        return want, streak, f"calm for {streak} evals"
    return held, streak, f"calm ({streak} evals)"


def _train_demand(t: Dict, cfg: ClusterConfig):
    brain = t.get("target")
    if brain is not None:
        return brain, 0, "brain target"
    return t["held"], 0, "hold"


def schedule(
    tenants: List[Dict], free: int, cfg: ClusterConfig
) -> Dict[str, Any]:
    """Pure policy: one evaluation's move (or none).

    Each tenant view is a plain dict::

        {"name", "kind": "train"|"serve", "priority": int,
         "floor", "ceiling", "node_unit", "held",
         "target": Optional[int],      # brain/explicit demand
         "signals": Optional[dict],    # serve: fleet_signals shape
         "calm_streak": int, "baseline": int,
         "busy": bool,                 # lease in flight
         "expandable": bool,
         "attached": bool,             # adapter present (default True)
         "queue_high"/"p95_target_s": Optional per-tenant SLO}

    Returns ``{"action": "grant"|"release"|None, "tenant", "units",
    "from_free", "victims": [{"tenant", "units"}...], "reason",
    "calm": {name: streak}, "demand": {name: effective_target}}`` —
    one decision covering the whole cascade: grant ``units`` to
    ``tenant``, drawing ``from_free`` from the pool and the rest by
    revoking each listed victim; a ``release`` drains ``units`` from
    ``tenant`` back to the free ledger (no grant leg). Kept free of
    ledger and tenant state so every branch is unit-testable on plain
    dicts.
    """
    out: Dict[str, Any] = {
        "action": None,
        "tenant": "",
        "units": 0,
        "from_free": 0,
        "victims": [],
        "reason": "",
        "calm": {},
        "demand": {},
    }
    views: Dict[str, Dict] = {}
    demand: Dict[str, int] = {}
    why: Dict[str, str] = {}
    for t in tenants:
        name = t["name"]
        views[name] = t
        if t["kind"] == SERVE:
            want, streak, reason = _serve_demand(t, cfg)
        else:
            want, streak, reason = _train_demand(t, cfg)
        # clamp to bounds, snap to the tenant's own gang grid
        want = max(t["floor"], min(want, t["ceiling"]))
        want = _snap_down(want, t["node_unit"])
        want = max(t["floor"], want)
        demand[name] = want
        why[name] = reason
        out["calm"][name] = streak
    out["demand"] = dict(demand)

    def _order(items):
        # ascending rank = most important first; registration order
        # (list position) breaks ties deterministically
        index = {t["name"]: i for i, t in enumerate(tenants)}
        return sorted(
            items, key=lambda t: (t["priority"], index[t["name"]])
        )

    claimants = _order(
        t
        for t in tenants
        if not t.get("busy") and demand[t["name"]] > t["held"]
    )
    stuck_reason = ""
    for c in claimants:
        move = _gather(c, tenants, demand, free, cfg, out)
        if move is not None:
            out.update(move)
            out["reason"] = f"{c['name']}: {why[c['name']]}"
            return out
        if not stuck_reason:
            stuck_reason = (
                f"{c['name']}: breach but no capacity movable"
            )

    # idle placement (the pool's "reclaim" branch): unowned free units
    # and voluntary surplus flow to the best expandable tenant so
    # capacity never strands in the free ledger. Tenants with an
    # explicit target are SKIPPED: their demand is brain-owned, and
    # greedily lifting one above its target would immediately make it
    # a voluntary victim — two targeted tenants then trade the same
    # unit every round (grant↔handback livelock) until a new target
    # breaks the tie. Unattached tenants (declared but no adapter yet)
    # are skipped too: the grant could only ever be journaled as
    # grant_skipped, repeating forever and starving the release branch.
    for c in _order(
        t
        for t in tenants
        if not t.get("busy")
        and t.get("expandable")
        and t.get("attached", True)
        and t.get("target") is None
        and t["held"] < t["ceiling"]
        and demand[t["name"]] <= t["held"]  # not already a claimant
    ):
        grid = c["node_unit"]
        headroom = _snap_down(c["ceiling"] - c["held"], grid)
        take = min(free, headroom)
        take = _snap_down(take, grid)
        if take > 0:
            out.update(
                action="grant",
                tenant=c["name"],
                units=take,
                from_free=take,
                victims=[],
                reason=f"{c['name']}: reclaim {free} free unit(s)",
            )
            return out
        # no free units (or below grid): voluntary surplus handback
        vol = _voluntary_victims(
            c, tenants, demand, headroom, out
        )
        if vol:
            total = sum(v["units"] for v in vol)
            out.update(
                action="grant",
                tenant=c["name"],
                units=total,
                from_free=0,
                victims=vol,
                reason=f"{c['name']}: handback",
            )
            for v in vol:
                out["calm"][v["tenant"]] = 0
            return out

    # surplus with no recipient: when every expandable tenant is
    # brain-capped (or at ceiling), a serve tenant's calm handback and
    # a trainer's shrink target still have to land somewhere — the
    # lease drains cooperatively as usual, the freed units just have
    # no grant leg and return to the FREE ledger. Without this branch
    # the surge stays with its tenant forever once the brain owns
    # every trainer's size.
    for d in sorted(
        (
            t
            for t in tenants
            if not t.get("busy")
            and t["held"] > max(t["floor"], demand[t["name"]])
        ),
        key=lambda t: -t["priority"],
    ):
        give = d["held"] - max(d["floor"], demand[d["name"]])
        give = _snap_down(give, d["node_unit"])
        if give <= 0:
            continue
        out.update(
            action="release",
            tenant=d["name"],
            units=give,
            from_free=0,
            victims=[],
            reason=f"{d['name']}: release {give} surplus unit(s)",
        )
        out["calm"][d["name"]] = 0
        return out

    out["reason"] = stuck_reason or "all tenants at target"
    return out


def _voluntary_victims(
    claimant: Dict,
    tenants: List[Dict],
    demand: Dict[str, int],
    cap: int,
    out: Dict,
) -> List[Dict]:
    """Victims offering surplus (demand < held) for an idle-placement
    grant — lowest priority first, never below max(floor, demand)."""
    victims: List[Dict] = []
    remaining = cap
    for v in sorted(
        (
            t
            for t in tenants
            if t is not claimant
            and not t.get("busy")
            and t["held"] > max(t["floor"], demand[t["name"]])
        ),
        key=lambda t: -t["priority"],
    ):
        if remaining <= 0:
            break
        give = v["held"] - max(v["floor"], demand[v["name"]])
        take = min(remaining, give)
        take = _snap_down(take, v["node_unit"])
        if take <= 0:
            continue
        victims.append({"tenant": v["name"], "units": take})
        remaining -= take
    return victims


def _gather(
    claimant: Dict,
    tenants: List[Dict],
    demand: Dict[str, int],
    free: int,
    cfg: ClusterConfig,
    out: Dict,
) -> Optional[Dict]:
    """Source one claimant's move: free pool → voluntary surplus →
    involuntary preemption (strictly lower priority, lowest first).
    Returns the move dict or None when nothing can be assembled."""
    grid = claimant["node_unit"]
    deficit = demand[claimant["name"]] - claimant["held"]
    headroom = claimant["ceiling"] - claimant["held"]
    # per-move cap: one attributable spike step, but never below the
    # claimant's gang grid (a grid tenant cannot take less than one
    # node_unit slice)
    move = min(deficit, headroom, max(cfg.spike_units, grid))
    move = _snap_down(move, grid)
    if move <= 0:
        return None
    from_free = min(free, move)
    remaining = move - from_free
    victims: List[Dict] = []
    if remaining > 0:
        cands = []
        for i, v in enumerate(tenants):
            if v is claimant or v.get("busy"):
                continue
            voluntary = max(
                0, v["held"] - max(v["floor"], demand[v["name"]])
            )
            if v["priority"] > claimant["priority"]:
                give = v["held"] - v["floor"]
            else:
                # equal/higher priority: only what it volunteers
                give = voluntary
            if give <= 0:
                continue
            # lowest-priority first; among equals, voluntary surplus
            # before involuntary revocation
            cands.append((-v["priority"], 0 if voluntary else 1, i, v, give))
        cands.sort()
        for _, _, _, v, give in cands:
            if remaining <= 0:
                break
            take = min(remaining, give)
            # snap UP to the victim's gang grid (its shrink ladder can
            # only land on grid worlds; the excess returns to the free
            # pool), then clamp back inside what it can give
            take = _snap_up(take, v["node_unit"])
            if take > give:
                take = _snap_down(give, v["node_unit"])
            if take <= 0:
                continue
            victims.append({"tenant": v["name"], "units": take})
            remaining -= take
    gathered = from_free + sum(v["units"] for v in victims)
    if gathered <= 0:
        return None
    if remaining > 0 and gathered < grid:
        # a gang claimant cannot use a partial slice
        return None
    for v in victims:
        out["calm"][v["tenant"]] = 0
    return {
        "action": "grant",
        "tenant": claimant["name"],
        "units": gathered,
        "from_free": from_free,
        "victims": victims,
    }


class ClusterScheduler:
    """Owns the N-tenant unit ledger; issues and reclaims leases.

    Tenants come from a :class:`TenantRegistry`; adapters speak the
    pool tenant protocol. Initial holdings are each adapter's
    ``initial_units`` (or the spec floor), and must fit the pool.
    """

    def __init__(
        self,
        registry: TenantRegistry,
        config: Optional[ClusterConfig] = None,
        trace_incidents: bool = False,
        exporter=None,
    ):
        self.cfg = config or ClusterConfig.from_env()
        self.registry = registry
        registry.validate(self.cfg.total_units)
        self.trace_incidents = trace_incidents
        self._mu = threading.Lock()
        self._alloc: Dict[str, int] = {}
        for spec in registry.specs():
            adapter = registry.adapter(spec.name)
            held = int(getattr(adapter, "initial_units", 0) or 0)
            self._alloc[spec.name] = held or spec.floor
        total_held = sum(self._alloc.values())
        if total_held > self.cfg.total_units:
            raise ValueError(
                "tenants hold more units than the pool: "
                f"{total_held} > {self.cfg.total_units}"
            )
        self._free = self.cfg.total_units - total_held
        self._baseline: Dict[str, int] = {
            s.name: self._alloc[s.name]
            for s in registry.specs()
            if s.kind == SERVE
        }
        self._calm: Dict[str, int] = {n: 0 for n in registry.names()}
        self._targets: Dict[str, Dict] = {}
        self._pending: List[ClusterLease] = []
        self._next_lease_id = 0
        self._journal = DecisionJournal(self.cfg.journal_path)
        self.last_signals: Dict[str, Optional[Dict]] = {}
        self.last_verdict: Dict[str, Any] = {}
        self.last_adopt_s: Optional[float] = None
        self.evaluations = 0
        self.revokes = 0
        self.grants = 0
        self.escalations = 0
        self.adoptions = 0
        self.phases = PhaseAccumulator()
        # an explicit exporter pins the event sink per scheduler (the
        # drill aims it at its own dir so tpurun-trace can merge the
        # cascade without depending on the process-global default)
        self._emitter = EventEmitter("cluster", exporter=exporter)
        # serializes whole evaluations (periodic loop vs POST
        # /cluster/step), the pool's _step_mu discipline
        self._step_mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- ledger views ----------------------------------------------------

    def allocations(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._alloc)

    def free_units(self) -> int:
        with self._mu:
            return self._free

    def pending_leases(self) -> List[ClusterLease]:
        with self._mu:
            return list(self._pending)

    def wait_idle(self, timeout: float = 60.0) -> bool:
        """Block until no revocation is in flight (drill/test helper)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._mu:
                if not self._pending:
                    return True
            if self._stop.wait(0.05):
                with self._mu:
                    return not self._pending
        return False

    # -- journal ---------------------------------------------------------

    def _record(self, event: str, **detail) -> Dict:
        """Journal one ledger event (caller may hold ``_mu``)."""
        return self._journal.record(
            event, self._alloc, self._free, **detail
        )

    def journal(self, tail: int = 0) -> List[Dict]:
        with self._mu:
            return self._journal.tail(tail)

    # -- brain targets ---------------------------------------------------

    def set_target(
        self, name: str, units: int, source: str = "brain"
    ) -> None:
        """Adopt a per-tenant target world as demand. Raises on an
        unknown tenant, and surfaces a chaos-injected failure to the
        caller (the brain loop journals and survives it)."""
        if name not in self.registry:
            raise KeyError(f"unknown tenant {name!r}")
        faults.inject("cluster.brain_target", tenant=name, units=units)
        with self._mu:
            prev = self._targets.get(name)
            if prev is not None and prev["units"] == units:
                return  # unchanged opinion keeps its adoption clock
            self._targets[name] = {
                "units": int(units),
                "source": source,
                "set_t": time.monotonic(),
                "adopted": False,
            }
            self._record(
                "target", tenant=name, units=int(units), source=source
            )
            if self._alloc.get(name, 0) >= int(units):
                # a target at or below current holdings is satisfied
                # the moment it lands (a SHRINK opinion is demand the
                # scheduler meets by not defending the surplus) —
                # adoption latency zero, and the grant path never runs
                self._targets[name]["adopted"] = True
                self.adoptions += 1
                self.last_adopt_s = 0.0
                self._record(
                    "target_adopted", tenant=name, adopt_s=0.0
                )

    def clear_target(self, name: str) -> None:
        with self._mu:
            self._targets.pop(name, None)

    def targets(self) -> Dict[str, Dict]:
        with self._mu:
            return {
                n: {"units": t["units"], "source": t["source"]}
                for n, t in self._targets.items()
            }

    # -- signal collection -----------------------------------------------

    def _collect(self, name: str) -> Optional[Dict]:
        adapter = self.registry.adapter(name)
        if adapter is None:
            return None
        try:
            return adapter.report()
        except Exception as e:  # noqa: BLE001 — one dark report
            logger.warning("cluster: %s report failed: %r", name, e)
            with self._mu:
                self._record(
                    "report_error", tenant=name, error=repr(e)[:200]
                )
            return None

    # -- policy loop -----------------------------------------------------

    def step(self) -> Dict:
        """One evaluate→decide→execute round; returns the verdict."""
        with self._step_mu:
            return self._step_locked()

    def _step_locked(self) -> Dict:
        self.evaluations += 1
        signals = {
            name: self._collect(name) for name in self.registry.names()
        }
        self.last_signals = signals
        self._check_deadlines()
        try:
            # chaos hook: an errored evaluation models a scheduler
            # whose control plane is dark — it must skip the round,
            # never wedge or move capacity it did not decide on
            faults.inject("cluster.schedule")
        except Exception as e:  # noqa: BLE001 — injected
            with self._mu:
                self._record("schedule_error", error=repr(e)[:200])
            return {
                "action": None,
                "reason": f"schedule error: {e!r}",
            }
        with self._mu:
            if len(self._pending) >= len(self.registry):
                return {
                    "action": None,
                    "reason": "all tenants busy",
                    "pending": [l.snapshot() for l in self._pending],
                }
            busy = {l.tenant for l in self._pending}
            busy |= {l.grant_to for l in self._pending if l.grant_to}
            views = [
                self._tenant_view(spec, signals.get(spec.name), busy)
                for spec in self.registry.specs()
            ]
            free = self._free
        verdict = schedule(views, free, self.cfg)
        self.last_verdict = verdict
        with self._mu:
            self._calm.update(verdict.get("calm", {}))
        if verdict["action"] == "grant":
            self._execute(verdict)
        elif verdict["action"] == "release":
            self._execute_release(verdict)
        return verdict

    def _tenant_view(
        self, spec, sig: Optional[Dict], busy
    ) -> Dict[str, Any]:
        """Build one policy-input dict (caller holds ``_mu``)."""
        target = self._targets.get(spec.name)
        return {
            "name": spec.name,
            "kind": spec.kind,
            "priority": spec.priority,
            "floor": spec.floor,
            "ceiling": self.registry.ceiling(
                spec.name, self.cfg.total_units
            ),
            "node_unit": spec.node_unit,
            "held": self._alloc[spec.name],
            "target": target["units"] if target else None,
            "signals": sig,
            "calm_streak": self._calm.get(spec.name, 0),
            "baseline": self._baseline.get(spec.name, 0),
            "busy": spec.name in busy,
            "expandable": spec.expandable,
            "attached": self.registry.adapter(spec.name) is not None,
            "queue_high": spec.queue_high,
            "p95_target_s": spec.p95_target_s,
        }

    def _execute(self, verdict: Dict) -> None:
        claimant = verdict["tenant"]
        victims = verdict.get("victims", [])
        if self.trace_incidents and victims and trace.current() is None:
            trace.start_incident()
        if victims:
            self._emitter.instant(
                "cluster_breach",
                tenant=claimant,
                units=verdict["units"],
                reason=verdict["reason"],
            )
        self._emitter.instant(
            "cluster_decision",
            tenant=claimant,
            units=verdict["units"],
            from_free=verdict["from_free"],
            victims=victims,
            reason=verdict["reason"],
        )
        with self._mu:
            self._record(
                "decision",
                tenant=claimant,
                units=verdict["units"],
                from_free=verdict["from_free"],
                victims=victims,
                reason=verdict["reason"],
            )
        if verdict["from_free"]:
            self._grant(
                claimant,
                verdict["from_free"],
                reason=verdict["reason"],
            )
        for v in victims:
            self._revoke(
                v["tenant"],
                v["units"],
                grant_to=claimant,
                reason=verdict["reason"],
            )

    def _execute_release(self, verdict: Dict) -> None:
        """A no-recipient shrink: revoke with no grant leg — the
        drained units land in the free ledger (``_on_released`` /
        ``_escalate`` skip the grant when ``grant_to`` is empty)."""
        donor = verdict["tenant"]
        self._emitter.instant(
            "cluster_decision",
            tenant=donor,
            units=verdict["units"],
            from_free=0,
            victims=[{"tenant": donor, "units": verdict["units"]}],
            reason=verdict["reason"],
        )
        with self._mu:
            self._record(
                "decision",
                tenant=donor,
                units=verdict["units"],
                from_free=0,
                victims=[{"tenant": donor, "units": verdict["units"]}],
                reason=verdict["reason"],
            )
        self._revoke(
            donor, verdict["units"], grant_to="", reason=verdict["reason"]
        )

    def _check_deadlines(self) -> None:
        with self._mu:
            overdue = [
                l
                for l in self._pending
                if time.monotonic() > l.deadline_t
            ]
        for lease in overdue:
            self._escalate(lease)

    # -- moves (the pool's lease machine, keyed by tenant name) ----------

    def _revoke(
        self, frm: str, units: int, grant_to: str, reason: str
    ) -> None:
        adapter = self.registry.adapter(frm)
        if adapter is None:
            with self._mu:
                self._alloc[frm] -= units
                self._free += units
                self._record(
                    "release", tenant=frm, units=units, reason="no adapter"
                )
            if grant_to:
                self._grant(grant_to, units, reason=reason)
            return
        t0 = time.perf_counter()
        with self._mu:
            held = self._alloc[frm]
            lease = ClusterLease(
                lease_id=self._next_lease_id,
                tenant=frm,
                units=units,
                deadline_t=time.monotonic()
                + self.cfg.revoke_deadline_s,
                grant_to=grant_to,
                reason=reason,
            )
            self._next_lease_id += 1
            self._pending.append(lease)
            self.revokes += 1
            self._record(
                "revoke",
                lease_id=lease.lease_id,
                tenant=frm,
                units=units,
                grant_to=grant_to,
                reason=reason,
                deadline_s=self.cfg.revoke_deadline_s,
            )
        # the drain leg of the cascade trace: from/to "rungs" are the
        # victim's world before/after, so tpurun-trace labels each
        # victim's cost (reshard_transitions)
        lease.span = self._emitter.duration(
            "cluster_revoke",
            tenant=frm,
            units=units,
            lease_id=lease.lease_id,
            from_rung=f"{frm}@{held}",
            to_rung=f"{frm}@{held - units}",
        ).begin()
        try:
            adapter.revoke(
                units,
                self.cfg.revoke_deadline_s,
                lambda released=units, _l=lease: self._on_released(
                    _l, released
                ),
            )
        except Exception as e:  # noqa: BLE001 — dispatch failed: the
            # deadline still stands; escalation reclaims at expiry
            logger.warning(
                "cluster: revoke dispatch to %s failed: %r", frm, e
            )
            with self._mu:
                self._record(
                    "revoke_error",
                    lease_id=lease.lease_id,
                    tenant=frm,
                    error=repr(e)[:200],
                )
        self.phases.add("revoke", time.perf_counter() - t0)

    def _on_released(self, lease: ClusterLease, released: int) -> None:
        """Tenant-side confirmation (tenant drain thread). ``released``
        may EXCEED the leased units — a gang shrink can only land on
        grid worlds — and the ledger moves by what was actually freed
        (the grant stays clamped; excess sits in the free pool)."""
        with self._mu:
            if lease.state != LeaseState.REVOKING:
                self._record(
                    "late_release",
                    lease_id=lease.lease_id,
                    tenant=lease.tenant,
                    units=released,
                )
                return
            lease.state = LeaseState.RELEASED
            lease.released_units = released
            self._pending.remove(lease)
            self._alloc[lease.tenant] -= released
            self._free += released
            drain_s = time.monotonic() - lease.created_t
            self._record(
                "release",
                lease_id=lease.lease_id,
                tenant=lease.tenant,
                units=released,
                drain_s=round(drain_s, 3),
            )
        if lease.span is not None:
            lease.span.end({"released": released})
        self.phases.add("drain", drain_s)
        if lease.grant_to and released > 0:
            self._grant(
                lease.grant_to,
                min(released, lease.units),
                reason=lease.reason,
            )

    def _escalate(self, lease: ClusterLease) -> None:
        """Cooperative drain missed its deadline: force the reclaim."""
        adapter = self.registry.adapter(lease.tenant)
        with self._mu:
            if lease.state != LeaseState.REVOKING:
                return
            lease.state = LeaseState.ESCALATED
            self.escalations += 1
            self._record(
                "escalate",
                lease_id=lease.lease_id,
                tenant=lease.tenant,
                units=lease.units,
                overdue_s=round(
                    time.monotonic() - lease.deadline_t, 3
                ),
            )
        freed = 0
        try:
            freed = int(adapter.escalate(lease.units))
        except Exception as e:  # noqa: BLE001 — even the hard path
            # failed: journal it; the units stay with the tenant (the
            # ledger never claims capacity nobody actually freed)
            logger.error(
                "cluster: escalation on %s failed: %r",
                lease.tenant,
                e,
            )
            with self._mu:
                self._record(
                    "escalate_error",
                    lease_id=lease.lease_id,
                    tenant=lease.tenant,
                    error=repr(e)[:200],
                )
        with self._mu:
            if lease in self._pending:
                self._pending.remove(lease)
            lease.released_units = freed
            self._alloc[lease.tenant] -= freed
            self._free += freed
            drain_s = time.monotonic() - lease.created_t
            if freed:
                self._record(
                    "escalate_freed",
                    lease_id=lease.lease_id,
                    tenant=lease.tenant,
                    units=freed,
                    drain_s=round(drain_s, 3),
                )
        if lease.span is not None:
            lease.span.end({"released": freed, "escalated": True})
        self.phases.add("drain", drain_s)
        if lease.grant_to and freed > 0:
            self._grant(
                lease.grant_to,
                min(freed, lease.units),
                reason=lease.reason,
            )

    def _grant(self, to: str, units: int, reason: str) -> None:
        adapter = self.registry.adapter(to)
        ceiling = self.registry.ceiling(to, self.cfg.total_units)
        with self._mu:
            # clamp to the FREE ledger too, not just the ceiling: a
            # drain-thread release and a concurrent step() can both
            # try to place the same freed units — whichever grant runs
            # second must find them spent, never drive _free negative
            grantable = min(
                units, ceiling - self._alloc.get(to, 0), self._free
            )
            if adapter is None or grantable <= 0:
                self._record(
                    "grant_skipped",
                    tenant=to,
                    units=units,
                    reason=reason,
                )
                return
            units = grantable
            self._alloc[to] += units
            self._free -= units
            self.grants += 1
            self._record("grant", tenant=to, units=units, reason=reason)
            adopt_s = self._note_adoption_locked(to)
        span = self._emitter.duration(
            "cluster_grant", tenant=to, units=units, reason=reason
        ).begin()
        t0 = time.perf_counter()
        try:
            adapter.grant(units)
        except Exception as e:  # noqa: BLE001 — the tenant could not
            # apply the capacity: roll the ledger back to free so a
            # later eval can retry the move
            logger.warning("cluster: grant to %s failed: %r", to, e)
            span.fail(repr(e)[:200])
            with self._mu:
                self._alloc[to] -= units
                self._free += units
                self._record(
                    "grant_error",
                    tenant=to,
                    units=units,
                    error=repr(e)[:200],
                )
            return
        span.end({"adopt_s": adopt_s} if adopt_s is not None else None)
        self.phases.add("grant", time.perf_counter() - t0)

    def _note_adoption_locked(self, to: str) -> Optional[float]:
        """Brain-target adoption latency: first grant that lifts the
        tenant to (or past) its target closes the adoption clock.
        Caller holds ``_mu``."""
        target = self._targets.get(to)
        if (
            target is None
            or target["adopted"]
            or self._alloc[to] < target["units"]
        ):
            return None
        target["adopted"] = True
        adopt_s = time.monotonic() - target["set_t"]
        self.adoptions += 1
        self.last_adopt_s = adopt_s
        self._record(
            "target_adopted",
            tenant=to,
            units=target["units"],
            source=target["source"],
            adopt_s=round(adopt_s, 3),
        )
        return round(adopt_s, 6)

    # -- status ----------------------------------------------------------

    def status(self) -> Dict:
        with self._mu:
            out = {
                "total_units": self.cfg.total_units,
                "allocations": dict(self._alloc),
                "free": self._free,
                "pending": [l.snapshot() for l in self._pending],
                "calm": dict(self._calm),
                "targets": {
                    n: {
                        "units": t["units"],
                        "source": t["source"],
                        "adopted": t["adopted"],
                    }
                    for n, t in self._targets.items()
                },
                "counters": {
                    "evaluations": self.evaluations,
                    "revokes": self.revokes,
                    "grants": self.grants,
                    "escalations": self.escalations,
                    "adoptions": self.adoptions,
                },
                "journal_tail": self._journal.tail(20),
            }
        out["signals"] = self.last_signals
        out["phase_split"] = self.phases.split().summary()
        out["tenants"] = {
            s.name: {
                "kind": s.kind,
                "priority": s.priority,
                "floor": s.floor,
                "ceiling": self.registry.ceiling(
                    s.name, self.cfg.total_units
                ),
                "node_unit": s.node_unit,
            }
            for s in self.registry.specs()
        }
        return out

    # -- periodic driver -------------------------------------------------

    def start(self) -> "ClusterScheduler":
        """Periodic evaluation at ``eval_interval_s`` (0 = manual
        ``step()`` only — start() is then a no-op)."""
        if self.cfg.eval_interval_s <= 0:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="cluster-scheduler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — scheduler survives
                logger.exception("cluster scheduler error: %s", e)
            self._stop.wait(self.cfg.eval_interval_s)
