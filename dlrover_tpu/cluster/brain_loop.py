"""BrainFeedback: close the loop from live metrics to scheduler demand.

The paper's Brain layer sits between the metrics plane and the
scheduler: observations flow *in* (master ``metrics_snapshot()``,
per-fleet ``fleet_signals()``, per-trainer controller reports → the
``brain/datastore.py`` job-profile store) and **per-tenant target
worlds** flow *out* (``ClusterResourceArbiter.allocate`` splits the
training share of the pool by marginal throughput gain;
``JobRunningResourceAlgorithm.optimize`` refines each job against its
scaling knee). The emitted targets land in
``ClusterScheduler.set_target`` — the scheduler treats them as demand,
replacing static knob targets (docs/cluster.md).

This module is the live caller ``brain/algorithms.py`` was missing:
before it, ``ClusterResourceArbiter.allocate()`` was a stub nothing
exercised.
"""

import json
import threading
from typing import Any, Callable, Dict, Optional

from ..brain.algorithms import (
    ClusterResourceArbiter,
    JobRunningResourceAlgorithm,
)
from ..brain.datastore import BrainDataStore, JobMetricSample, JobRecord
from ..common.log import logger
from .registry import SERVE

__all__ = ["BrainFeedback"]


class BrainFeedback:
    """Metrics in, targets out, on a fixed cadence (or manually via
    ``poll_once()`` / ``evaluate_once()`` for tests and drills)."""

    def __init__(
        self,
        scheduler,
        store: Optional[BrainDataStore] = None,
        master: Any = None,
        master_job: str = "",
        min_samples: int = 0,
        eval_interval_s: float = 0.0,
    ):
        self.scheduler = scheduler
        self.store = store or BrainDataStore(":memory:")
        self.master = master
        # tenant name whose job profile the master's snapshot feeds
        # (the master aggregates exactly one training job)
        self.master_job = master_job
        cfg = scheduler.cfg
        self.min_samples = min_samples or cfg.brain_min_samples
        self.eval_interval_s = eval_interval_s or cfg.brain_eval_s
        self._trainers: Dict[str, Any] = {}
        self._fleets: Dict[str, Callable[[], Dict]] = {}
        self.polls = 0
        self.evaluations = 0
        self.emissions = 0
        self.target_errors = 0
        self.last_targets: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- source registration ---------------------------------------------

    def add_training_job(
        self,
        tenant: str,
        controller: Any,
        model_signature: str = "elastic-train",
    ) -> None:
        """Track one training tenant: its controller's ``report()``
        feeds the job's scaling curve sample by sample."""
        spec = self.scheduler.registry.spec(tenant)
        self._trainers[tenant] = controller
        self.store.upsert_job(
            JobRecord(
                job_uuid=tenant,
                job_name=tenant,
                model_signature=model_signature,
                worker_num=self.scheduler.allocations().get(tenant, 0),
                node_unit=spec.node_unit,
            )
        )

    def add_fleet(
        self, tenant: str, signals_fn: Callable[[], Dict]
    ) -> None:
        """Track one serving tenant's ``fleet_signals()`` source; the
        signal history lands in the datastore's event stream."""
        self._fleets[tenant] = signals_fn

    # -- ingestion (metrics plane -> datastore) ---------------------------

    def poll_once(self) -> int:
        """One ingestion round; returns the number of samples stored."""
        self.polls += 1
        stored = 0
        held = self.scheduler.allocations()
        for tenant, controller in self._trainers.items():
            try:
                rep = controller.report() or {}
            except Exception as e:  # noqa: BLE001 — one dark trainer
                logger.warning(
                    "brain: %s report failed: %r", tenant, e
                )
                continue
            world = int(rep.get("world") or held.get(tenant, 0))
            sps = float(rep.get("steps_per_s") or 0.0)
            if world <= 0 or sps <= 0:
                continue  # no signal yet; don't poison the curve
            self.store.add_metric(
                JobMetricSample(
                    job_uuid=tenant,
                    world_size=world,
                    steps_per_second=sps,
                )
            )
            stored += 1
        if self.master is not None and self.master_job:
            try:
                gauges = self.master.metrics_snapshot()
            except Exception as e:  # noqa: BLE001 — master dark
                logger.warning("brain: master snapshot failed: %r", e)
                gauges = {}
            if gauges:
                sample = self.store.ingest_gauges(
                    self.master_job,
                    gauges,
                    world_size=held.get(self.master_job, 0),
                )
                if sample is not None:
                    stored += 1
        for tenant, signals_fn in self._fleets.items():
            try:
                sig = signals_fn() or {}
            except Exception as e:  # noqa: BLE001 — one dark fleet
                logger.warning(
                    "brain: %s signals failed: %r", tenant, e
                )
                continue
            self.store.add_event(
                tenant, "fleet_signals", detail=json.dumps(sig)
            )
        return stored

    # -- evaluation (datastore -> per-tenant targets) ---------------------

    def _train_budget(self) -> int:
        """Units the training tenants may split: the pool minus what
        serving currently holds (serving keeps what the SLO policy
        gave it; brain arbitrates the rest)."""
        held = self.scheduler.allocations()
        serve_held = sum(
            held.get(s.name, 0)
            for s in self.scheduler.registry.specs()
            if s.kind == SERVE
        )
        return self.scheduler.cfg.total_units - serve_held

    def _sampled_jobs(self) -> Dict[str, int]:
        """Training tenants with enough metric history to trust,
        mapped to their current holdings."""
        held = self.scheduler.allocations()
        out = {}
        for tenant in self._trainers:
            if (
                len(
                    self.store.job_metrics(
                        tenant, limit=self.min_samples
                    )
                )
                >= self.min_samples
            ):
                out[tenant] = held.get(tenant, 0)
        return out

    def evaluate_once(self) -> Dict[str, int]:
        """One optimization round: split the training budget across
        sampled jobs (``ClusterResourceArbiter.allocate`` — marginal
        gain per host), refine each share against the job's own
        scaling knee, and emit the targets as scheduler demand."""
        self.evaluations += 1
        jobs = self._sampled_jobs()
        if not jobs:
            return {}
        registry = self.scheduler.registry
        budget = self._train_budget()
        grid = min(
            registry.spec(t).node_unit for t in jobs
        )
        arbiter = ClusterResourceArbiter(self.store)
        allocation = arbiter.allocate(
            sorted(jobs), total_hosts=budget, node_unit=grid
        )
        running = JobRunningResourceAlgorithm(self.store)
        targets: Dict[str, int] = {}
        for tenant, current in jobs.items():
            share = allocation.get(tenant, 0)
            cap = share or registry.ceiling(
                tenant, self.scheduler.cfg.total_units
            )
            plan = running.optimize(
                tenant,
                current_workers=current,
                node_unit=registry.spec(tenant).node_unit,
                max_workers=cap,
            )
            # the knee refines the arbiter's split downward; with no
            # usable knee the split itself is the target
            target = plan.worker_num if plan.worker_num > 0 else share
            if target <= 0:
                continue
            targets[tenant] = target
        for tenant, target in targets.items():
            try:
                self.scheduler.set_target(tenant, target, source="brain")
                self.emissions += 1
            except Exception as e:  # noqa: BLE001 — chaos-injected or
                # racing tenant teardown: journal and keep the loop
                self.target_errors += 1
                logger.warning(
                    "brain: target emission for %s failed: %r",
                    tenant,
                    e,
                )
                self.store.add_event(
                    tenant, "brain_target_error", detail=repr(e)[:200]
                )
        self.last_targets = targets
        return targets

    # -- periodic driver -------------------------------------------------

    def start(self) -> "BrainFeedback":
        """Poll + evaluate at ``brain_eval_s`` (0 = manual only)."""
        if self.eval_interval_s <= 0:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="brain-feedback", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
                self.evaluate_once()
            except Exception as e:  # noqa: BLE001 — loop survives
                logger.exception("brain feedback error: %s", e)
            self._stop.wait(self.eval_interval_s)

    def status(self) -> Dict:
        return {
            "polls": self.polls,
            "evaluations": self.evaluations,
            "emissions": self.emissions,
            "target_errors": self.target_errors,
            "last_targets": dict(self.last_targets),
            "min_samples": self.min_samples,
        }
