"""Tenant registry: priority classes, bounds, and the gang grid.

A **tenant** is one workload holding a revocable share of the pool —
a serving fleet (``pool/tenants.py::ServingTenant``) or a training job
(``TrainingTenant`` over a ``LoopTrainingController``). The registry
binds each tenant's *adapter* (the report/grant/revoke/escalate
protocol the PR 8 arbiter defined) to a :class:`TenantSpec`:

- **priority**: an integer rank, lower = more important. Ranks come
  from the operator's priority-class table
  (``DLROVER_CLUSTER_PRIORITY_CLASSES``, e.g. ``critical=0``,
  ``preemptible=30``) or are given directly. The scheduler grants
  deficits in ascending rank order and revokes from the **highest**
  rank (lowest priority) above floor first.
- **floor / ceiling**: capacity a tenant is never revoked below /
  granted above (ceiling 0 = the whole pool). Floors are reserved —
  their sum must fit the pool.
- **node_unit**: the gang grid. Every grant/revoke sized against this
  tenant is snapped to a multiple of ``node_unit`` (a training job can
  only land on grid worlds; serving replicas use ``node_unit=1``).
- per-tenant SLO overrides (``queue_high`` / ``p95_target_s``) for
  serving tenants whose breach thresholds differ from the cluster
  default.
"""

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Union

__all__ = [
    "SERVE",
    "TRAIN",
    "TenantRegistry",
    "TenantSpec",
    "parse_priority_classes",
]

TRAIN = "train"
SERVE = "serve"


def parse_priority_classes(text: str) -> Dict[str, int]:
    """``"critical=0,high=10"`` → ``{"critical": 0, "high": 10}``."""
    classes: Dict[str, int] = {}
    for part in (text or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"priority class {part!r} is not name=rank"
            )
        name, _, rank = part.partition("=")
        classes[name.strip()] = int(rank)
    return classes


def resolve_priority(
    value: Union[int, str], classes: Dict[str, int]
) -> int:
    """A priority is a class name from the table or a bare rank."""
    if isinstance(value, int):
        return value
    text = str(value).strip()
    if text in classes:
        return classes[text]
    try:
        return int(text)
    except ValueError:
        raise ValueError(
            f"unknown priority class {text!r} "
            f"(known: {sorted(classes)})"
        ) from None


@dataclass
class TenantSpec:
    """Scheduling contract for one tenant (docs/cluster.md)."""

    name: str
    kind: str  # TRAIN | SERVE
    priority: int = 20  # rank; lower = more important
    floor: int = 0  # never revoked below
    ceiling: int = 0  # never granted above (0 = whole pool)
    node_unit: int = 1  # gang grid for grants/revokes
    # SLO overrides for serving tenants (None = cluster default)
    queue_high: Optional[float] = None
    p95_target_s: Optional[float] = None
    # whether idle free units may be parked here absent an explicit
    # target (the pool's "reclaim" branch); None = kind == TRAIN
    expandable: Optional[bool] = None

    def __post_init__(self):
        if self.kind not in (TRAIN, SERVE):
            raise ValueError(
                f"tenant {self.name!r}: kind must be "
                f"{TRAIN!r}|{SERVE!r}, got {self.kind!r}"
            )
        if self.node_unit < 1:
            raise ValueError(
                f"tenant {self.name!r}: node_unit must be >= 1"
            )
        if self.floor < 0:
            raise ValueError(
                f"tenant {self.name!r}: floor must be >= 0"
            )
        if self.floor % self.node_unit:
            raise ValueError(
                f"tenant {self.name!r}: floor {self.floor} off the "
                f"node_unit={self.node_unit} grid"
            )
        if self.ceiling and self.ceiling % self.node_unit:
            raise ValueError(
                f"tenant {self.name!r}: ceiling {self.ceiling} off "
                f"the node_unit={self.node_unit} grid"
            )
        if self.ceiling and self.floor > self.ceiling:
            raise ValueError(
                f"tenant {self.name!r}: floor above ceiling"
            )
        if self.expandable is None:
            self.expandable = self.kind == TRAIN

    @classmethod
    def parse(
        cls, entry: str, classes: Dict[str, int]
    ) -> "TenantSpec":
        """One ``DLROVER_CLUSTER_TENANTS`` entry:
        ``name:kind:priority[:floor[:ceiling[:node_unit]]]``."""
        parts = [p.strip() for p in entry.split(":")]
        if len(parts) < 3:
            raise ValueError(
                f"tenant spec {entry!r}: need at least "
                "name:kind:priority"
            )
        kw: Dict[str, Any] = {
            "name": parts[0],
            "kind": parts[1],
            "priority": resolve_priority(parts[2], classes),
        }
        for field_name, idx in (
            ("floor", 3),
            ("ceiling", 4),
            ("node_unit", 5),
        ):
            if len(parts) > idx and parts[idx]:
                kw[field_name] = int(parts[idx])
        return cls(**kw)


class TenantRegistry:
    """Name → (spec, adapter) with roster-level validation.

    The adapter is anything speaking the pool tenant protocol:
    ``initial_units`` (attr), ``report()``, ``grant(units)``,
    ``revoke(units, deadline_s, on_released)``, ``escalate(units)``.
    ``ServingTenant`` / ``TrainingTenant`` qualify unchanged — the
    registry is how the PR 8 two-tenant pool generalizes without a
    new tenant-side contract.
    """

    def __init__(self, priority_classes: Optional[Dict[str, int]] = None):
        self.priority_classes = dict(priority_classes or {})
        self._specs: Dict[str, TenantSpec] = {}
        self._adapters: Dict[str, Any] = {}
        self._order: List[str] = []  # registration order, for ties

    @classmethod
    def from_config(cls, cfg) -> "TenantRegistry":
        """Registry pre-seeded with specs parsed from
        ``cfg.tenants`` (adapters attached later via ``attach``)."""
        reg = cls(parse_priority_classes(cfg.priority_classes))
        for entry in (cfg.tenants or "").split(";"):
            entry = entry.strip()
            if entry:
                spec = TenantSpec.parse(entry, reg.priority_classes)
                reg.register(spec, adapter=None)
        return reg

    def register(self, spec: TenantSpec, adapter: Any) -> TenantSpec:
        if spec.name in self._specs:
            raise ValueError(
                f"tenant {spec.name!r} already registered"
            )
        self._specs[spec.name] = spec
        self._adapters[spec.name] = adapter
        self._order.append(spec.name)
        return spec

    def attach(self, name: str, adapter: Any) -> None:
        if name not in self._specs:
            raise KeyError(f"unknown tenant {name!r}")
        self._adapters[name] = adapter

    def names(self) -> List[str]:
        return list(self._order)

    def specs(self) -> List[TenantSpec]:
        return [self._specs[n] for n in self._order]

    def spec(self, name: str) -> TenantSpec:
        return self._specs[name]

    def adapter(self, name: str) -> Any:
        return self._adapters.get(name)

    def __contains__(self, name: str) -> bool:
        return name in self._specs

    def __len__(self) -> int:
        return len(self._order)

    def validate(self, total_units: int) -> None:
        """Roster-level invariants against one pool inventory."""
        floors = sum(s.floor for s in self._specs.values())
        if floors > total_units:
            raise ValueError(
                f"tenant floors exceed the pool: {floors} > "
                f"{total_units}"
            )
        for s in self._specs.values():
            if s.ceiling > total_units:
                raise ValueError(
                    f"tenant {s.name!r}: ceiling {s.ceiling} above "
                    f"the pool ({total_units})"
                )

    def ceiling(self, name: str, total_units: int) -> int:
        c = self._specs[name].ceiling
        return c if c > 0 else total_units
