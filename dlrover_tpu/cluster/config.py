"""Cluster configuration: the ``DLROVER_CLUSTER_*`` operator surface.

One typed dataclass consumed by the scheduler, the tenant registry,
the brain loop, the ``tpurun-cluster`` CLI, and the drill. Every field
is overridable through a registered env knob (``common/constants.py
ENV_KNOBS`` — the ``tpurun-lint`` env-knobs pass enforces registered ⇔
documented ⇔ referenced), mirroring the pool's ``DLROVER_POOL_*``
contract (docs/cluster.md knob table).
"""

from dataclasses import dataclass, fields

from ..common.constants import ENV_KNOBS

# field name -> env knob. Declared next to the dataclass so a new
# field and its knob land in the same diff (the lint staleness check
# fails on either half missing).
_CLUSTER_KNOBS = {
    "total_units": "DLROVER_CLUSTER_TOTAL_UNITS",
    "tenants": "DLROVER_CLUSTER_TENANTS",
    "priority_classes": "DLROVER_CLUSTER_PRIORITY_CLASSES",
    "eval_interval_s": "DLROVER_CLUSTER_EVAL_INTERVAL_S",
    "revoke_deadline_s": "DLROVER_CLUSTER_REVOKE_DEADLINE_S",
    "handback_evals": "DLROVER_CLUSTER_HANDBACK_EVALS",
    "spike_units": "DLROVER_CLUSTER_SPIKE_UNITS",
    "queue_high": "DLROVER_CLUSTER_QUEUE_HIGH",
    "p95_target_s": "DLROVER_CLUSTER_P95_TARGET_S",
    "brain_eval_s": "DLROVER_CLUSTER_BRAIN_EVAL_S",
    "brain_min_samples": "DLROVER_CLUSTER_BRAIN_MIN_SAMPLES",
    "journal_path": "DLROVER_CLUSTER_JOURNAL",
    "status_timeout_s": "DLROVER_CLUSTER_STATUS_TIMEOUT_S",
}


@dataclass
class ClusterConfig:
    """Knobs for one N-tenant cluster scheduler (docs/cluster.md)."""

    # inventory: device-capacity units (1 unit = 1 serving replica =
    # 1 training worker-host at node_unit granularity)
    total_units: int = 8

    # declarative tenant roster for the CLI/serve shape, parsed by
    # ``registry.TenantRegistry.parse`` — semicolon-separated
    # ``name:kind:priority[:floor[:ceiling[:node_unit]]]`` entries,
    # e.g. ``api:serve:critical:1;batch:train:preemptible:1:0:2``.
    # Priority accepts a class name from ``priority_classes`` or a
    # bare integer rank. Empty = tenants registered programmatically.
    tenants: str = ""

    # priority-class table: ``name=rank`` pairs, lower rank = more
    # important (revoked last, granted first)
    priority_classes: str = "critical=0,high=10,standard=20,preemptible=30"

    # policy loop
    eval_interval_s: float = 0.0  # 0 = manual step() only
    revoke_deadline_s: float = 30.0  # cooperative drain budget
    handback_evals: int = 3  # calm evals before surge units return
    spike_units: int = 1  # units moved per breach decision

    # serving SLO defaults (a TenantSpec may override per tenant)
    queue_high: float = 4.0  # mean queued/replica that breaches
    p95_target_s: float = 0.0  # p95 latency target (0 = off)

    # brain loop cadence (0 = manual evaluate_once() only) and the
    # metric-sample floor below which brain opinions are not adopted
    brain_eval_s: float = 0.0
    brain_min_samples: int = 2

    # decision journal (JSONL; empty = in-memory only)
    journal_path: str = ""

    # HTTP status endpoint client deadline (CLI, drill watchers)
    status_timeout_s: float = 10.0

    def __post_init__(self):
        if self.total_units < 2:
            raise ValueError(
                f"total_units must be >= 2 (one per tenant floor), got "
                f"{self.total_units}"
            )
        if self.revoke_deadline_s <= 0:
            raise ValueError("revoke_deadline_s must be > 0")
        if self.handback_evals < 1:
            raise ValueError("handback_evals must be >= 1")
        if self.spike_units < 1:
            raise ValueError("spike_units must be >= 1")
        if self.brain_min_samples < 1:
            raise ValueError("brain_min_samples must be >= 1")

    @classmethod
    def from_env(cls, **overrides) -> "ClusterConfig":
        """Defaults ← ``DLROVER_CLUSTER_*`` env ← explicit overrides."""
        kwargs = {}
        for f in fields(cls):
            knob = ENV_KNOBS[_CLUSTER_KNOBS[f.name]]
            val = knob.get()
            if val is not None:
                kwargs[f.name] = val
        kwargs.update(overrides)
        return cls(**kwargs)
