"""Shared-memory staging of jax pytrees (the "flash" in flash checkpoint).

Reference mechanism: ``SharedMemoryHandler`` (``ckpt_saver.py:234-397``) —
trainer memcpys tensors into POSIX shm; the agent persists asynchronously.
TPU version: the unit staged is each *addressable unique* device shard
(replica_id 0) of each pytree leaf, after an async device→host copy, so
the trainer blocks only for the D2H + memcpy, never for storage IO.

Layout of the segment: [u64 meta_len][meta JSON][payload bytes...].
"""

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..common.log import logger
from ..common.multi_process import SharedMemorySegment
from .meta import (
    HEADER_LEN_BYTES,
    CheckpointMeta,
    ShardRecord,
    assemble_global,
    jsonable_to_spec,
    spec_to_jsonable,
)


_IMAGE_CHUNK = 8 << 20


def segment_image_size(segment: SharedMemorySegment) -> int:
    """Logical byte length of a segment image
    (``[8B meta_len][meta JSON][payload]``), 0 when absent/invalid."""
    if not segment.attach():
        return 0
    try:
        meta_len = int.from_bytes(segment.read(0, HEADER_LEN_BYTES), "little")
        if meta_len <= 0 or meta_len > segment.size:
            return 0
        meta = CheckpointMeta.from_json(
            segment.read(HEADER_LEN_BYTES, meta_len).decode()
        )
        return HEADER_LEN_BYTES + meta_len + meta.total_bytes
    except Exception as e:  # noqa: BLE001 — torn/absent header reads as empty
        logger.debug("shm size probe: %r", e)
        return 0


def stream_into_segment(
    segment: SharedMemorySegment, total: int, read
) -> None:
    """Overwrite ``segment`` with a ``total``-byte image from ``read(n)``.

    Torn-write safe: the 8-byte header is zeroed first and written LAST,
    so a stream that dies mid-transfer leaves a segment whose meta never
    parses (readers see "empty") instead of a valid-looking image over a
    truncated payload. Raises on truncation; the header stays invalid.
    """
    segment.ensure(total)
    buf = segment.buf
    buf[:HEADER_LEN_BYTES] = b"\x00" * HEADER_LEN_BYTES
    header = b""
    off = 0
    while off < total:
        chunk = read(min(_IMAGE_CHUNK, total - off))
        if not chunk:
            raise IOError(f"segment image truncated at {off}/{total}")
        if off < HEADER_LEN_BYTES:
            take = min(len(chunk), HEADER_LEN_BYTES - off)
            header += chunk[:take]
            if len(chunk) > take:
                buf[off + take : off + len(chunk)] = chunk[take:]
        else:
            buf[off : off + len(chunk)] = chunk
        off += len(chunk)
    buf[:HEADER_LEN_BYTES] = header


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _leaf_records(path: str, leaf) -> List[Tuple[ShardRecord, Any]]:
    """Plan the shard records for one leaf (no data copied yet)."""
    records = []
    if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
        spec = []
        try:
            spec = spec_to_jsonable(leaf.sharding.spec)
        except Exception as e:  # noqa: BLE001 — exotic sharding: no spec
            logger.debug("sharding spec not jsonable: %r", e)
            spec = []
        seen_indices = set()
        for shard in leaf.addressable_shards:
            # Dedupe by index among THIS HOST's shards only (NOT by
            # replica_id): on a multi-process mesh a replicated leaf's
            # replica_id-0 copy lives on ONE host — filtering on it
            # would leave every other host's shm empty for that leaf,
            # making its staged checkpoint unrestorable after a re-mesh.
            key = tuple(
                (s.start or 0, s.stop if s.stop is not None else dim)
                for s, dim in zip(shard.index, leaf.shape)
            )
            if key in seen_indices:
                continue
            seen_indices.add(key)
            local_shape = [b - a for a, b in key]
            rec = ShardRecord(
                path=path,
                global_shape=list(leaf.shape),
                local_shape=local_shape,
                dtype=str(leaf.dtype),
                index=list(key),
                offset=0,
                nbytes=int(np.dtype(leaf.dtype).itemsize * np.prod(local_shape or [1])),
                spec=spec,
            )
            records.append((rec, shard))
        return records
    # Host array / scalar: one full record
    arr = np.asarray(leaf)
    rec = ShardRecord(
        path=path,
        global_shape=list(arr.shape),
        local_shape=list(arr.shape),
        dtype=str(arr.dtype),
        index=[(0, d) for d in arr.shape],
        offset=0,
        nbytes=int(arr.nbytes),
        spec=[],
    )
    return [(rec, arr)]


class SharedMemoryHandler:
    """One shm segment per host shard of the checkpoint."""

    def __init__(self, host_rank: int = 0, name: str = ""):
        self.host_rank = host_rank
        self._segment = SharedMemorySegment(name or f"ckpt_shard_{host_rank}")

    # -- trainer side ------------------------------------------------------

    def save_pytree(
        self,
        step: int,
        pytree: Any,
        num_hosts: int = 1,
        mesh=None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> CheckpointMeta:
        flat, _ = jax.tree_util.tree_flatten_with_path(pytree)
        plan: List[Tuple[ShardRecord, Any]] = []
        for path, leaf in flat:
            plan.extend(_leaf_records(_path_str(path), leaf))

        # Start all D2H copies before any blocking read (overlap on TPU).
        for _, shard in plan:
            if isinstance(shard, np.ndarray):
                # ndarray.data raises ValueError for non-buffer dtypes
                # (ml_dtypes bfloat16), and a host array has no D2H copy
                # to start anyway.
                continue
            data = getattr(shard, "data", None)
            if data is not None and hasattr(data, "copy_to_host_async"):
                data.copy_to_host_async()

        meta = CheckpointMeta(
            step=step,
            host_rank=self.host_rank,
            num_hosts=num_hosts,
            mesh_axes=list(getattr(mesh, "axis_names", []) or []),
            mesh_shape=[int(s) for s in getattr(mesh, "devices", np.empty(0)).shape]
            if mesh is not None
            else [],
            timestamp=time.time(),
            extra=extra or {},
        )
        offset = 0
        for rec, _ in plan:
            rec.offset = offset
            offset += rec.nbytes
            meta.records.append(rec)
        meta.total_bytes = offset

        meta_bytes = meta.to_json().encode()
        total = HEADER_LEN_BYTES + len(meta_bytes) + offset
        self._segment.ensure(total)
        buf = self._segment.buf
        # Header lands LAST: a trainer killed mid-stage must leave an
        # image that parses as absent, not a fresh meta over a torn
        # payload (the agent's breakpoint save would persist it).
        buf[:HEADER_LEN_BYTES] = b"\x00" * HEADER_LEN_BYTES
        payload_base = HEADER_LEN_BYTES + len(meta_bytes)
        buf[HEADER_LEN_BYTES:payload_base] = meta_bytes
        for rec, shard in plan:
            if isinstance(shard, np.ndarray):
                data = shard
            else:
                data = getattr(shard, "data", shard)
            flat = np.ascontiguousarray(np.asarray(data)).reshape(-1)
            start = payload_base + rec.offset
            view = np.frombuffer(buf, dtype=np.uint8, count=rec.nbytes, offset=start)
            view[:] = flat.view(np.uint8)
            del view  # release the exported buffer pointer promptly
        buf[:HEADER_LEN_BYTES] = len(meta_bytes).to_bytes(
            HEADER_LEN_BYTES, "little"
        )
        return meta

    # -- agent / loader side ----------------------------------------------

    def attach(self) -> bool:
        return self._segment.attach()

    def read_meta(self) -> Optional[CheckpointMeta]:
        if not self._segment.attach():
            return None
        try:
            meta_len = int.from_bytes(self._segment.read(0, HEADER_LEN_BYTES), "little")
            if meta_len <= 0 or meta_len > self._segment.size:
                return None
            return CheckpointMeta.from_json(
                self._segment.read(HEADER_LEN_BYTES, meta_len).decode()
            )
        except Exception:
            logger.exception("unreadable checkpoint shm meta")
            return None

    def payload_reader(
        self, copy: bool = True
    ) -> Optional[Callable[[int, int], Any]]:
        """Reader over the payload region. With ``copy=False`` the reader
        returns zero-copy memoryviews into the segment — valid only while
        the segment stays mapped and unmodified (hold the shard lock)."""
        meta = self.read_meta()
        if meta is None:
            return None
        meta_len = int.from_bytes(self._segment.read(0, HEADER_LEN_BYTES), "little")
        base = HEADER_LEN_BYTES + meta_len

        if copy:

            def read(offset: int, nbytes: int) -> bytes:
                return self._segment.read(base + offset, nbytes)

        else:
            buf = self._segment.buf

            def read(offset: int, nbytes: int):
                return buf[base + offset : base + offset + nbytes]

        return read

    def load_pytree_host(
        self, copy: bool = True
    ) -> Optional[Tuple[CheckpointMeta, Dict[str, np.ndarray]]]:
        """Reassemble {leaf_path: global np array} from this host's shm.

        Only complete when this host holds every shard (single-host case);
        multi-host loads go through the storage/gather paths. With
        ``copy=False``, unsharded leaves are zero-copy views into the
        segment (see :meth:`payload_reader`).
        """
        meta = self.read_meta()
        reader = self.payload_reader(copy=copy)
        if meta is None or reader is None:
            return None
        by_path: Dict[str, List[ShardRecord]] = {}
        for rec in meta.records:
            by_path.setdefault(rec.path, []).append(rec)
        out = {}
        for path, records in by_path.items():
            out[path] = assemble_global(
                records, lambda rec: reader(rec.offset, rec.nbytes)
            )
        return meta, out

    # -- raw segment image (peer replication) ------------------------------

    def image_size(self) -> int:
        """Total bytes of the current segment image, 0 when empty."""
        return segment_image_size(self._segment)

    def read_image(self, offset: int, nbytes: int) -> bytes:
        return self._segment.read(offset, nbytes)

    def write_image_stream(self, total: int, read) -> None:
        """Overwrite this segment with a ``total``-byte image streamed
        from ``read(n)`` (restore-from-peer path). Torn-write safe —
        see :func:`stream_into_segment`."""
        stream_into_segment(self._segment, total, read)

    def invalidate(self) -> None:
        """Zero the header so the staged image reads as absent (e.g. a
        stale peer image that must not be breakpoint-persisted)."""
        if self._segment.attach():
            buf = self._segment.buf
            if buf is not None and len(buf) >= HEADER_LEN_BYTES:
                buf[:HEADER_LEN_BYTES] = b"\x00" * HEADER_LEN_BYTES

    def exists(self) -> bool:
        return self._segment.exists()

    def close(self) -> None:
        self._segment.close()

    def unlink(self) -> None:
        self._segment.unlink()
