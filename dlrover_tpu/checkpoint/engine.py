"""Trainer-side checkpoint engine for jax pytrees.

Reference: ``CheckpointEngine`` (``flash_checkpoint/engine.py:154``) — the
in-training-process half: ``save_to_memory`` (blocking sub-second),
``save_to_storage`` (hand off to the agent saver), ``load`` (memory first,
storage fallback). One engine covers DDP/FSDP/TP cases uniformly because
the shard topology is derived from each leaf's jax sharding rather than
from a framework-specific engine subclass (reference needed
full/fsdp/megatron engines; SURVEY.md §2.4).
"""

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..chaos import faults
from ..common.constants import NodeEnv
from ..common.log import logger
from ..common.multi_process import LocalSocketClient, SharedLock, SharedQueue
from ..common.events import TrainerEvents
from .saver import (
    EVENT_QUEUE,
    FACTORY_QUEUE,
    AsyncCheckpointSaver,
    CheckpointEvent,
    lock_name,
)
from .shm_handler import SharedMemoryHandler
from .storage import PosixCheckpointStorage


def _restore_into_template(template: Any, arrays: Dict[str, np.ndarray]) -> Any:
    """Map {path: global np array} back onto the template pytree, placing
    each leaf with the template leaf's sharding (re-mesh happens here: the
    saved mesh may differ from the template's — device_put reshards).

    All device leaves go through ONE batched ``jax.device_put`` call: a
    per-leaf loop costs a dispatch round trip per leaf (~450 for a GPT-2
    train state), which dominated restore time in round 1
    (BENCH_r01 restore_s=21.4 for 1.5 GB ≈ 70 MB/s).
    """
    from .shm_handler import _path_str

    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves: list = [None] * len(flat)
    host_arrs, shardings, positions = [], [], []
    for i, (path, leaf) in enumerate(flat):
        key = _path_str(path)
        if key not in arrays:
            raise KeyError(f"checkpoint missing leaf {key}")
        arr = arrays[key]
        if isinstance(leaf, jax.Array):
            if str(arr.dtype) != str(leaf.dtype):
                arr = arr.astype(leaf.dtype)
            host_arrs.append(arr)
            shardings.append(leaf.sharding)
            positions.append(i)
        else:
            # Force a copy: `arr` may be a zero-copy view into shm whose
            # lifetime ends when the caller releases the shard lock.
            leaves[i] = np.array(arr, dtype=getattr(leaf, "dtype", arr.dtype))
    if host_arrs:
        placed = jax.device_put(host_arrs, shardings)
        jax.block_until_ready(placed)
        for i, p in zip(positions, placed):
            leaves[i] = p
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _process_count() -> int:
    """World size WITHOUT initializing a jax backend: the engine also
    runs inside non-JAX workers (torch family), where jax.process_count()
    would boot a hardware plugin just to answer "1" — and hang if the
    accelerator is unreachable. jax.distributed.initialize records the
    world in the distributed global state; absent that, we are single
    process by definition."""
    try:
        from jax._src import distributed

        return int(getattr(distributed.global_state, "num_processes", None) or 1)
    except Exception as e:  # noqa: BLE001 — private-module drift
        logger.debug("jax distributed state unreadable: %r", e)
        return 1


class CheckpointEngine:
    def __init__(
        self,
        checkpoint_dir: str,
        mesh=None,
        host_rank: Optional[int] = None,
        num_hosts: Optional[int] = None,
        master_client=None,
        standalone: Optional[bool] = None,
        replicate: Optional[bool] = None,
        replica_peers: Optional[Dict[int, str]] = None,
        saver_timeout_s: Optional[float] = None,
        prefetch_restore: Optional[bool] = None,
        durable_dir: Optional[str] = None,
        durable_lineage: Optional[str] = None,
    ):
        self.checkpoint_dir = checkpoint_dir
        self.mesh = mesh
        # Durable tier (checkpoint/durable/): None → Context knobs, so
        # production jobs configure via DLROVER_DURABLE_* while tests
        # and warm-pool callers pass explicit values.
        if durable_dir is None or durable_lineage is None:
            from ..common.config import get_context

            _ctx = get_context()
            if durable_dir is None:
                durable_dir = _ctx.durable_dir
            if durable_lineage is None:
                durable_lineage = _ctx.durable_lineage
        self.durable_dir = durable_dir or ""
        self.durable_lineage = (
            durable_lineage
            or os.environ.get("DLROVER_JOB_NAME", "")
            or "default"
        )
        self.host_rank = (
            host_rank
            if host_rank is not None
            else int(os.getenv(NodeEnv.PROCESS_ID, "0"))
        )
        self.num_hosts = (
            num_hosts
            if num_hosts is not None
            else int(os.getenv(NodeEnv.NUM_PROCESSES, "1"))
        )
        self.master_client = master_client
        self.storage = PosixCheckpointStorage(checkpoint_dir)
        self.shm = SharedMemoryHandler(self.host_rank)
        self._events = TrainerEvents()
        self._latest_storage_step = -1
        # Peer-memory replication (reference replica.py): on by default
        # for multi-host jobs; each memory save is mirrored into a backup
        # host's memory by the agent saver, and load() can recover this
        # host's shard from a peer when the node was replaced.
        self._replicate = (
            replicate if replicate is not None else self.num_hosts > 1
        )
        self._replica_peers = replica_peers

        # How long to wait for the saver's shard-lock server before
        # declaring its IPC wedged (chaos tests shorten this; the
        # default matches the old hard-coded 30 s).
        self._saver_timeout_s = (
            saver_timeout_s
            if saver_timeout_s is not None
            else float(os.getenv("DLROVER_CKPT_SAVER_TIMEOUT_S", "30"))
        )
        if standalone is None:
            standalone = not LocalSocketClient("queue_" + FACTORY_QUEUE).available()
        self._standalone = standalone
        if standalone:
            # No agent supervising us (reference start_saver_process
            # fallback, engine.py:118): run the saver in-process.
            self._saver_thread = AsyncCheckpointSaver.start_async_saving_ckpt()
        # A persist-error marker surviving from a PREVIOUS incarnation is
        # stale history (e.g. disk-full fixed, job resumed at a lower
        # step): left in place it would fail-fast every wait_saving of
        # the new run whose steps sit below the old failed step.
        self.storage.clear_persist_error(self.host_rank)
        self._factory_q = SharedQueue(FACTORY_QUEUE)
        self._event_q = SharedQueue(EVENT_QUEUE)
        self._factory_q.put(self._factory_msg())
        try:
            self._shard_lock = self._wait_lock(self._saver_timeout_s)
        except TimeoutError:
            if self._standalone:
                raise  # our own in-process saver failed: nothing to fall to
            self._fallback_standalone_saver()
        # Async staging (save_to_memory(block=False)): the trainer's
        # blocking cost is one device-side snapshot dispatch; a
        # background thread does the D2H + shm memcpy and releases the
        # shard lock when done.
        self._stage_thread: Optional[threading.Thread] = None
        self._stage_error: Optional[BaseException] = None
        self._snap_fn = None
        # Async staging needs ~+1x the state's bytes of free HBM for
        # the snapshot window. If the device can't afford it, the first
        # attempt fails RESOURCE_EXHAUSTED and all later block=False
        # saves transparently degrade to the blocking path.
        self._async_disabled = False
        # Overlapped restore (warm-restart fast path, docs/recovery.md):
        # the host-side half of the restore — shm attach + copy-out, or
        # the peer replica fetch when this host's shm is empty
        # (replica-first ordering for a replaced node) — starts NOW, in
        # the background, so it overlaps whatever runs between engine
        # construction and load()/load_consistent() (model build, train
        # step compile, the restore-source agreement's allgather). The
        # restore call then pays only the fused host→device put.
        self._prefetched: Optional[Tuple[Any, Dict[str, np.ndarray]]] = None
        self._prefetch_thread: Optional[threading.Thread] = None
        self._prefetch_invalid = False
        self.prefetch_used = False  # last restore consumed the prefetch
        if prefetch_restore is None:
            from ..common.config import get_context

            prefetch_restore = get_context().ckpt_prefetch_restore
        if prefetch_restore:
            self._prefetch_thread = threading.Thread(
                target=self._prefetch_restore_host,
                name="ckpt-restore-prefetch",
                daemon=True,
            )
            self._prefetch_thread.start()

    def _factory_msg(self) -> Dict:
        return {
            "type": "create",
            "storage_root": self.checkpoint_dir,
            "host_rank": self.host_rank,
            "num_hosts": self.num_hosts,
            "replicate": self._replicate,
            "replica_peers": self._replica_peers,
            "durable_dir": self.durable_dir,
            "durable_lineage": self.durable_lineage,
        }

    def _wait_lock(self, timeout: float = 30.0) -> SharedLock:
        deadline = time.time() + timeout
        lock = SharedLock(lock_name(self.host_rank))
        while not lock._client.available():
            if time.time() > deadline:
                raise TimeoutError("checkpoint saver did not come up")
            time.sleep(0.05)
        return lock

    def _fallback_standalone_saver(self) -> None:
        """The agent saver's IPC is wedged: its factory socket accepted
        our create message (``available()`` said yes) but the shard-lock
        server never came up within ``saver_timeout_s``. Checkpointing
        must not die with it — re-point this process at a FRESH private
        IPC namespace and run an in-process saver there. The wedged
        namespace's sockets/shm are left to the wedged owner; staging
        restarts clean in the fallback namespace (memory restore of the
        old incarnation's image is sacrificed — storage history, which
        the fallback saver keeps writing, is not)."""
        from ..common.multi_process import _ipc_namespace

        old_ns = _ipc_namespace()
        fresh_ns = f"{old_ns}_fb{os.getpid()}"
        logger.error(
            "checkpoint saver IPC wedged (no shard lock within %.0fs); "
            "falling back to a standalone saver in fresh namespace %s",
            self._saver_timeout_s,
            fresh_ns,
        )
        for res in (self._factory_q, self._event_q):
            try:
                res.close()
            except Exception as e:  # noqa: BLE001 — old namespace, best effort
                logger.debug("closing old-namespace IPC resource: %r", e)
        self.shm.close()
        os.environ["DLROVER_IPC_NAMESPACE"] = fresh_ns
        self.shm = SharedMemoryHandler(self.host_rank)
        self._standalone = True
        self._saver_thread = AsyncCheckpointSaver.start_async_saving_ckpt()
        self._factory_q = SharedQueue(FACTORY_QUEUE)
        self._event_q = SharedQueue(EVENT_QUEUE)
        self._factory_q.put(self._factory_msg())
        self._shard_lock = self._wait_lock(self._saver_timeout_s)

    # -- overlapped restore ------------------------------------------------

    def _read_staged_host(
        self, timeout: float = 60.0
    ) -> Optional[Tuple[Any, Dict[str, np.ndarray]]]:
        """(meta, arrays) copied out of shm under the shard lock, or
        None when there is no readable image."""
        if not self._shard_lock.acquire(blocking=True, timeout=timeout):
            return None
        try:
            if not self.shm.attach():
                return None
            return self.shm.load_pytree_host(copy=True)
        finally:
            self._shard_lock.release()

    def _prefetch_restore_host(self) -> None:
        """Background half of the overlapped restore: read this host's
        staged image out of shm — or, when shm is empty, pull the
        replica of this host's shard from its backup peer FIRST (the
        replaced-node case, where the peer fetch is the expensive part)
        — so the foreground restore call finds the host bytes ready."""
        try:
            got = self._read_staged_host(timeout=30.0)
            # A save (or close) sets _prefetch_invalid to CANCEL this
            # thread: never start the peer fetch afterwards — a late
            # refill would overwrite shm with a replica OLDER than the
            # step the save is about to stage.
            if (
                got is None
                and not self._prefetch_invalid
                and self._replicate
                and self._refill_from_peer()
                and not self._prefetch_invalid
            ):
                got = self._read_staged_host(timeout=30.0)
            self._prefetched = got
        except Exception as e:  # noqa: BLE001 — an optimization only
            logger.warning("restore prefetch failed: %s", e)

    def _restore_from_prefetch(
        self, template: Any, pre: Optional[Tuple[Any, Dict[str, np.ndarray]]]
    ) -> Optional[Tuple[int, Any]]:
        """Place a consumed prefetch onto the device — the one restore
        path shared by load() and load_consistent(). None when there is
        no prefetch or the image does not fit ``template`` (callers
        fall through to the locked re-read)."""
        if pre is None:
            return None
        meta, arrays = pre
        try:
            restored = _restore_into_template(template, arrays)
        except (KeyError, ValueError) as e:
            logger.warning("prefetched image unusable (%s); re-reading", e)
            return None
        self.prefetch_used = True
        logger.info("restored step %s from prefetched host read", meta.step)
        return meta.step, restored

    def _consume_prefetch(
        self,
    ) -> Optional[Tuple[Any, Dict[str, np.ndarray]]]:
        """Join the prefetch and hand over its result — None when it is
        disabled, still running, empty, or invalidated by a save that
        restaged the segment after the prefetch read it."""
        t = self._prefetch_thread
        if t is not None:
            t.join(60.0)
            if t.is_alive():
                logger.warning(
                    "restore prefetch still running; ignoring its result"
                )
                self._prefetch_invalid = True
            self._prefetch_thread = None
        got, self._prefetched = self._prefetched, None
        if self._prefetch_invalid or got is None:
            return None
        return got

    # -- save --------------------------------------------------------------

    def _all_hosts_ready(self, ready: bool) -> bool:
        """All-or-none gate for a multi-process save (reference
        ``check_all_rank_ready`` allreduce, engine.py:57-71): if ANY
        host's persister holds its shard lock, every host skips this
        step. Without it hosts stage DIFFERENT steps over time and a
        re-meshed world has no common memory step to resume from."""
        if _process_count() <= 1:
            return ready
        from jax.experimental import multihost_utils

        all_ready = multihost_utils.process_allgather(
            np.int64(1 if ready else 0)
        )
        return bool(np.all(all_ready))

    def save_to_memory(
        self,
        step: int,
        pytree: Any,
        extra: Optional[Dict] = None,
        block: bool = True,
        for_storage: bool = False,
    ) -> bool:
        """Stage the pytree into host shm. Skips (returns False) if ANY
        host's persister still holds its shard lock (reference
        non-blocking acquire + all-rank-ready allreduce,
        engine.py:57-71,351-365) — all-or-none, so every host's shm
        always stages the SAME step.

        ``block=True`` blocks for D2H + memcpy (sub-second at HBM/shm
        bandwidth). ``block=False`` blocks only to DISPATCH a
        device-side snapshot (an HBM-bandwidth copy this engine owns —
        NOTE: the snapshot holds ~+1x the state's bytes in HBM until
        staging drains; a device without that headroom OOMs the first
        attempt, which permanently degrades block=False to the blocking
        path for this engine):
        the train step donates its state buffers
        (``train_step.py:donate``), so staging must not read them after
        the trainer's next dispatch — ``copy_to_host_async`` alone does
        NOT survive donation (the array is marked deleted). A background
        thread then streams the snapshot to host shm and releases the
        shard lock; the lock serializes it against the persister and
        cross-process readers. The next save from THIS engine must be
        guarded separately — the shard lock is reentrant per owner
        (same pid+object), so an in-flight staging thread would not
        block a sibling acquire — hence the explicit thread-alive skip,
        folded into the all-hosts allreduce so every host skips the
        same step together.
        """
        # Chaos hook: a delay here stretches the trainer's blocking
        # window; an error must surface to the loop (which re-saves
        # blocking or skips the step), never wedge the shard lock.
        faults.inject("ckpt.engine.save", step=step)
        # Any save supersedes the restore prefetch: a later consume of
        # the pre-save image would silently restore an older step.
        # Invalid FIRST — it doubles as the cancel signal, so a thread
        # that has not yet started its peer fetch skips it instead of
        # stalling this save (a saving host's state is newer than any
        # replica of it). Then wait the remainder out: the prefetch
        # briefly holds the shard lock and the non-blocking acquire
        # below must not misread the init-time read as "persister busy"
        # and skip the step.
        self._prefetch_invalid = True
        self._prefetched = None
        pt = self._prefetch_thread
        if pt is not None and pt.is_alive():
            pt.join(30.0)
        staging = self._stage_thread is not None and self._stage_thread.is_alive()
        if staging:
            logger.warning(
                "step %s: previous async stage still in flight", step
            )
        acquired = (not staging) and self._shard_lock.acquire(blocking=False)
        try:
            ready = self._all_hosts_ready(acquired)
        except Exception:
            # a peer died mid-allgather: surface it, but NEVER while
            # holding the shard lock — a leaked lock starves the agent
            # persister forever
            if acquired:
                self._shard_lock.release()
            raise
        if not ready:
            if acquired:
                self._shard_lock.release()
            logger.warning(
                "skip save_to_memory step %s: a persister is busy", step
            )
            return False
        if not block and self._async_disabled:
            block = True  # degraded: no HBM headroom for snapshots
        if not block:
            try:
                snapshot = self._snapshot(pytree)
                t = threading.Thread(
                    target=self._stage_async,
                    args=(step, snapshot, extra, for_storage),
                    name=f"ckpt-stage-{step}",
                    daemon=True,
                )
                t.start()
                # Assigned only AFTER start(): join() on a never-started
                # thread raises, which would break every later
                # wait_staged/close if start() itself failed.
                self._stage_thread = t
                return True
            except Exception as e:
                msg = repr(e).lower()
                if "resource_exhausted" in msg or "out of memory" in msg:
                    # No HBM headroom for the snapshot: degrade THIS and
                    # all later saves to the blocking path (we still
                    # hold the shard lock — fall through below).
                    self._async_disabled = True
                    logger.error(
                        "snapshot OOM at step %s; degrading to blocking "
                        "saves", step
                    )
                else:
                    self._shard_lock.release()
                    raise
        try:
            with self._events.ckpt_save(step, storage="memory"):
                self.shm.save_pytree(
                    step,
                    pytree,
                    num_hosts=self.num_hosts,
                    mesh=self.mesh,
                    extra=extra,
                )
            # A successful blocking save supersedes any stale async
            # failure: without this, a degraded (async-disabled) engine
            # would keep failing wait_staged_all and force redundant
            # re-saves of steps that already landed.
            self._stage_error = None
        finally:
            self._shard_lock.release()
        if self._replicate:
            # Mirror to the backup peer — handled by the agent saver so
            # the trainer never blocks on a DCN transfer.
            self._event_q.put({"type": CheckpointEvent.REPLICATE, "step": step})
        return True

    def _snapshot(self, pytree: Any) -> Any:
        """Device-side copy of every jax leaf in ONE jitted dispatch
        (fresh buffers — ``jnp.copy`` lowers to an explicit copy that
        cannot alias its input), host leaves copied on host. The result
        is immune to the caller donating/overwriting the originals."""
        import jax.numpy as jnp

        flat, treedef = jax.tree_util.tree_flatten(pytree)
        is_dev = [isinstance(leaf, jax.Array) for leaf in flat]
        dev_leaves = [l for l, d in zip(flat, is_dev) if d]
        if dev_leaves:
            if self._snap_fn is None:
                self._snap_fn = jax.jit(
                    lambda leaves: [jnp.copy(l) for l in leaves]
                )
            dev_copies = iter(self._snap_fn(dev_leaves))
        else:
            dev_copies = iter(())
        out = [
            next(dev_copies)
            if d
            else (np.array(l, copy=True) if isinstance(l, np.ndarray) else l)
            for l, d in zip(flat, is_dev)
        ]
        return jax.tree_util.tree_unflatten(treedef, out)

    def _stage_async(self, step: int, snapshot: Any, extra, for_storage: bool) -> None:
        """Background half of save_to_memory(block=False). Owns the
        already-acquired shard lock; ALWAYS releases it. ``_stage_error``
        is sticky across saves until a stage SUCCEEDS (or wait_staged
        consumes it): the loop's boundary checks turn it into a blocking
        re-save, where the silent alternative loses the step."""
        ok = False
        try:
            with self._events.ckpt_save(step, storage="memory"):
                self.shm.save_pytree(
                    step,
                    snapshot,
                    num_hosts=self.num_hosts,
                    mesh=self.mesh,
                    extra=extra,
                )
            ok = True
            self._stage_error = None
        except BaseException as e:  # noqa: BLE001 — recorded, surfaced by wait_staged
            self._stage_error = e
            logger.error("async checkpoint staging failed at step %s: %s", step, e)
            msg = repr(e).lower()
            if "resource_exhausted" in msg or "out of memory" in msg:
                self._async_disabled = True
                logger.error(
                    "no HBM headroom for snapshot staging; later saves "
                    "fall back to blocking D2H"
                )
            if for_storage:
                # The SAVE event is already queued; the persister will
                # find an absent image and skip. Leave a persist-error
                # marker so wait_saving fails FAST instead of burning
                # its whole timeout on a step that will never commit.
                try:
                    self.storage.record_persist_error(
                        self.host_rank, step, f"async stage failed: {e!r}"
                    )
                except Exception as rec_err:  # noqa: BLE001
                    logger.warning(
                        "could not record persist error for step %s: %r",
                        step,
                        rec_err,
                    )
        finally:
            self._shard_lock.release()
        if ok and self._replicate:
            self._event_q.put({"type": CheckpointEvent.REPLICATE, "step": step})

    def wait_staged_all(self, timeout: float = 300.0) -> bool:
        """Collective wait_staged: ANDs every host's local outcome via
        the same allgather as ``_all_hosts_ready``. The train loop gates
        COLLECTIVE decisions (blocking re-save before a re-mesh, final
        re-save) on the staging verdict — a per-host verdict would send
        hosts down different code paths and wedge the world's collective
        sequence (one host in save_to_memory's allgather, another in
        remesh). Call points must themselves be collective-aligned."""
        ok = self.wait_staged(timeout)
        if _process_count() <= 1:
            return ok
        from jax.experimental import multihost_utils

        all_ok = multihost_utils.process_allgather(np.int64(1 if ok else 0))
        return bool(np.all(all_ok))

    def _drain_stage_for_read(self) -> None:
        """Gate every restore path on the staging thread being DEAD —
        not merely timed out. A wedged stage thread still writes through
        the reentrant shard lock; proceeding would let a second writer
        (peer refill) interleave on the same segment, which the
        header-last protocol cannot protect against. A dead thread with
        a recorded failure is fine: the zeroed/absent header parses as
        no-image and load falls through to peer/storage."""
        t = self._stage_thread
        if t is not None and t.is_alive():
            t.join(300.0)
            if t.is_alive():
                raise RuntimeError(
                    "async checkpoint staging is wedged (>300s); refusing "
                    "to restore over a live writer on the shm segment"
                )
        self.wait_staged(timeout=0.1)

    def wait_staged(self, timeout: float = 300.0) -> bool:
        """Join the outstanding async staging, if any. Returns False if
        it failed or is still running at the deadline. A recorded
        failure is CONSUMED here: the caller reacts (the loop re-saves
        blocking), so a later wait must not keep reporting it."""
        t = self._stage_thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                return False
            self._stage_thread = None
        err, self._stage_error = self._stage_error, None
        return err is None

    def save_to_storage(
        self,
        step: int,
        pytree: Any,
        extra: Optional[Dict] = None,
        block: bool = True,
    ) -> bool:
        """Stage to memory, then hand persistence to the agent saver.
        With ``block=False`` the SAVE event is enqueued while staging
        still runs — safe because the persister must take the shard
        lock, which the staging thread holds until the image is
        complete."""
        if not self.save_to_memory(
            step, pytree, extra, block=block, for_storage=True
        ):
            return False
        self._event_q.put({"type": CheckpointEvent.SAVE, "step": step})
        self._latest_storage_step = step
        return True

    def wait_saving(self, timeout: float = 300.0) -> bool:
        """Block until the queued *storage* saves are persisted (tracker
        catches up). Memory-only saves don't gate this — they have no
        pending disk work.

        Fails fast (no full-timeout stall) when the saver reported a
        persist error for this shard or its event-queue server vanished
        (saver process crashed)."""
        if self._latest_storage_step < 0:
            return True
        deadline = time.time() + timeout
        while time.time() < deadline:
            latest = self.storage.latest_step()
            # NOT `latest or -1`: a committed step 0 is falsy and the
            # idiom would spin out the whole timeout on the first save.
            if latest is not None and latest >= self._latest_storage_step:
                return True
            err = self.storage.persist_error(self.host_rank)
            if err is not None and err[0] >= self._latest_storage_step:
                # Markers from OLDER steps are stale history — a newer
                # save is in flight and may well succeed.
                logger.error(
                    "saver reported persist failure at step %s: %s",
                    err[0],
                    err[1],
                )
                return False
            if not self._event_q.available():
                # Re-check the tracker once: the saver may have committed
                # and exited between our two probes.
                latest = self.storage.latest_step()
                if latest is not None and latest >= self._latest_storage_step:
                    return True
                logger.error(
                    "checkpoint saver is gone (event queue unreachable); "
                    "step %s will not be persisted",
                    self._latest_storage_step,
                )
                return False
            time.sleep(0.1)
        return False

    # -- load --------------------------------------------------------------

    def load(self, template: Any) -> Tuple[int, Optional[Any]]:
        """Restore into ``template``'s structure/shardings: own host
        memory first, then a peer's replica of this host's shard
        (node-replacement recovery without touching storage — reference
        engine.py:375,392-409), then storage.

        Returns (step, restored_pytree) or (-1, None) if nothing to load.
        """
        faults.inject("ckpt.engine.load", host_rank=self.host_rank)
        # Drain any in-flight async stage first: the shard lock is
        # reentrant for this engine, so _load_from_memory would NOT
        # block on the staging thread and could read a half-written
        # image.
        self._drain_stage_for_read()
        with self._events.ckpt_load():
            pre = self._consume_prefetch()
            result = self._restore_from_prefetch(template, pre)
            if result is not None:
                return result
            result = self._load_from_memory(template)
            if result is not None:
                return result
            result = self._load_from_peer(template)
            if result is not None:
                return result
            result = self._load_from_storage(template)
            if result is not None:
                return result
            result = self._load_from_durable(template)
            if result is not None:
                return result
        return -1, None

    def load_resharded(
        self, mesh, step: Optional[int] = None
    ) -> Tuple[int, Optional[Dict[str, Any]], Dict[str, Any]]:
        """Templateless restore of the staged flash image under ``mesh``
        — the in-memory rung transition of the elastic replanner
        (docs/elastic_parallelism.md).

        Unlike :meth:`load`, there is no template state to borrow
        shardings from: the OLD world's programs are gone (the new rung
        has different mesh extents), so each leaf's target sharding is
        derived from its RESHARD_RULES category + the spec stamped into
        the shm image at save time — the same
        ``place_arrays_with_rules`` engine the durable tier's
        reshard-on-read restore drives. Returns ``(step, {leaf path:
        placed array}, extra)`` or ``(-1, None, {})`` when shm holds no
        image (or ``step`` was given and the image is a different
        step — the caller wants THIS step's state, not whatever is
        lying around).
        """
        from ..parallel.sharding import place_arrays_with_rules

        faults.inject("ckpt.engine.load", host_rank=self.host_rank)
        self._drain_stage_for_read()
        with self._events.ckpt_load():
            got = self._read_staged_host()
            if got is None:
                return -1, None, {}
            meta, arrays = got
            if step is not None and meta.step != step:
                logger.warning(
                    "staged image holds step %s, wanted %s; not resharding",
                    meta.step,
                    step,
                )
                return -1, None, {}
            saved_specs = {rec.path: rec.spec for rec in meta.records}
            placed = place_arrays_with_rules(saved_specs, arrays, mesh)
        logger.info(
            "resharded step %s from host memory onto mesh %s (%s leaves)",
            meta.step,
            dict(getattr(mesh, "shape", {})),
            len(placed),
        )
        return meta.step, placed, dict(meta.extra)

    def _refill_from_peer(self) -> bool:
        """Pull this host's replicated shard from its backup peer into
        local shm (control-plane transfer only — NO device collectives,
        so it is safe before a multi-process restore agreement). True
        when shm now holds a usable image."""
        if not self._replicate:
            return False
        from .replica import ReplicaManager, default_master_client

        client = self.master_client
        if client is None and self._replica_peers is None:
            client = default_master_client()
            if client is None:
                return False
        manager = ReplicaManager(
            self.host_rank,
            self.num_hosts,
            master_client=client,
            peers=self._replica_peers,
        )
        if not self._shard_lock.acquire(blocking=True, timeout=60.0):
            manager.stop()
            return False
        try:
            # Staleness check BEFORE the expensive host->device restore:
            # a replica can lag behind storage (push failures are
            # log-and-drop), and restoring a multi-GB pytree only to
            # throw it away wastes minutes on the recovery path.
            return manager.refill_shm(self.shm, self.storage) == "refilled"
        finally:
            self._shard_lock.release()
            manager.stop()

    def _load_from_peer(self, template: Any):
        """Refill this host's shm from the peer that replicated it, then
        load through the normal memory path."""
        if not self._refill_from_peer():
            return None
        return self._load_from_memory(template)

    def _load_from_memory(self, template: Any):
        # Everything happens under the shard lock: the persister (or a
        # dying trainer's last save) may be mid-write. The load COPIES
        # out of the segment (copy=True): zero-copy views were tried and
        # leak — on the CPU backend jax.device_put aliases the host
        # buffer, so a view into the mmap outlives the lock scope and
        # the segment can never be closed (BufferError: cannot close
        # exported pointers exist). One memcpy at memory bandwidth is
        # cheap next to the device transfer it feeds.
        if not self._shard_lock.acquire(blocking=True, timeout=60.0):
            logger.warning("shard lock busy; skipping memory restore")
            return None
        try:
            if not self.shm.attach():
                return None
            got = self.shm.load_pytree_host(copy=True)
            if got is None:
                return None
            meta, arrays = got
            try:
                restored = _restore_into_template(template, arrays)
            except (KeyError, ValueError) as e:
                logger.warning(
                    "memory checkpoint unusable (%s); trying storage", e
                )
                return None
        finally:
            self._shard_lock.release()
        logger.info("restored step %s from host memory", meta.step)
        return meta.step, restored

    def _load_from_storage(self, template: Any, step: Optional[int] = None):
        if step is None:
            step = self.storage.latest_step()
        if step is None:
            return None
        arrays = self.storage.load_step_host(step)
        if arrays is None:
            return None
        try:
            restored = _restore_into_template(template, arrays)
        except (KeyError, ValueError) as e:
            logger.warning(
                "storage checkpoint step %s unusable (%s); starting fresh",
                step,
                e,
            )
            return None
        logger.info("restored step %s from storage %s", step, self.checkpoint_dir)
        return step, restored

    def _load_from_durable(self, template: Any, step: Optional[int] = None):
        """Last rung of the restore chain: the durable tier
        (``checkpoint/durable/``). The generation may have been written
        by a DIFFERENT world — world size and axis layout both — so
        this is a reshard-on-read: saved specs are validated against
        RESHARD_RULES, the global arrays are assembled from all saved
        shards, and the template's current-mesh shardings place them."""
        if not self.durable_dir:
            return None
        try:
            from ..parallel.sharding import validate_saved_spec
            from .durable.restore import read_generation

            got_step, manifest, arrays, _extra = read_generation(
                self.durable_dir,
                self.durable_lineage,
                step=step,
                host_rank=self.host_rank,
            )
            if got_step is None or manifest is None:
                return None
            for cat, specs in manifest.category_specs.items():
                for _path, saved_spec in specs.items():
                    validate_saved_spec(cat, saved_spec)
            restored = _restore_into_template(template, arrays)
        except Exception as e:  # noqa: BLE001 — last rung: a torn durable tier degrades to a fresh start, never a crash
            logger.warning("durable restore failed (%s); starting fresh", e)
            return None
        logger.info(
            "restored step %s from durable tier %s/%s "
            "(saved world %s, mesh %sx%s -> current mesh)",
            got_step,
            self.durable_dir,
            self.durable_lineage,
            manifest.num_hosts,
            manifest.mesh_axes,
            manifest.mesh_shape,
        )
        return got_step, restored

    # Floor for how many of each host's newest committed steps enter the
    # cross-host agreement; the effective count always exceeds the
    # configured ckpt_keep_latest (see _restore_candidate_steps) so
    # pruning can't hide a still-on-disk common step from the
    # intersection.
    RESTORE_CANDIDATE_STEPS = 8

    def _restore_candidate_steps(self) -> int:
        # Job config is uniform across hosts, so every host computes the
        # same K — required: the allgather row length depends on it.
        from ..common.config import get_context

        return max(self.RESTORE_CANDIDATE_STEPS, get_context().ckpt_keep_latest + 2)

    def _gather_restore_meta(
        self, mem_step: int, tracker_step: int, committed: List[int]
    ) -> Tuple[List[int], List[int], List[set]]:
        """Every host's (staged shm step, storage tracker step, committed
        step set) — host-only metadata, gathered before any collective
        restore. The committed set (top-K of ``storage.list_steps()``)
        rather than just the tracker: with per-host storage roots plus
        ``ckpt_keep_latest`` pruning, a host may have already deleted
        another host's tracker step while a common older step still
        exists on every host."""
        K = self._restore_candidate_steps()
        own = sorted(committed)[-K:]
        if _process_count() <= 1:
            return [mem_step], [tracker_step], [set(own)]
        from jax.experimental import multihost_utils

        row = np.full(2 + K, -1, np.int64)
        row[0], row[1] = mem_step, tracker_step
        row[2 : 2 + len(own)] = own
        gathered = multihost_utils.process_allgather(row)
        return (
            [int(v) for v in gathered[:, 0]],
            [int(v) for v in gathered[:, 1]],
            [
                {int(s) for s in host_row[2:] if s >= 0}
                for host_row in gathered
            ],
        )

    def load_consistent(self, template: Any) -> Tuple[int, Optional[Any]]:
        """``load`` + cross-host consistency (reference
        ``verify_all_rank_step_consistent`` allgather,
        flash_checkpoint/engine.py:74-95).

        ``load`` is per-host (own shm → peer → storage), so after a node
        replacement hosts can legally restore DIFFERENT steps — and a
        step-count fix alone would train a model whose shards mix two
        checkpoints.

        On a MULTI-PROCESS world the restore itself is collective: when
        the template leaves live on a global (multi-process) mesh, each
        ``device_put`` participates in cross-host transfers, so hosts
        must agree on the restore SOURCE before moving a single byte —
        a host restoring from memory while another reads storage would
        interleave mismatched collectives and deadlock/abort the world.
        The agreement therefore happens on cheap host-only metadata
        (shm meta step, storage tracker) gathered FIRST; then every
        host executes the SAME restore path:

        Drains any in-flight async stage up front (same reentrancy
        hazard as ``load``).

        - all hosts stage the same memory step → memory restore
          everywhere;
        - otherwise the NEWEST step committed on EVERY host (max of the
          intersection of per-host committed sets, capped at the newest
          tracker so a stale high-numbered step left in a reused root
          can't shadow the live history);
        - no common storage step → everyone starts fresh, consistently.
        """
        faults.inject("ckpt.engine.load", host_rank=self.host_rank)
        self._drain_stage_for_read()
        # Prefetched host read first: it already did shm attach (and
        # the peer refill for a replaced node) in the background, so
        # the agreement below runs on bytes that are ALREADY host-side.
        pre = self._consume_prefetch()
        if pre is not None:
            meta = pre[0]
        else:
            meta = self.shm.read_meta() if self.shm.attach() else None
            if meta is None and self._refill_from_peer():
                meta = self.shm.read_meta()
        mem_step = -1 if meta is None else meta.step
        storage_latest = self.storage.latest_step()
        st_step = -1 if storage_latest is None else storage_latest
        mem_steps, st_steps, committed_sets = self._gather_restore_meta(
            mem_step, st_step, self.storage.list_steps()
        )
        if mem_steps[0] >= 0 and len(set(mem_steps)) == 1:
            # only a prefetch of the AGREED step may serve the restore;
            # on an unusable image, fall through to the locked re-read —
            # the multi-process unreadable case is handled below exactly
            # as without prefetch
            if pre is not None and pre[0].step == mem_steps[0]:
                result = self._restore_from_prefetch(template, pre)
                if result is not None:
                    return result
            result = self._load_from_memory(template)
            if result is not None:
                return result
            if _process_count() > 1:
                # our shm image turned out unreadable AFTER agreement —
                # the other hosts are already inside the memory
                # restore's collectives; no safe divergence from here.
                raise RuntimeError(
                    f"agreed memory step {mem_steps[0]} unreadable "
                    "locally; restart the worker to re-rendezvous"
                )
            # single process: nothing collective at risk — storage next
        common = set.intersection(*committed_sets) if committed_sets else set()
        cap = max(st_steps)
        candidates = {s for s in common if cap < 0 or s <= cap}
        target = max(candidates) if candidates else -1
        if len(set(mem_steps)) != 1 or mem_steps[0] < 0:
            logger.info(
                "staged steps %s not uniformly restorable (trackers %s, "
                "common committed %s); restoring step %s",
                mem_steps,
                st_steps,
                sorted(common),
                target,
            )
        if target < 0:
            # Whole-pool loss: no usable shm image, peer replica, or
            # flash storage step anywhere — the durable tier is what's
            # left, under the same agree-then-restore discipline.
            return self._load_consistent_durable(template)
        return target, self._reload(template, target)

    def _durable_latest(self) -> int:
        """This host's view of the newest committed durable generation
        (-1 when the tier is off, empty, or unreachable)."""
        if not self.durable_dir:
            return -1
        try:
            from .durable.layout import DurableLayout

            latest = DurableLayout(
                self.durable_dir, self.durable_lineage
            ).latest_committed()
        except Exception as e:  # noqa: BLE001 — probe only; absence of the tier is not an error
            logger.warning("durable tier probe failed: %r", e)
            return -1
        return -1 if latest is None else latest

    def _load_consistent_durable(
        self, template: Any
    ) -> Tuple[int, Optional[Any]]:
        """Cross-host agreement for the durable rung, mirroring the
        flash rungs: gather each host's newest committed generation
        first (host-only metadata), then every host runs the SAME
        collective restore. The target is the min over hosts — the
        newest generation visible on EVERY host, robust to a shared
        filesystem propagating the newest commit unevenly."""
        own = self._durable_latest()
        if _process_count() <= 1:
            steps = [own]
        else:
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(
                np.asarray([own], np.int64)
            )
            steps = [int(v) for v in gathered[:, 0]]
        if any(s < 0 for s in steps):
            if own >= 0:
                logger.info(
                    "durable gen_%s visible locally but not on every "
                    "host (%s); starting fresh",
                    own,
                    steps,
                )
            return -1, None
        target = min(steps)
        result = self._load_from_durable(template, step=target)
        if result is None:
            if _process_count() > 1:
                raise RuntimeError(
                    f"agreed durable generation {target} unreadable "
                    "locally; restart the worker to re-rendezvous"
                )
            return -1, None
        return result

    def _reload(self, template: Any, step: int):
        result = self._load_from_storage(template, step=step)
        if result is None:
            raise RuntimeError(
                f"agreed checkpoint step {step} unreadable from storage"
            )
        return result[1]

    # -- shard topology (reference get_local/global_shard_num) -------------

    def get_local_shard_num(self) -> int:
        return 1  # one staged shard per host

    def get_global_shard_num(self) -> int:
        return self.num_hosts

    def close(self) -> None:
        """Release IPC clients and the shm mapping; in standalone mode
        also tear down the in-process saver (thread + servers), so a
        re-meshed world can build a fresh engine without leaking one
        saver stack per topology round."""
        self._prefetch_invalid = True  # cancel: skip a not-yet-started fetch
        pt = self._prefetch_thread
        if pt is not None and pt.is_alive():
            pt.join(30.0)
        self._prefetch_thread = None
        self._prefetched = None
        t = self._stage_thread
        if t is not None and t.is_alive():
            t.join(60.0)
            if t.is_alive():
                # A wedged staging thread still writes through self.shm
                # and releases through self._shard_lock: closing them
                # under it trades a leak for corruption (and the lock
                # server's death-of-holder handling will free the lock
                # when this process exits anyway). Leak loudly instead.
                logger.error(
                    "async stage still running after 60s; leaving shm/"
                    "lock open (leaked until process exit)"
                )
                return
        self.wait_staged(timeout=0.1)
        for res in (self._event_q, self._factory_q, self._shard_lock, self.shm):
            try:
                res.close()
            except Exception as e:  # noqa: BLE001 — teardown, best effort
                logger.debug("engine close: %r", e)
        if self._standalone:
            AsyncCheckpointSaver.shutdown()
