"""Trainer-side checkpoint engine for jax pytrees.

Reference: ``CheckpointEngine`` (``flash_checkpoint/engine.py:154``) — the
in-training-process half: ``save_to_memory`` (blocking sub-second),
``save_to_storage`` (hand off to the agent saver), ``load`` (memory first,
storage fallback). One engine covers DDP/FSDP/TP cases uniformly because
the shard topology is derived from each leaf's jax sharding rather than
from a framework-specific engine subclass (reference needed
full/fsdp/megatron engines; SURVEY.md §2.4).
"""

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..common.constants import NodeEnv
from ..common.log import logger
from ..common.multi_process import LocalSocketClient, SharedLock, SharedQueue
from ..common.events import TrainerEvents
from .saver import (
    EVENT_QUEUE,
    FACTORY_QUEUE,
    AsyncCheckpointSaver,
    CheckpointEvent,
    lock_name,
)
from .shm_handler import SharedMemoryHandler
from .storage import PosixCheckpointStorage


def _restore_into_template(template: Any, arrays: Dict[str, np.ndarray]) -> Any:
    """Map {path: global np array} back onto the template pytree, placing
    each leaf with the template leaf's sharding (re-mesh happens here: the
    saved mesh may differ from the template's — device_put reshards).

    All device leaves go through ONE batched ``jax.device_put`` call: a
    per-leaf loop costs a dispatch round trip per leaf (~450 for a GPT-2
    train state), which dominated restore time in round 1
    (BENCH_r01 restore_s=21.4 for 1.5 GB ≈ 70 MB/s).
    """
    from .shm_handler import _path_str

    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves: list = [None] * len(flat)
    host_arrs, shardings, positions = [], [], []
    for i, (path, leaf) in enumerate(flat):
        key = _path_str(path)
        if key not in arrays:
            raise KeyError(f"checkpoint missing leaf {key}")
        arr = arrays[key]
        if isinstance(leaf, jax.Array):
            if str(arr.dtype) != str(leaf.dtype):
                arr = arr.astype(leaf.dtype)
            host_arrs.append(arr)
            shardings.append(leaf.sharding)
            positions.append(i)
        else:
            # Force a copy: `arr` may be a zero-copy view into shm whose
            # lifetime ends when the caller releases the shard lock.
            leaves[i] = np.array(arr, dtype=getattr(leaf, "dtype", arr.dtype))
    if host_arrs:
        placed = jax.device_put(host_arrs, shardings)
        jax.block_until_ready(placed)
        for i, p in zip(positions, placed):
            leaves[i] = p
    return jax.tree_util.tree_unflatten(treedef, leaves)


class CheckpointEngine:
    def __init__(
        self,
        checkpoint_dir: str,
        mesh=None,
        host_rank: Optional[int] = None,
        num_hosts: Optional[int] = None,
        master_client=None,
        standalone: Optional[bool] = None,
        replicate: Optional[bool] = None,
        replica_peers: Optional[Dict[int, str]] = None,
    ):
        self.checkpoint_dir = checkpoint_dir
        self.mesh = mesh
        self.host_rank = (
            host_rank
            if host_rank is not None
            else int(os.getenv(NodeEnv.PROCESS_ID, "0"))
        )
        self.num_hosts = (
            num_hosts
            if num_hosts is not None
            else int(os.getenv(NodeEnv.NUM_PROCESSES, "1"))
        )
        self.master_client = master_client
        self.storage = PosixCheckpointStorage(checkpoint_dir)
        self.shm = SharedMemoryHandler(self.host_rank)
        self._events = TrainerEvents()
        self._latest_storage_step = -1
        # Peer-memory replication (reference replica.py): on by default
        # for multi-host jobs; each memory save is mirrored into a backup
        # host's memory by the agent saver, and load() can recover this
        # host's shard from a peer when the node was replaced.
        self._replicate = (
            replicate if replicate is not None else self.num_hosts > 1
        )
        self._replica_peers = replica_peers

        if standalone is None:
            standalone = not LocalSocketClient("queue_" + FACTORY_QUEUE).available()
        self._standalone = standalone
        if standalone:
            # No agent supervising us (reference start_saver_process
            # fallback, engine.py:118): run the saver in-process.
            self._saver_thread = AsyncCheckpointSaver.start_async_saving_ckpt()
        # A persist-error marker surviving from a PREVIOUS incarnation is
        # stale history (e.g. disk-full fixed, job resumed at a lower
        # step): left in place it would fail-fast every wait_saving of
        # the new run whose steps sit below the old failed step.
        self.storage.clear_persist_error(self.host_rank)
        self._factory_q = SharedQueue(FACTORY_QUEUE)
        self._event_q = SharedQueue(EVENT_QUEUE)
        self._factory_q.put(
            {
                "type": "create",
                "storage_root": checkpoint_dir,
                "host_rank": self.host_rank,
                "num_hosts": self.num_hosts,
                "replicate": self._replicate,
                "replica_peers": self._replica_peers,
            }
        )
        self._shard_lock = self._wait_lock()

    def _wait_lock(self, timeout: float = 30.0) -> SharedLock:
        deadline = time.time() + timeout
        lock = SharedLock(lock_name(self.host_rank))
        while not lock._client.available():
            if time.time() > deadline:
                raise TimeoutError("checkpoint saver did not come up")
            time.sleep(0.05)
        return lock

    # -- save --------------------------------------------------------------

    def save_to_memory(self, step: int, pytree: Any, extra: Optional[Dict] = None) -> bool:
        """Stage the pytree into host shm. Blocks only for D2H + memcpy.
        Skips (returns False) if the persister still holds the shard lock
        (reference non-blocking acquire, engine.py:351-365)."""
        if not self._shard_lock.acquire(blocking=False):
            logger.warning(
                "skip save_to_memory step %s: persister busy with shard", step
            )
            return False
        try:
            with self._events.ckpt_save(step, storage="memory"):
                self.shm.save_pytree(
                    step,
                    pytree,
                    num_hosts=self.num_hosts,
                    mesh=self.mesh,
                    extra=extra,
                )
        finally:
            self._shard_lock.release()
        if self._replicate:
            # Mirror to the backup peer — handled by the agent saver so
            # the trainer never blocks on a DCN transfer.
            self._event_q.put({"type": CheckpointEvent.REPLICATE, "step": step})
        return True

    def save_to_storage(self, step: int, pytree: Any, extra: Optional[Dict] = None) -> bool:
        """Stage to memory, then hand persistence to the agent saver."""
        if not self.save_to_memory(step, pytree, extra):
            return False
        self._event_q.put({"type": CheckpointEvent.SAVE, "step": step})
        self._latest_storage_step = step
        return True

    def wait_saving(self, timeout: float = 300.0) -> bool:
        """Block until the queued *storage* saves are persisted (tracker
        catches up). Memory-only saves don't gate this — they have no
        pending disk work.

        Fails fast (no full-timeout stall) when the saver reported a
        persist error for this shard or its event-queue server vanished
        (saver process crashed)."""
        if self._latest_storage_step < 0:
            return True
        deadline = time.time() + timeout
        while time.time() < deadline:
            latest = self.storage.latest_step()
            # NOT `latest or -1`: a committed step 0 is falsy and the
            # idiom would spin out the whole timeout on the first save.
            if latest is not None and latest >= self._latest_storage_step:
                return True
            err = self.storage.persist_error(self.host_rank)
            if err is not None and err[0] >= self._latest_storage_step:
                # Markers from OLDER steps are stale history — a newer
                # save is in flight and may well succeed.
                logger.error(
                    "saver reported persist failure at step %s: %s",
                    err[0],
                    err[1],
                )
                return False
            if not self._event_q.available():
                # Re-check the tracker once: the saver may have committed
                # and exited between our two probes.
                latest = self.storage.latest_step()
                if latest is not None and latest >= self._latest_storage_step:
                    return True
                logger.error(
                    "checkpoint saver is gone (event queue unreachable); "
                    "step %s will not be persisted",
                    self._latest_storage_step,
                )
                return False
            time.sleep(0.1)
        return False

    # -- load --------------------------------------------------------------

    def load(self, template: Any) -> Tuple[int, Optional[Any]]:
        """Restore into ``template``'s structure/shardings: own host
        memory first, then a peer's replica of this host's shard
        (node-replacement recovery without touching storage — reference
        engine.py:375,392-409), then storage.

        Returns (step, restored_pytree) or (-1, None) if nothing to load.
        """
        with self._events.ckpt_load():
            result = self._load_from_memory(template)
            if result is not None:
                return result
            result = self._load_from_peer(template)
            if result is not None:
                return result
            result = self._load_from_storage(template)
            if result is not None:
                return result
        return -1, None

    def _load_from_peer(self, template: Any):
        """Refill this host's shm from the peer that replicated it, then
        load through the normal memory path. A replica can be stale
        (push failures are log-and-drop), so if storage holds a NEWER
        step the peer result is discarded and load() falls through."""
        if not self._replicate:
            return None
        from .replica import ReplicaManager, default_master_client

        client = self.master_client
        if client is None and self._replica_peers is None:
            client = default_master_client()
            if client is None:
                return None
        manager = ReplicaManager(
            self.host_rank,
            self.num_hosts,
            master_client=client,
            peers=self._replica_peers,
        )
        if not self._shard_lock.acquire(blocking=True, timeout=60.0):
            return None
        try:
            fetched = manager.fetch_own_shard(self.shm.write_image_stream)
            if not fetched:
                return None
            # Staleness check BEFORE the expensive host->device restore:
            # a replica can lag behind storage (push failures are
            # log-and-drop), and restoring a multi-GB pytree only to
            # throw it away wastes minutes on the recovery path.
            meta = self.shm.read_meta()
            storage_step = self.storage.latest_step()
            storage_step = -1 if storage_step is None else storage_step
            if meta is not None and storage_step > meta.step:
                logger.info(
                    "peer replica holds step %s but storage has %s; "
                    "preferring storage",
                    meta.step,
                    storage_step,
                )
                # Drop the stale image: a later breakpoint save would
                # otherwise persist it and regress the tracker.
                self.shm.invalidate()
                return None
        finally:
            self._shard_lock.release()
            manager.stop()
        return self._load_from_memory(template)

    def _load_from_memory(self, template: Any):
        # Everything happens under the shard lock: the persister (or a
        # dying trainer's last save) may be mid-write, and the restore
        # uses zero-copy views into the segment, which must not be
        # overwritten until the device transfer completes
        # (_restore_into_template blocks on it before returning).
        if not self._shard_lock.acquire(blocking=True, timeout=60.0):
            logger.warning("shard lock busy; skipping memory restore")
            return None
        try:
            if not self.shm.attach():
                return None
            got = self.shm.load_pytree_host(copy=False)
            if got is None:
                return None
            meta, arrays = got
            try:
                restored = _restore_into_template(template, arrays)
            except (KeyError, ValueError) as e:
                logger.warning(
                    "memory checkpoint unusable (%s); trying storage", e
                )
                return None
        finally:
            self._shard_lock.release()
        logger.info("restored step %s from host memory", meta.step)
        return meta.step, restored

    def _load_from_storage(self, template: Any, step: Optional[int] = None):
        if step is None:
            step = self.storage.latest_step()
        if step is None:
            return None
        arrays = self.storage.load_step_host(step)
        if arrays is None:
            return None
        try:
            restored = _restore_into_template(template, arrays)
        except (KeyError, ValueError) as e:
            logger.warning(
                "storage checkpoint step %s unusable (%s); starting fresh",
                step,
                e,
            )
            return None
        logger.info("restored step %s from storage %s", step, self.checkpoint_dir)
        return step, restored

    def _gather_steps(self, step: int) -> List[int]:
        """Every host's restored step (single-process: just ours)."""
        if jax.process_count() <= 1:
            return [step]
        from jax.experimental import multihost_utils

        return [
            int(s) for s in multihost_utils.process_allgather(np.int64(step))
        ]

    def load_consistent(self, template: Any) -> Tuple[int, Optional[Any]]:
        """``load`` + cross-host consistency (reference
        ``verify_all_rank_step_consistent`` allgather,
        flash_checkpoint/engine.py:74-95).

        ``load`` is per-host (own shm → peer → storage), so after a node
        replacement hosts can legally restore DIFFERENT steps — and a
        step-count fix alone would train a model whose shards mix two
        checkpoints. When the allgathered steps disagree, every host
        discards its restore and reloads the newest step available to
        ALL of them: the smallest committed-storage step across hosts
        (storage is the shared tier; commit markers make it complete).
        No common storage step → everyone starts fresh, consistently.
        """
        step, restored = self.load(template)
        steps = self._gather_steps(step)
        if len(set(steps)) == 1:
            return step, restored
        storage_latest = self.storage.latest_step()
        target = min(
            self._gather_steps(
                -1 if storage_latest is None else storage_latest
            )
        )
        logger.warning(
            "hosts restored different steps %s; reloading common storage "
            "step %s",
            steps,
            target,
        )
        if target < 0:
            return -1, None
        if step == target and restored is not None:
            # our restore already holds exactly this step's data (memory
            # stages and storage commits of a step are the same bytes)
            return step, restored
        del restored
        return target, self._reload(template, target)

    def _reload(self, template: Any, step: int):
        result = self._load_from_storage(template, step=step)
        if result is None:
            raise RuntimeError(
                f"agreed checkpoint step {step} unreadable from storage"
            )
        return result[1]

    # -- shard topology (reference get_local/global_shard_num) -------------

    def get_local_shard_num(self) -> int:
        return 1  # one staged shard per host

    def get_global_shard_num(self) -> int:
        return self.num_hosts

    def close(self) -> None:
        """Release IPC clients and the shm mapping; in standalone mode
        also tear down the in-process saver (thread + servers), so a
        re-meshed world can build a fresh engine without leaking one
        saver stack per topology round."""
        for res in (self._event_q, self._factory_q, self._shard_lock, self.shm):
            try:
                res.close()
            except Exception:
                pass
        if self._standalone:
            AsyncCheckpointSaver.shutdown()
