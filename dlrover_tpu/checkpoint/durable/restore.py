"""Reshard-on-read restore from the durable tier.

Reads a generation written under one (world size, sharding) and
materializes it under the *current* mesh — the first dynamic consumer
of the statically-verified ``RESHARD_RULES``/``ELASTIC_AXES`` rails in
:mod:`dlrover_tpu.parallel.sharding`:

1. discover the newest committed generation (torn-tracker hardened),
   take a GC lease on it;
2. verify every shard's crc32 against the manifest *before* touching
   its contents — a torn or bit-rotted shard fails the restore loudly;
3. assemble each leaf's global array from all saved shards (records
   are deduped by slice: replicated save-shardings write the same
   slice from several hosts);
4. place each leaf under the current mesh by the leaf's category rule
   (replicate / respec / mirror_params via the manifest's saved specs;
   host_local payloads stay host-side, keyed by the current rank);
5. release the lease.

Host-side reads and device placement are split (:func:`read_generation`
vs :func:`place_with_rules`) so the engine can reuse its own batched
template placement while the warm-pool path — no template, possibly a
different job — derives shardings purely from manifest + rules.
"""

import os
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...common.log import logger
from ..meta import CheckpointMeta, ShardRecord, assemble_global
from .layout import CHUNK, DurableLayout, GenerationManifest
from .layout import list_lineages as list_lineages  # re-export for callers


class DurableShardError(RuntimeError):
    """A shard failed checksum or coverage validation."""


def verify_shards(
    layout: DurableLayout, step: int, manifest: GenerationManifest
) -> None:
    """crc32 every shard payload against the manifest before reading
    state out of it."""
    for rank_s, rec in manifest.shards.items():
        rank = int(rank_s)
        path = layout.shard_bin_path(step, rank)
        crc = 0
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                while True:
                    block = f.read(CHUNK)
                    if not block:
                        break
                    crc = zlib.crc32(block, crc)
        except OSError as e:
            raise DurableShardError(
                f"durable shard {rank} of gen_{step} unreadable: {e}"
            ) from e
        if size != int(rec["nbytes"]) or crc != int(rec["checksum"]):
            raise DurableShardError(
                f"durable shard {rank} of gen_{step} failed verification: "
                f"size {size}/{rec['nbytes']}, crc {crc}/{rec['checksum']}"
            )


def _dedupe_records(
    metas: Dict[int, CheckpointMeta],
) -> Dict[str, List[Tuple[int, ShardRecord]]]:
    """Group records by leaf path across all saved ranks, keeping one
    record per distinct slice (replicated shardings stage the same
    slice on several hosts)."""
    by_path: Dict[str, List[Tuple[int, ShardRecord]]] = {}
    seen = set()
    for rank in sorted(metas):
        for rec in metas[rank].records:
            key = (rec.path, tuple(tuple(i) for i in rec.index))
            if key in seen:
                continue
            seen.add(key)
            by_path.setdefault(rec.path, []).append((rank, rec))
    return by_path


def read_generation(
    root: str,
    lineage: str,
    step: Optional[int] = None,
    host_rank: int = 0,
    verify: bool = True,
) -> Tuple[Optional[int], Optional[GenerationManifest], Dict[str, np.ndarray], Dict[str, Any]]:
    """Host-side half of the restore: (step, manifest, {leaf path:
    global np array}, this-host extra). ``(None, None, {}, {})`` when
    the lineage has no committed generation. Holds a GC lease for the
    duration of the read."""
    layout = DurableLayout(root, lineage)
    if step is None:
        step = layout.latest_committed()
    if step is None or not layout.committed(step):
        return None, None, {}, {}
    token = layout.take_lease(step)
    handles = []
    try:
        manifest = layout.read_manifest(step)
        if manifest is None:
            raise DurableShardError(
                f"gen_{step} committed but manifest unreadable"
            )
        if verify:
            verify_shards(layout, step, manifest)
        metas: Dict[int, CheckpointMeta] = {}
        for rank in range(manifest.num_hosts):
            with open(layout.shard_meta_path(step, rank)) as f:
                metas[rank] = CheckpointMeta.from_json(f.read())
        files = {}
        for rank in range(manifest.num_hosts):
            f = open(layout.shard_bin_path(step, rank), "rb")
            handles.append(f)
            files[rank] = f

        def record_read(rank: int):
            def read(rec: ShardRecord) -> bytes:
                f = files[rank]
                f.seek(rec.offset)
                return f.read(rec.nbytes)

            return read

        arrays: Dict[str, np.ndarray] = {}
        for path, recs in _dedupe_records(metas).items():
            # assemble_global takes one reader; close over per-record rank
            rank_of = {id(rec): rank for rank, rec in recs}

            def read_any(rec: ShardRecord) -> bytes:
                return record_read(rank_of[id(rec)])(rec)

            arrays[path] = assemble_global(
                [rec for _, rec in recs], read_any
            )
        # host_local: this host's extra comes from the same-rank saved
        # shard; a host beyond the saved world starts with nothing
        # (rng/data cursors are rebuilt by the loop).
        extra = (
            dict(metas[host_rank].extra)
            if host_rank in metas
            else {}
        )
        return step, manifest, arrays, extra
    finally:
        for f in handles:
            try:
                f.close()
            except OSError:
                pass
        layout.release_lease(step, token)


def place_with_rules(
    manifest: GenerationManifest,
    arrays: Dict[str, np.ndarray],
    mesh,
) -> Dict[str, Any]:
    """Templateless device placement (the warm-pool path): derive each
    leaf's target sharding from its category rule + the manifest's
    saved spec. Thin wrapper over the shared reshard engine in
    :func:`dlrover_tpu.parallel.sharding.place_arrays_with_rules` —
    the same code path the elastic replanner drives for in-memory
    flash-image transitions."""
    from ...parallel.sharding import place_arrays_with_rules

    saved_specs: Dict[str, Any] = {}
    for specs in manifest.category_specs.values():
        saved_specs.update(specs)
    return place_arrays_with_rules(saved_specs, arrays, mesh)


def warm_start(
    root: str,
    lineage: str,
    mesh,
    step: Optional[int] = None,
    host_rank: int = 0,
) -> Tuple[Optional[int], Dict[str, Any], Dict[str, Any]]:
    """Cross-job warm pool entry: restore another job's newest durable
    generation under *this* job's mesh, no template required. Returns
    (step, {leaf path: placed jax array}, extra); (None, {}, {}) when
    the lineage is empty."""
    step, manifest, arrays, extra = read_generation(
        root, lineage, step=step, host_rank=host_rank
    )
    if step is None or manifest is None:
        return None, {}, {}
    placed = place_with_rules(manifest, arrays, mesh)
    logger.info(
        "warm start from lineage %s gen_%s: %s leaves, saved world %s "
        "→ current mesh %s",
        lineage,
        step,
        len(placed),
        manifest.num_hosts,
        dict(getattr(mesh, "shape", {})),
    )
    return step, placed, extra
