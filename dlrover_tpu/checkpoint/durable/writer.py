"""Background durable writer: committed flash image → durable tier.

The flash tier's persist path already keeps the trainer's blocking cost
at D2H + memcpy; the durable tier must not move that number. So the
DurableWriter never runs on the trainer's or the persister's critical
path: the saver *submits* a step after the flash commit succeeds
(latest-wins, a newer submit supersedes an undrained older one) and a
dedicated thread drains it — snapshot the shm payload into a private
buffer under the shard lock (memcpy only, no I/O under the lock: the
double-buffer), then stream + checksum that buffer to durable storage
with the lock released and the trainer free to stage the next step.

Rank 0's writer additionally runs phase 2 (:func:`.commit.commit_generation`)
after the cross-host barrier, then applies the GC keep-policy.
"""

import threading
from typing import Optional

from ...chaos import faults
from ...common.log import logger
from ..meta import CheckpointMeta
from ..shm_handler import SharedMemoryHandler
from .commit import commit_generation, make_barrier
from .gc import collect_generations
from .layout import CHUNK, DurableLayout

DRAIN_RETRIES = 3
DRAIN_RETRY_DELAY_S = 0.2


class DurableWriter:
    """One per host. ``submit`` is the async entry (saver hook);
    ``drain`` is the synchronous core (tests, breakpoint saves, and the
    worker thread all share it)."""

    def __init__(
        self,
        root: str,
        lineage: str,
        host_rank: int,
        num_hosts: int,
        shm: SharedMemoryHandler,
        shard_lock=None,
        master_client=None,
        keep: int = 3,
        commit_timeout_s: float = 120.0,
    ):
        self.layout = DurableLayout(root, lineage)
        self.host_rank = host_rank
        self.num_hosts = num_hosts
        self.shm = shm
        # Coordinates with the trainer's staging writes; standalone
        # tests may run without the cross-process lock.
        self.shard_lock = shard_lock or threading.Lock()
        self.barrier = make_barrier(self.layout, num_hosts, master_client)
        self.keep = keep
        self.commit_timeout_s = commit_timeout_s
        self._cond = threading.Condition()
        self._pending: Optional[int] = None  # latest-wins slot
        self._running = True
        self._thread: Optional[threading.Thread] = None
        self._busy = False
        self.drained_steps = 0
        self.failed_steps = 0

    # -- async path ---------------------------------------------------------

    def submit(self, step: int) -> None:
        """Queue a flash-committed step for durable drain. Latest wins:
        an undrained older step is superseded, never queued behind."""
        with self._cond:
            if self._pending is None or step > self._pending:
                self._pending = step
            if self._thread is None:
                # Lazy start: jobs without a durable tier never pay for
                # the thread.
                self._thread = threading.Thread(
                    target=self._worker,
                    name=f"durable-writer-{self.host_rank}",
                    daemon=True,
                )
                self._thread.start()
            self._cond.notify_all()

    def _worker(self) -> None:
        while True:
            with self._cond:
                while self._running and self._pending is None:
                    self._cond.wait(timeout=1.0)
                if not self._running and self._pending is None:
                    return
                step, self._pending = self._pending, None
            try:
                self.drain(step)
            except Exception as e:  # noqa: BLE001 — durable tier is best-effort; flash tier unaffected
                self.failed_steps += 1
                logger.error(
                    "durable drain of step %s failed permanently: %s",
                    step,
                    e,
                )

    def stop(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def wait_idle(self, timeout: float = 60.0) -> bool:
        """Test/bench helper: block until the queued step (if any) has
        been drained. Returns False on timeout."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cond:
                idle = self._pending is None
            if idle and (self._thread is None or not self._busy):
                return True
            time.sleep(0.02)
        return False

    # -- synchronous core ---------------------------------------------------

    def drain(self, step: int) -> bool:
        """Copy the shm image for ``step`` to the durable tier, signal
        the barrier, and (rank 0) commit. Retries transient shard-write
        faults; raises when the image is gone or retries exhaust."""
        self._busy = True
        try:
            return self._drain(step)
        finally:
            self._busy = False

    def _drain(self, step: int) -> bool:
        import time

        last_err: Optional[Exception] = None
        for attempt in range(DRAIN_RETRIES):
            try:
                meta, buf = self._snapshot(step)
                if meta is None:
                    logger.warning(
                        "durable drain: shm no longer holds step %s "
                        "(superseded); skipping",
                        step,
                    )
                    return False
                self._write_shard(meta, buf)
                break
            except Exception as e:  # noqa: BLE001 — retried; re-raised when exhausted
                last_err = e
                logger.warning(
                    "durable shard write for step %s failed "
                    "(attempt %s/%s): %s",
                    step,
                    attempt + 1,
                    DRAIN_RETRIES,
                    e,
                )
                time.sleep(DRAIN_RETRY_DELAY_S)
        else:
            raise RuntimeError(
                f"durable shard write for step {step} failed after "
                f"{DRAIN_RETRIES} attempts"
            ) from last_err
        self.barrier.signal(step, self.host_rank)
        self.drained_steps += 1
        if self.host_rank != 0:
            return True
        committed = commit_generation(
            self.layout,
            step,
            self.num_hosts,
            barrier=self.barrier,
            timeout_s=self.commit_timeout_s,
        )
        if committed and self.keep > 0:
            collect_generations(self.layout, keep=self.keep)
        return committed

    def _snapshot(self, step: int):
        """Double-buffer: memcpy meta + payload out of shm under the
        shard lock. Chunked so the lock hold is bounded by memcpy speed,
        never by durable-tier I/O."""
        with self.shard_lock:
            meta = self.shm.read_meta()
            if meta is None or meta.step != step:
                return None, None
            reader = self.shm.payload_reader(copy=False)
            if reader is None:
                return None, None
            buf = bytearray(meta.total_bytes)
            offset = 0
            while offset < meta.total_bytes:
                n = min(CHUNK, meta.total_bytes - offset)
                buf[offset : offset + n] = reader(offset, n)
                offset += n
        return meta, buf

    def _write_shard(self, meta: CheckpointMeta, buf: bytearray) -> None:
        faults.inject(
            "ckpt.durable_write", step=meta.step, rank=self.host_rank
        )
        view = memoryview(buf)

        def read(offset: int, nbytes: int) -> bytes:
            return view[offset : offset + nbytes]

        self.layout.write_shard(meta, read)
