"""Two-phase commit of a durable generation.

Phase 1 (per host, :meth:`.layout.DurableLayout.write_shard`): stream
the shard, record its crc32 + size in a done file. Phase 2 (rank 0
only): wait for a barrier saying every host is checksummed-and-done,
then write ``manifest.json`` → ``commit_success`` → advance ``LATEST``
— each write atomic, tracker strictly last, so a crash anywhere in the
window leaves either the previous generation visible or this one,
never a torn tail.

The barrier rides the master's **journaled kv store** when a master is
reachable (``kv_store_add`` — every mutation lands in the master WAL as
``kv.set``, so a failed-over master replays the barrier count and a
re-driven commit converges); standalone mode falls back to the done
files themselves, which the committer re-verifies on the filesystem in
both modes before writing the marker (the kv count is a fast signal,
the done files are the truth).
"""

import time
from typing import Optional

from ...chaos import faults
from ...common.log import logger
from .layout import DurableLayout, GenerationManifest

BARRIER_POLL_S = 0.1
KV_PREFIX = "ckpt/durable"


class FsBarrier:
    """Done-file barrier for standalone (no-master) jobs: the phase-1
    done files double as the arrival signal."""

    def __init__(self, layout: DurableLayout, num_hosts: int):
        self.layout = layout
        self.num_hosts = num_hosts

    def signal(self, step: int, rank: int) -> None:
        pass  # write_shard's done file IS the signal

    def wait_all(self, step: int, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while True:
            if self.layout.all_shards_done(step, self.num_hosts):
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(BARRIER_POLL_S)


class MasterKVBarrier:
    """Commit barrier through the master's journaled kv store.

    Each host bumps one counter key per (lineage, step); the committer
    polls it with the kv ``add(key, 0)`` read idiom. No new master
    endpoints: ``kv_store_add`` is already journaled (the WAL records
    the resulting value), so the barrier survives master failover.
    """

    def __init__(self, client, lineage: str, num_hosts: int):
        self.client = client
        self.lineage = lineage
        self.num_hosts = num_hosts

    def key(self, step: int) -> str:
        return f"{KV_PREFIX}/{self.lineage}/{step}/done"

    def signal(self, step: int, rank: int) -> None:
        self.client.kv_store_add(self.key(step), 1)

    def wait_all(self, step: int, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                count = int(self.client.kv_store_add(self.key(step), 0))
            except Exception as e:  # noqa: BLE001 — master flapping mid-barrier
                logger.warning(
                    "durable barrier poll failed for step %s: %s", step, e
                )
                count = -1
            if count >= self.num_hosts:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(BARRIER_POLL_S)


def build_manifest(
    layout: DurableLayout, step: int, num_hosts: int
) -> GenerationManifest:
    """Assemble the phase-2 manifest from the phase-1 artifacts: shard
    checksums from the done files, the save-time sharding from the
    per-rank metas, and a snapshot of the reshard rule table."""
    from ...parallel.sharding import RESHARD_RULES, category_of_path

    manifest = GenerationManifest(
        step=step,
        lineage=layout.lineage,
        num_hosts=num_hosts,
        timestamp=time.time(),
        reshard_rules={
            cat: [policy, list(axes)]
            for cat, (policy, axes) in RESHARD_RULES.items()
        },
    )
    from ..meta import CheckpointMeta

    for rank in range(num_hosts):
        done = layout.read_done(step, rank)
        if done is None:
            raise RuntimeError(
                f"durable commit for gen_{step}: shard {rank} has no "
                "done record despite a met barrier"
            )
        manifest.shards[str(rank)] = {
            "checksum": int(done["checksum"]),
            "nbytes": int(done["nbytes"]),
        }
        with open(layout.shard_meta_path(step, rank)) as f:
            meta = CheckpointMeta.from_json(f.read())
        if rank == 0:
            manifest.mesh_axes = list(meta.mesh_axes)
            manifest.mesh_shape = list(meta.mesh_shape)
        for rec in meta.records:
            cat = category_of_path(rec.path)
            manifest.category_specs.setdefault(cat, {}).setdefault(
                rec.path, list(rec.spec or [])
            )
    return manifest


def commit_generation(
    layout: DurableLayout,
    step: int,
    num_hosts: int,
    barrier=None,
    timeout_s: float = 120.0,
) -> bool:
    """Rank-0 phase 2. Returns True iff the generation committed. On a
    barrier timeout the generation is left uncommitted (invisible to
    readers) for a later retry or the GC's stale-partial sweep."""
    barrier = barrier or FsBarrier(layout, num_hosts)
    if not barrier.wait_all(step, timeout_s):
        logger.warning(
            "durable commit barrier for %s gen_%s timed out after %.0fs",
            layout.lineage,
            step,
            timeout_s,
        )
        return False
    # The kv barrier is a signal; the done files are the truth — verify
    # them regardless of which barrier fired.
    if not layout.all_shards_done(step, num_hosts):
        logger.warning(
            "durable barrier met for gen_%s but done files missing; "
            "refusing to commit",
            step,
        )
        return False
    faults.inject("ckpt.durable_commit", step=step, lineage=layout.lineage)
    manifest = build_manifest(layout, step, num_hosts)
    layout.atomic_write(
        layout.manifest_path(step), manifest.to_json().encode()
    )
    layout.atomic_write(layout.commit_path(step), b"ok")
    layout.advance_tracker(step)
    logger.info(
        "durable generation committed: %s gen_%s (%s shards)",
        layout.lineage,
        step,
        num_hosts,
    )
    return True


def make_barrier(
    layout: DurableLayout, num_hosts: int, master_client=None
) -> "Optional[FsBarrier]":
    """Pick the barrier for this deployment: master kv when a client is
    available, else the done-file fallback."""
    if master_client is not None:
        return MasterKVBarrier(master_client, layout.lineage, num_hosts)
    return FsBarrier(layout, num_hosts)
