"""Durable checkpoint tier: async sharded persistence with
reshard-on-read restore and cross-job warm pools.

The third rung of the restore chain (shm → peer replica → flash storage
→ **durable**): a background writer drains each flash-committed image to
durable storage behind a two-phase, checksum-verified commit, and the
restore path reshards on read via ``parallel/sharding.py``'s
RESHARD_RULES — so a job restarted at a different world size, or a
different job entirely (warm pool), can materialize the state under its
own mesh. See ``docs/recovery.md`` (durable tier section).
"""

from .commit import FsBarrier, MasterKVBarrier, commit_generation
from .gc import collect_generations
from .layout import DurableLayout, GenerationManifest, list_lineages
from .restore import (
    DurableShardError,
    place_with_rules,
    read_generation,
    warm_start,
)
from .writer import DurableWriter

__all__ = [
    "DurableLayout",
    "GenerationManifest",
    "DurableWriter",
    "DurableShardError",
    "FsBarrier",
    "MasterKVBarrier",
    "commit_generation",
    "collect_generations",
    "list_lineages",
    "read_generation",
    "place_with_rules",
    "warm_start",
]
