"""Generation GC: keep-policy sweep of a durable lineage.

Keep = newest N committed generations ∪ pinned steps ∪ leased steps
(an in-flight restore holds a lease; deleting under it would tear the
read) ∪ the tracker target. Uncommitted partials — a crash between
phase 1 and phase 2 — are swept only once they are older than a grace
window, so an in-flight drain or a barrier still converging is never
collected out from under itself.
"""

import os
import shutil
import time
from typing import List

from ...common.log import logger
from .layout import DurableLayout

# Mirrors the flash tier's stale-partial grace: a partial younger than
# this may still be mid-commit on a slow barrier.
STALE_PARTIAL_GRACE_S = 3600.0


def collect_generations(
    layout: DurableLayout,
    keep: int = 3,
    grace_s: float = STALE_PARTIAL_GRACE_S,
) -> List[int]:
    """Apply the keep-policy to one lineage; returns the swept steps."""
    committed = layout.list_committed()
    protected = set(committed[-keep:]) if keep > 0 else set()
    protected.update(layout.pinned_steps())
    protected.update(layout.leased_steps())
    latest = layout.latest_committed()
    if latest is not None:
        protected.add(latest)
    removed: List[int] = []
    for step in committed:
        if step in protected:
            continue
        shutil.rmtree(layout.gen_dir(step), ignore_errors=True)
        removed.append(step)

    now = time.time()
    try:
        names = os.listdir(layout.lineage_dir)
    except OSError:
        names = []
    for name in names:
        if not (name.startswith("gen_") and name[4:].lstrip("-").isdigit()):
            continue
        step = int(name[4:])
        if layout.committed(step) or step in protected:
            continue
        path = layout.gen_dir(step)
        try:
            age = now - os.path.getmtime(path)
        except OSError:
            continue
        if age > grace_s:
            shutil.rmtree(path, ignore_errors=True)
            removed.append(step)
    if removed:
        logger.info(
            "durable GC swept %s generation(s) from %s: %s",
            len(removed),
            layout.lineage,
            sorted(removed),
        )
    return sorted(removed)
