"""On-disk layout of the durable checkpoint tier.

One durable root holds many *lineages* (one per training run family —
the cross-job warm pool key), each lineage holds *generations* (one per
persisted step), and each generation is the familiar sharded layout
behind a two-phase commit:

    <root>/<lineage>/gen_<step>/shard_<rank>.meta.json
    <root>/<lineage>/gen_<step>/shard_<rank>.bin
    <root>/<lineage>/gen_<step>/.done/shard_<rank>.done   (phase 1)
    <root>/<lineage>/gen_<step>/manifest.json             (phase 2)
    <root>/<lineage>/gen_<step>/commit_success            (phase 2)
    <root>/<lineage>/LATEST                               (tracker)
    <root>/<lineage>/pins/<step>.pin                      (GC keep)
    <root>/<lineage>/leases/gen_<step>/<token>.lease      (GC shield)

Differences from the flash tier's ``PosixCheckpointStorage``:

- every shard carries a crc32 **checksum** (stored in its done file and
  re-stated in the manifest) so a reshard-on-read restore can reject a
  torn or bit-rotted shard *before* assembling state from it;
- the commit marker is only written after a cross-host barrier agrees
  every shard is checksummed-and-done (see :mod:`.commit`), so a torn
  tail — some hosts' shards from generation N, others still at N-1 —
  is never visible to a reader;
- the per-generation ``manifest.json`` records the save-time sharding
  (mesh axes/shape + PartitionSpec per leaf, grouped by TrainState
  category) plus the reshard-rule snapshot, which is what lets a
  restore under a *different* mesh drive ``RESHARD_RULES`` instead of
  guessing.
"""

import json
import os
import uuid
import zlib
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

from ...common.log import logger
from ..meta import CheckpointMeta
from ..storage import PosixCheckpointStorage

TRACKER_FILE = "LATEST"
MANIFEST_FILE = "manifest.json"
COMMIT_FILE = "commit_success"
DONE_DIR = ".done"
PINS_DIR = "pins"
LEASES_DIR = "leases"

# Streaming unit for shard writes/checksums (matches the flash tier's
# chunked persist: no full-payload copy per write call).
CHUNK = 64 * 1024 * 1024


def checksum_stream(reader, total: int, chunk: int = CHUNK) -> int:
    """crc32 over ``total`` bytes served by ``reader(offset, nbytes)``."""
    crc = 0
    offset = 0
    while offset < total:
        n = min(chunk, total - offset)
        crc = zlib.crc32(reader(offset, n), crc)
        offset += n
    return crc


@dataclass
class GenerationManifest:
    """Phase-2 commit artifact: everything a reader in a *different*
    world needs to validate and reshard the generation."""

    step: int = 0
    lineage: str = ""
    num_hosts: int = 1
    mesh_axes: List[str] = field(default_factory=list)
    mesh_shape: List[int] = field(default_factory=list)
    timestamp: float = 0.0
    # rank -> {"checksum": crc32, "nbytes": payload bytes}
    shards: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # TrainState category -> {leaf path: save-time PartitionSpec (jsonable)}
    category_specs: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    # snapshot of parallel/sharding.py RESHARD_RULES at save time
    reshard_rules: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, data: str) -> "GenerationManifest":
        return cls(**json.loads(data))


class DurableLayout:
    """Path arithmetic + tracker/pin/lease bookkeeping for one lineage.

    Pure filesystem mechanics — the commit *protocol* lives in
    :mod:`.commit`, the data plane in :mod:`.writer`/:mod:`.restore`.
    Reuses the flash storage's fsync-then-rename atomic writes.
    """

    def __init__(self, root: str, lineage: str):
        if not lineage:
            raise ValueError("durable lineage must be non-empty")
        self.root = root
        self.lineage = lineage
        self.lineage_dir = os.path.join(root, lineage)
        os.makedirs(self.lineage_dir, exist_ok=True)
        # borrow the atomic-write helpers; its root is our lineage dir
        self._fs = PosixCheckpointStorage(self.lineage_dir)

    # -- paths -------------------------------------------------------------

    def gen_dir(self, step: int) -> str:
        return os.path.join(self.lineage_dir, f"gen_{step}")

    def done_dir(self, step: int) -> str:
        return os.path.join(self.gen_dir(step), DONE_DIR)

    def done_path(self, step: int, rank: int) -> str:
        return os.path.join(self.done_dir(step), f"shard_{rank}.done")

    def shard_meta_path(self, step: int, rank: int) -> str:
        return os.path.join(self.gen_dir(step), f"shard_{rank}.meta.json")

    def shard_bin_path(self, step: int, rank: int) -> str:
        return os.path.join(self.gen_dir(step), f"shard_{rank}.bin")

    def manifest_path(self, step: int) -> str:
        return os.path.join(self.gen_dir(step), MANIFEST_FILE)

    def commit_path(self, step: int) -> str:
        return os.path.join(self.gen_dir(step), COMMIT_FILE)

    def tracker_path(self) -> str:
        return os.path.join(self.lineage_dir, TRACKER_FILE)

    def atomic_write(self, path: str, data: bytes) -> None:
        self._fs._atomic_write(path, data)

    def atomic_write_stream(self, path: str, reader, total: int) -> None:
        self._fs._atomic_write_stream(path, reader, total)

    # -- shard writes (phase 1) --------------------------------------------

    def write_shard(self, meta: CheckpointMeta, reader) -> int:
        """Stream one host's shard into the generation dir and mark it
        done. Returns the payload crc32, which is also recorded in the
        done file so the committer can assemble the manifest without
        re-reading multi-GB payloads."""
        step, rank = meta.step, meta.host_rank
        os.makedirs(self.done_dir(step), exist_ok=True)
        self.atomic_write(
            self.shard_meta_path(step, rank), meta.to_json().encode()
        )
        crc = 0

        def counting_read(offset: int, nbytes: int) -> bytes:
            nonlocal crc
            block = reader(offset, nbytes)
            crc = zlib.crc32(block, crc)
            return block

        self.atomic_write_stream(
            self.shard_bin_path(step, rank), counting_read, meta.total_bytes
        )
        self.atomic_write(
            self.done_path(step, rank),
            json.dumps(
                {"checksum": crc, "nbytes": meta.total_bytes}
            ).encode(),
        )
        return crc

    def read_done(self, step: int, rank: int) -> Optional[Dict[str, int]]:
        try:
            with open(self.done_path(step, rank)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def all_shards_done(self, step: int, num_hosts: int) -> bool:
        return all(
            self.read_done(step, r) is not None for r in range(num_hosts)
        )

    # -- commit state ------------------------------------------------------

    def committed(self, step: int) -> bool:
        return os.path.exists(self.commit_path(step))

    def read_manifest(self, step: int) -> Optional[GenerationManifest]:
        try:
            with open(self.manifest_path(step)) as f:
                return GenerationManifest.from_json(f.read())
        except (OSError, ValueError, TypeError):
            return None

    def list_committed(self) -> List[int]:
        steps = []
        try:
            names = os.listdir(self.lineage_dir)
        except OSError:
            return steps
        for name in names:
            if name.startswith("gen_") and name[4:].lstrip("-").isdigit():
                step = int(name[4:])
                if self.committed(step):
                    steps.append(step)
        return sorted(steps)

    def latest_committed(self) -> Optional[int]:
        """Newest restorable generation. Same torn-tracker discipline
        as the hardened flash ``latest_step``: a tracker pointing at a
        generation whose commit marker is missing (crash inside the
        commit window, or a swept generation) is skipped in favor of
        the newest generation that actually committed."""
        tracked: Optional[int] = None
        try:
            with open(self.tracker_path()) as f:
                tracked = int(f.read().strip())
        except (OSError, ValueError):
            tracked = None
        if tracked is not None and self.committed(tracked):
            return tracked
        committed = self.list_committed()
        if committed:
            if tracked is not None:
                logger.warning(
                    "durable tracker for %s points at uncommitted "
                    "gen_%s; falling back to committed gen_%s",
                    self.lineage,
                    tracked,
                    committed[-1],
                )
            return committed[-1]
        return None

    def advance_tracker(self, step: int) -> None:
        self.atomic_write(self.tracker_path(), str(step).encode())

    # -- pins (operator keep) ----------------------------------------------

    def pin_path(self, step: int) -> str:
        return os.path.join(self.lineage_dir, PINS_DIR, f"{step}.pin")

    def pin(self, step: int) -> None:
        self.atomic_write(self.pin_path(step), b"pinned")

    def unpin(self, step: int) -> None:
        try:
            os.unlink(self.pin_path(step))
        except OSError:
            pass

    def pinned_steps(self) -> List[int]:
        pins_dir = os.path.join(self.lineage_dir, PINS_DIR)
        out = []
        try:
            names = os.listdir(pins_dir)
        except OSError:
            return out
        for name in names:
            stem = name[:-4] if name.endswith(".pin") else name
            if stem.lstrip("-").isdigit():
                out.append(int(stem))
        return sorted(out)

    # -- restore leases (GC shield) ----------------------------------------

    def lease_dir(self, step: int) -> str:
        return os.path.join(self.lineage_dir, LEASES_DIR, f"gen_{step}")

    def take_lease(self, step: int) -> str:
        """Mark a restore in flight on this generation; GC refuses to
        delete a leased generation (an in-flight reader may hold open
        file handles on a filesystem where unlink is not graceful)."""
        token = uuid.uuid4().hex
        self.atomic_write(
            os.path.join(self.lease_dir(step), f"{token}.lease"), b"lease"
        )
        return token

    def release_lease(self, step: int, token: str) -> None:
        try:
            os.unlink(os.path.join(self.lease_dir(step), f"{token}.lease"))
        except OSError:
            pass
        try:
            os.rmdir(self.lease_dir(step))
        except OSError:
            pass  # other leases still active, or already gone

    def leased_steps(self) -> List[int]:
        leases_root = os.path.join(self.lineage_dir, LEASES_DIR)
        out = []
        try:
            names = os.listdir(leases_root)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("gen_") and name[4:].lstrip("-").isdigit()):
                continue
            try:
                active = bool(os.listdir(os.path.join(leases_root, name)))
            except OSError:
                active = False
            if active:
                out.append(int(name[4:]))
        return sorted(out)


def list_lineages(root: str) -> List[str]:
    """Lineage names with at least one committed generation — what the
    cross-job warm pool can start from."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in sorted(names):
        path = os.path.join(root, name)
        if not os.path.isdir(path):
            continue
        if DurableLayout(root, name).list_committed():
            out.append(name)
    return out
