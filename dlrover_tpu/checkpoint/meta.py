"""Checkpoint metadata: self-describing, re-mesh-aware shard records.

The reference stages torch tensors with ``TensorMeta{shape,dtype,offset}``
(``ckpt_saver.py:89``). For jax the unit is a *device shard* of a pytree
leaf: each record carries the leaf's GLOBAL shape, its PartitionSpec and
the mesh shape it was saved under, plus the local index (slice bounds) of
the shard — exactly the information needed to reassemble or re-shard the
leaf onto a *different* mesh at load time (SURVEY.md §7 "re-mesh
correctness" hard part).
"""

import json
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

HEADER_LEN_BYTES = 8  # u64 little-endian length of the JSON meta block


@dataclass
class ShardRecord:
    """One device-shard of one pytree leaf staged at ``offset``."""

    path: str  # "/"-joined pytree key path
    global_shape: List[int]
    local_shape: List[int]
    dtype: str  # numpy dtype string
    # [(start, stop) per dim] of this shard within the global array
    index: List[Tuple[int, int]]
    offset: int
    nbytes: int
    spec: List[Any] = field(default_factory=list)  # PartitionSpec as lists

    def slices(self) -> Tuple[slice, ...]:
        return tuple(slice(a, b) for a, b in self.index)


@dataclass
class CheckpointMeta:
    step: int = 0
    host_rank: int = 0
    num_hosts: int = 1
    mesh_axes: List[str] = field(default_factory=list)
    mesh_shape: List[int] = field(default_factory=list)
    records: List[ShardRecord] = field(default_factory=list)
    total_bytes: int = 0
    timestamp: float = 0.0
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, data: str) -> "CheckpointMeta":
        raw = json.loads(data)
        records = [
            ShardRecord(**{**r, "index": [tuple(i) for i in r["index"]]})
            for r in raw.pop("records", [])
        ]
        return cls(records=records, **{k: v for k, v in raw.items()})


def spec_to_jsonable(spec) -> List[Any]:
    """PartitionSpec → JSON-able nested lists (tuples → lists)."""
    out: List[Any] = []
    for entry in tuple(spec or ()):
        if isinstance(entry, (tuple, list)):
            out.append(list(entry))
        else:
            out.append(entry)
    return out


def jsonable_to_spec(data: List[Any]):
    from jax.sharding import PartitionSpec

    entries = []
    for entry in data or []:
        if isinstance(entry, list):
            entries.append(tuple(entry))
        else:
            entries.append(entry)
    return PartitionSpec(*entries)


def assemble_global(records: List[ShardRecord], record_read) -> np.ndarray:
    """Reassemble one leaf's global array from (possibly partial) records.

    ``record_read(rec) -> buffer`` returns one record's payload (bytes or
    a zero-copy view) — records may live in different shard files
    (multi-host) or one shm segment. Records must cover the full global
    index space (validated).

    When a single record covers the whole leaf, its buffer is wrapped
    without copying — the caller owns keeping the backing storage alive
    until it is done with the result (the engine holds the shard lock
    through the device transfer for exactly this reason).
    """
    assert records, "no records for leaf"
    head = records[0]
    total = int(np.prod(head.global_shape)) if head.global_shape else 1
    if len(records) == 1:
        covers = (not head.index) or all(
            a == 0 and b == dim
            for (a, b), dim in zip(head.index, head.global_shape)
        )
        if covers:
            return np.frombuffer(
                record_read(head), dtype=np.dtype(head.dtype)
            ).reshape(head.global_shape)
    out = np.empty(head.global_shape, dtype=np.dtype(head.dtype))
    covered_elems = 0
    full_write = False
    for rec in records:
        block = np.frombuffer(
            record_read(rec), dtype=np.dtype(rec.dtype)
        ).reshape(rec.local_shape)
        if rec.index:
            out[rec.slices()] = block
            covered_elems += int(np.prod(rec.local_shape)) if rec.local_shape else 1
        else:
            out[...] = block
            full_write = True
    # Records are disjoint (JAX shard indices after replica dedup), so a
    # volume sum equals full coverage — no per-element mask needed.
    if not full_write and covered_elems != total:
        raise ValueError(
            f"incomplete shard coverage for leaf {head.path}: "
            f"{covered_elems}/{total} elements"
        )
    return out
