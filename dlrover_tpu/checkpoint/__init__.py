from .checkpointer import Checkpointer, StorageType  # noqa: F401
from .engine import CheckpointEngine  # noqa: F401
