"""Peer-memory checkpoint replicas over the DCN control plane.

Reference: ``CkptReplicaManger`` / ``ShardCkptReplicaManager``
(``dlrover/trainer/torch/flash_checkpoint/replica.py:28,73-245``) back up
each node's shm checkpoint shard into a peer node's memory via an
allgather over backup ranks, and ``engine.py:392-409`` gathers a lost
shard back from peers on restart — recovery without touching storage
even when a whole node (and its shm) is replaced.

TPU-native shape: replication is a *host-level* concern, so it lives in
the agent's saver process, not the training loop. The staged shm bytes
are pushed asynchronously to a peer host's :class:`ReplicaServer` over
DCN (plain HTTP, streamed in chunks), never riding the ICI data plane
and never blocking the train step — the reference's in-training
allgather would serialize a multi-GB transfer into the step time on a
TPU, and a host-level push is also what survives when the training
process is already dead. Peer discovery goes through the master KV
store (``ckpt_replica/{rank}`` -> ``host:port``).

The stored unit is the raw shm segment image
(``[8B meta_len][meta JSON][payload]``), so a fetched replica can be
written verbatim into the replacement host's segment and loaded through
the normal memory-restore path.
"""

import hashlib
import os
import socket
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from ..chaos import faults
from ..common.config import get_context
from ..common.log import logger
from ..common.multi_process import SharedMemorySegment
from .meta import HEADER_LEN_BYTES, CheckpointMeta
from .shm_handler import segment_image_size, stream_into_segment

KV_PREFIX = "ckpt_replica/"
_CHUNK = 8 << 20
_TOKEN_HEADER = "X-Replica-Token"


def _job_token() -> str:
    """Shared-secret for the replica endpoints. Prefer an operator-set
    secret (DLROVER_REPLICA_TOKEN); otherwise derive from the job name so
    at least cross-job and drive-by requests are rejected. Proper network
    isolation (k8s NetworkPolicy scoping the job's pods) is still the
    primary control; this closes the unauthenticated-write hole."""
    secret = os.getenv("DLROVER_REPLICA_TOKEN")
    if secret:
        return secret
    job = os.getenv("DLROVER_JOB_NAME", "default")
    return hashlib.sha256(f"dlrover-replica:{job}".encode()).hexdigest()


def default_master_client():
    """MasterClient from env if a master address is configured."""
    try:
        from ..rpc.client import MasterClient

        return MasterClient.singleton()
    except Exception as e:  # noqa: BLE001 — master optional for replicas
        logger.debug("no master client for replica placement: %r", e)
        return None


def replica_segment_name(owner_rank: int) -> str:
    return f"ckpt_replica_{owner_rank}"


def backup_rank(host_rank: int, num_hosts: int) -> int:
    """Peer that stores this host's replica.

    Pairs of adjacent ranks back each other up (reference
    ``ShardCkptReplicaManager`` builds 2-rank backup groups,
    replica.py:99-116); a trailing odd rank wraps to rank 0.
    """
    if num_hosts <= 1:
        return host_rank
    partner = host_rank ^ 1
    if partner >= num_hosts:
        partner = 0
    return partner


class ReplicaStore:
    """Holds peers' segment images in this host's memory (shm-backed, so
    a replica survives agent restarts just like the local shard)."""

    def __init__(self):
        self._segments: Dict[int, SharedMemorySegment] = {}
        self._sizes: Dict[int, int] = {}
        self._gens: Dict[int, int] = {}  # bumped on every overwrite
        # Per-rank locks: a PUT body is paced by the remote sender, so a
        # stalled peer must only wedge its own slot, never the endpoint
        # (rank 0 can hold two ranks' replicas under the odd-N wrap).
        self._meta_lock = threading.Lock()
        self._rank_locks: Dict[int, threading.Lock] = {}

    def _lock_for(self, owner_rank: int) -> threading.Lock:
        with self._meta_lock:
            lock = self._rank_locks.get(owner_rank)
            if lock is None:
                lock = threading.Lock()
                self._rank_locks[owner_rank] = lock
            return lock

    def _segment(self, owner_rank: int) -> SharedMemorySegment:
        seg = self._segments.get(owner_rank)
        if seg is None:
            seg = SharedMemorySegment(replica_segment_name(owner_rank))
            self._segments[owner_rank] = seg
        return seg

    def put_stream(
        self, owner_rank: int, total: int, read: Callable[[int], bytes]
    ) -> None:
        """Stream ``total`` bytes from ``read(n)`` into the owner's
        replica segment (no full-payload copy in RAM). Torn-write safe:
        the advertised size is dropped before the overwrite and the
        segment header lands last (:func:`stream_into_segment`), so an
        interrupted PUT leaves an image readers treat as absent — never
        a new meta over an old payload."""
        with self._lock_for(owner_rank):
            self._sizes.pop(owner_rank, None)
            self._gens[owner_rank] = self._gens.get(owner_rank, 0) + 1
            seg = self._segment(owner_rank)
            stream_into_segment(seg, total, read)
            self._sizes[owner_rank] = total

    def image_size(self, owner_rank: int) -> int:
        with self._lock_for(owner_rank):
            size = self._sizes.get(owner_rank, 0)
            if size:
                return size
            # After an agent restart the segment may pre-exist in shm:
            # recover its logical size from the embedded meta.
            size = segment_image_size(self._segment(owner_rank))
            if size:
                self._sizes[owner_rank] = size
            return size

    def generation(self, owner_rank: int) -> int:
        with self._lock_for(owner_rank):
            return self._gens.get(owner_rank, 0)

    def read_checked(
        self, owner_rank: int, offset: int, nbytes: int, gen: int
    ):
        """Read bytes IF the slot still holds generation ``gen``; None
        when it was overwritten (the gen check and the read share one
        lock acquisition, so a PUT can never interleave between them)."""
        with self._lock_for(owner_rank):
            if self._gens.get(owner_rank, 0) != gen:
                return None
            seg = self._segment(owner_rank)
            if not seg.attach():
                return None
            return seg.read(offset, nbytes)

    def read(self, owner_rank: int, offset: int, nbytes: int) -> bytes:
        with self._lock_for(owner_rank):
            seg = self._segment(owner_rank)
            if not seg.attach():
                return b""
            return seg.read(offset, nbytes)

    def step_of(self, owner_rank: int) -> Optional[int]:
        if not self.image_size(owner_rank):
            return None
        with self._lock_for(owner_rank):
            seg = self._segment(owner_rank)
            try:
                meta_len = int.from_bytes(
                    seg.read(0, HEADER_LEN_BYTES), "little"
                )
                meta = CheckpointMeta.from_json(
                    seg.read(HEADER_LEN_BYTES, meta_len).decode()
                )
                return meta.step
            except Exception as e:  # noqa: BLE001 — torn header reads as absent
                logger.debug("replica meta unreadable: %r", e)
                return None

    def close(self) -> None:
        with self._meta_lock:
            for seg in self._segments.values():
                try:
                    seg.close()
                except Exception as e:  # noqa: BLE001 — teardown
                    logger.debug("replica segment close: %r", e)
            self._segments.clear()

    def unlink(self) -> None:
        with self._meta_lock:
            for seg in self._segments.values():
                try:
                    seg.unlink()
                except Exception as e:  # noqa: BLE001 — teardown
                    logger.debug("replica segment unlink: %r", e)
            self._segments.clear()
            self._sizes.clear()


class _ReplicaHandler(BaseHTTPRequestHandler):
    store: ReplicaStore = None  # set on the server subclass
    protocol_version = "HTTP/1.1"
    # Socket timeout: a peer that dies mid-PUT without RST must not pin
    # this handler thread (and its rank lock) forever.
    timeout = 60

    def _rank(self) -> Optional[int]:
        parts = self.path.strip("/").split("/")
        if len(parts) == 2 and parts[0] == "shard":
            try:
                return int(parts[1])
            except ValueError:
                return None
        return None

    def _authorized(self) -> bool:
        if self.headers.get(_TOKEN_HEADER, "") == _job_token():
            return True
        self.send_error(403)
        return False

    def do_PUT(self):  # noqa: N802 — http.server API
        if not self._authorized():
            return
        rank = self._rank()
        length = int(self.headers.get("Content-Length", 0))
        if rank is None or length <= 0:
            self.send_error(400)
            return
        try:
            self.store.put_stream(rank, length, self.rfile.read)
        except Exception as e:
            logger.exception("replica PUT failed")
            self.send_error(500, str(e))
            return
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):  # noqa: N802
        if not self._authorized():
            return
        rank = self._rank()
        if rank is None:
            self.send_error(404)
            return
        total = self.store.image_size(rank)
        if not total:
            self.send_error(404)
            return
        # Chunked reads interleaving with a concurrent PUT would serve a
        # mixed-generation image; abort (short body -> client discards)
        # if the slot is overwritten mid-stream.
        gen = self.store.generation(rank)
        self.send_response(200)
        self.send_header("Content-Length", str(total))
        self.end_headers()
        off = 0
        while off < total:
            chunk = self.store.read_checked(
                rank, off, min(_CHUNK, total - off), gen
            )
            if chunk is None:
                logger.warning(
                    "replica GET for rank %s aborted: slot overwritten", rank
                )
                self.close_connection = True
                return
            if not chunk:
                break
            self.wfile.write(chunk)
            off += len(chunk)

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class ReplicaServer:
    """Per-host replica endpoint (runs in the agent/saver process)."""

    def __init__(self, store: ReplicaStore, port: int = 0):
        handler = type("BoundReplicaHandler", (_ReplicaHandler,), {"store": store})
        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="ckpt-replica"
        )

    def start(self) -> None:
        self._thread.start()
        logger.info("checkpoint replica server on :%s", self.port)

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception as e:  # noqa: BLE001 — teardown
            logger.debug("replica server stop: %r", e)


class ReplicaClient:
    """Push/fetch segment images to/from a peer's ReplicaServer."""

    @staticmethod
    def push(
        addr: str,
        owner_rank: int,
        total: int,
        read: Callable[[int, int], bytes],
        timeout: Optional[float] = None,
    ) -> bool:
        """PUT ``total`` bytes (``read(offset, n)``) as rank's shard.

        ``timeout`` None → ``Context.ckpt_replica_timeout_s``
        (DLROVER_CKPT_REPLICA_TIMEOUT_S): replica transfers move whole
        shard images, so they get their own deadline knob rather than
        the control-plane ``rpc_deadline_s``."""

        class _Reader:
            def __init__(self):
                self.off = 0

            def read(self, n: int = -1) -> bytes:
                if self.off >= total:
                    return b""
                n = total - self.off if n is None or n < 0 else min(n, total - self.off)
                chunk = read(self.off, n)
                self.off += len(chunk)
                return chunk

        req = urllib.request.Request(
            f"http://{addr}/shard/{owner_rank}", data=_Reader(), method="PUT"
        )
        req.add_header("Content-Length", str(total))
        req.add_header(_TOKEN_HEADER, _job_token())
        try:
            # Chaos hook inside the try: an injected push failure rides
            # the real log-and-drop path (replication is best-effort).
            faults.inject("ckpt.replica.push", rank=owner_rank, addr=addr)
            if timeout is None:
                timeout = get_context().ckpt_replica_timeout_s
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status == 200
        except Exception as e:
            logger.warning("replica push to %s failed: %s", addr, e)
            return False

    @staticmethod
    def fetch_stream(
        addr: str,
        owner_rank: int,
        sink: Callable[[int, Callable[[int], bytes]], None],
        timeout: Optional[float] = None,
    ) -> bool:
        """GET rank's shard from ``addr``; call ``sink(total, read)``."""
        req = urllib.request.Request(
            f"http://{addr}/shard/{owner_rank}",
            headers={_TOKEN_HEADER: _job_token()},
        )
        try:
            # Chaos hook: peer-replica loss mid-restore — the engine's
            # load must continue down the fallback chain to storage.
            faults.inject("ckpt.replica.fetch", rank=owner_rank, addr=addr)
            if timeout is None:
                timeout = get_context().ckpt_replica_timeout_s
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                total = int(resp.headers.get("Content-Length", 0))
                if resp.status != 200 or total <= 0:
                    return False
                sink(total, resp.read)
                return True
        except Exception as e:
            logger.debug("replica fetch from %s: %s", addr, e)
            return False


class ReplicaManager:
    """Agent-side replication driver.

    ``replicate()`` pushes the local staged shard to the backup peer;
    ``fetch_own_shard(sink)`` recovers this host's shard from whichever
    peer holds it (reference engine.py:392-409 ``gather``).
    """

    def __init__(
        self,
        host_rank: int,
        num_hosts: int,
        master_client=None,
        peers: Optional[Dict[int, str]] = None,
        advertise_host: Optional[str] = None,
    ):
        self.host_rank = host_rank
        self.num_hosts = num_hosts
        self.master_client = master_client
        self._static_peers = peers
        self.store = ReplicaStore()
        # Server is created in start(): fetch-only users (the trainer
        # engine restoring from a peer) must not bind a port.
        self.server: Optional[ReplicaServer] = None
        self._advertise_host = advertise_host or _local_host()

    def start(self) -> None:
        if self.server is None:
            self.server = ReplicaServer(self.store)
        self.server.start()
        self._register()

    def _register(self) -> None:
        if self.master_client is None or self.server is None:
            return
        addr = f"{self._advertise_host}:{self.server.port}"
        try:
            self.master_client.kv_store_set(
                f"{KV_PREFIX}{self.host_rank}", addr.encode()
            )
        except Exception:
            logger.exception("replica address registration failed")

    def peer_addrs(self) -> Dict[int, str]:
        if self._static_peers is not None:
            return dict(self._static_peers)
        if self.master_client is None:
            return {}
        keys = [f"{KV_PREFIX}{r}" for r in range(self.num_hosts)]
        try:
            kvs = self.master_client.kv_store_multi_get(keys)
        except Exception:
            logger.exception("replica peer lookup failed")
            return {}
        out = {}
        for key, val in (kvs or {}).items():
            if val:
                out[int(key.rsplit("/", 1)[-1])] = val.decode()
        return out

    def replicate(
        self, total: int, read: Callable[[int, int], bytes]
    ) -> bool:
        """Push this host's staged segment image to its backup peer."""
        peer = backup_rank(self.host_rank, self.num_hosts)
        if peer == self.host_rank:
            return True  # single host: nothing to protect against
        addr = self.peer_addrs().get(peer)
        if not addr:
            logger.warning("no replica address for peer %s", peer)
            return False
        ok = ReplicaClient.push(addr, self.host_rank, total, read)
        if ok:
            logger.info(
                "replicated shard of rank %s (%d bytes) to rank %s",
                self.host_rank,
                total,
                peer,
            )
        return ok

    def refill_shm(self, shm, storage) -> str:
        """Fetch this host's replicated shard into ``shm`` with the
        storage-staleness guard — the ONE refill rule shared by the
        engine-side restore fallback and the agent-side overlapped
        prefetch (callers hold their own shard lock). Returns
        ``empty`` (no replica / unreadable) | ``stale`` (replica lags
        committed storage; image dropped so a later breakpoint save
        cannot persist it and regress the tracker) | ``refilled``."""
        if not self.fetch_own_shard(shm.write_image_stream):
            return "empty"
        meta = shm.read_meta()
        if meta is None:
            return "empty"
        storage_step = storage.latest_step()
        if storage_step is not None and storage_step > meta.step:
            logger.info(
                "peer replica holds step %s but storage has %s; "
                "preferring storage",
                meta.step,
                storage_step,
            )
            shm.invalidate()
            return "stale"
        return "refilled"

    def fetch_own_shard(
        self, sink: Callable[[int, Callable[[int], bytes]], None]
    ) -> bool:
        """Recover this host's shard from the peer that holds it.

        Only ``backup_rank(self)`` can have the replica (the mapping is
        deterministic), so no full-fleet probe — each dead peer would
        otherwise cost a connect timeout during recovery."""
        holder = backup_rank(self.host_rank, self.num_hosts)
        if holder == self.host_rank:
            return False
        addrs = self.peer_addrs()
        addr = addrs.get(holder)
        if not addr:
            logger.warning("no replica address for holder %s", holder)
            return False
        if ReplicaClient.fetch_stream(addr, self.host_rank, sink):
            logger.info(
                "recovered shard of rank %s from peer %s",
                self.host_rank,
                holder,
            )
            return True
        return False

    def stop(self) -> None:
        if self.server is not None:
            self.server.stop()
            # Unregister: peers must not keep pushing to a dead endpoint
            # (peer_addrs skips empty values).
            if self.master_client is not None:
                try:
                    self.master_client.kv_store_set(
                        f"{KV_PREFIX}{self.host_rank}", b""
                    )
                except Exception:
                    logger.warning("replica address unregister failed")
        self.store.close()


def _local_host() -> str:
    """Advertised host for THIS node's replica endpoint: never a
    loopback (see platform.routable_host) and never an env override —
    DLROVER_MASTER_HOST is typically set job-uniformly via the pod
    template, and honoring it here would make every node advertise the
    master's address as its own shard endpoint."""
    from ..common.platform import routable_host

    return routable_host()
