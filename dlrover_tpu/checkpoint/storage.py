"""Filesystem storage backend with the done-dir commit protocol.

Reference: ``ckpt_saver.py`` persistence half — per-shard files, a
``.done`` marker per shard, a commit marker once every shard landed, and
the ``dlrover_latest.txt`` tracker pointing at the newest complete step.
Layout:

    <dir>/<step>/shard_<rank>.meta.json
    <dir>/<step>/shard_<rank>.bin
    <dir>/<step>/.done/shard_<rank>.done
    <dir>/<step>/commit_success
    <dir>/dlrover_latest.txt
"""

import os
import shutil
import tempfile
from typing import Dict, List, Optional

import numpy as np

from ..common.constants import CheckpointConstant
from ..common.log import logger
from .meta import CheckpointMeta, ShardRecord, assemble_global


class PosixCheckpointStorage:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    # -- paths -------------------------------------------------------------

    def step_dir(self, step: int) -> str:
        return os.path.join(self.root, str(step))

    def _done_dir(self, step: int) -> str:
        return os.path.join(self.step_dir(step), CheckpointConstant.DONE_DIR)

    def tracker_path(self) -> str:
        return os.path.join(self.root, CheckpointConstant.TRACKER_FILE)

    # -- writes ------------------------------------------------------------

    WRITE_CHUNK = 64 * 1024 * 1024

    def write_shard(self, meta: CheckpointMeta, payload) -> None:
        """``payload`` is either the raw bytes or a reader
        ``(offset, nbytes) -> bytes`` streamed in chunks (no full copy)."""
        step_dir = self.step_dir(meta.step)
        os.makedirs(self._done_dir(meta.step), exist_ok=True)
        rank = meta.host_rank
        self._atomic_write(
            os.path.join(step_dir, f"shard_{rank}.meta.json"),
            meta.to_json().encode(),
        )
        bin_path = os.path.join(step_dir, f"shard_{rank}.bin")
        if callable(payload):
            self._atomic_write_stream(bin_path, payload, meta.total_bytes)
        else:
            self._atomic_write(bin_path, payload)
        self._atomic_write(
            os.path.join(self._done_dir(meta.step), f"shard_{rank}.done"), b"ok"
        )

    def commit(self, step: int, num_shards: int) -> bool:
        """All shards done → write commit marker + update tracker."""
        if not self.all_shards_done(step, num_shards):
            return False
        self._atomic_write(
            os.path.join(self.step_dir(step), CheckpointConstant.COMMIT_FILE), b"ok"
        )
        self._atomic_write(self.tracker_path(), str(step).encode())
        logger.info("checkpoint step %s committed (%s shards)", step, num_shards)
        return True

    def _atomic_write_stream(self, path: str, reader, total_bytes: int) -> None:
        d = os.path.dirname(path)
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                offset = 0
                while offset < total_bytes:
                    n = min(self.WRITE_CHUNK, total_bytes - offset)
                    f.write(reader(offset, n))
                    offset += n
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def _atomic_write(self, path: str, data: bytes) -> None:
        d = os.path.dirname(path)
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    # -- persist error channel (saver → blocked trainer) -------------------

    def _error_path(self, rank: int) -> str:
        return os.path.join(self.root, f".persist_error_{rank}")

    def record_persist_error(self, rank: int, step: int, reason: str) -> None:
        self._atomic_write(
            self._error_path(rank), f"{step}\n{reason}".encode()
        )

    def clear_persist_error(self, rank: int) -> None:
        try:
            os.unlink(self._error_path(rank))
        except OSError:
            pass

    def persist_error(self, rank: int):
        """(step, reason) of the rank's last failed persist, or None."""
        try:
            with open(self._error_path(rank)) as f:
                step_line, _, reason = f.read().partition("\n")
                return int(step_line), reason
        except (FileNotFoundError, ValueError):
            return None

    # -- queries -----------------------------------------------------------

    def all_shards_done(self, step: int, num_shards: int) -> bool:
        done = self._done_dir(step)
        if not os.path.isdir(done):
            return False
        return all(
            os.path.exists(os.path.join(done, f"shard_{r}.done"))
            for r in range(num_shards)
        )

    def committed(self, step: int) -> bool:
        return os.path.exists(
            os.path.join(self.step_dir(step), CheckpointConstant.COMMIT_FILE)
        )

    def latest_step(self) -> Optional[int]:
        """Newest restorable step. The tracker is a hint, not the
        truth: a crash inside the commit window (marker written, then
        died before — or mid — tracker update) or a swept step can
        leave it pointing at a step with no ``commit_success``. Such a
        torn tracker is skipped in favor of the newest step that
        actually committed."""
        tracked: Optional[int] = None
        try:
            with open(self.tracker_path()) as f:
                tracked = int(f.read().strip())
        except (FileNotFoundError, ValueError):
            tracked = None
        if tracked is not None and self.committed(tracked):
            return tracked
        committed = self.list_steps()
        if committed:
            if tracked is not None:
                logger.warning(
                    "checkpoint tracker points at uncommitted step %s; "
                    "falling back to committed step %s",
                    tracked,
                    committed[-1],
                )
            return committed[-1]
        return None

    def list_steps(self) -> List[int]:
        steps = []
        if not os.path.isdir(self.root):
            return steps
        for name in os.listdir(self.root):
            if name.isdigit() and self.committed(int(name)):
                steps.append(int(name))
        return sorted(steps)

    # -- reads -------------------------------------------------------------

    def read_shard_meta(self, step: int, rank: int) -> Optional[CheckpointMeta]:
        path = os.path.join(self.step_dir(step), f"shard_{rank}.meta.json")
        try:
            with open(path) as f:
                return CheckpointMeta.from_json(f.read())
        except FileNotFoundError:
            return None

    def shard_payload_reader(self, step: int, rank: int):
        path = os.path.join(self.step_dir(step), f"shard_{rank}.bin")
        if not os.path.exists(path):
            return None
        f = open(path, "rb")

        def read(offset: int, nbytes: int) -> bytes:
            f.seek(offset)
            return f.read(nbytes)

        return read

    def load_step_host(self, step: int) -> Optional[Dict[str, np.ndarray]]:
        """Assemble {leaf_path: global array} from all shards of a step."""
        metas = []
        rank = 0
        while True:
            meta = self.read_shard_meta(step, rank)
            if meta is None:
                break
            metas.append(meta)
            rank += 1
        if not metas:
            return None
        by_path: Dict[str, List[ShardRecord]] = {}
        readers = {}
        rec_owner: Dict[int, int] = {}
        for meta in metas:
            readers[meta.host_rank] = self.shard_payload_reader(step, meta.host_rank)
            for rec in meta.records:
                by_path.setdefault(rec.path, []).append(rec)
                rec_owner[id(rec)] = meta.host_rank
        def record_read(rec: ShardRecord) -> bytes:
            return readers[rec_owner[id(rec)]](rec.offset, rec.nbytes)

        out = {}
        for path, records in by_path.items():
            # Deduplicate identical indices across hosts (dp replicas)
            uniq = {}
            for rec in records:
                uniq.setdefault(tuple(map(tuple, rec.index)), rec)
            out[path] = assemble_global(list(uniq.values()), record_read)
        return out

    def remove_step(self, step: int) -> None:
        shutil.rmtree(self.step_dir(step), ignore_errors=True)

    # Uncommitted step dirs older than this are crash leftovers (a host
    # died mid-persist); anything younger may be an in-flight write.
    STALE_PARTIAL_GRACE_S = 3600.0

    def keep_latest(self, count: int) -> None:
        """Retain the ``count`` most RECENTLY COMMITTED steps (by commit
        marker mtime, NOT step number: a fresh run reusing a root that
        still holds a stale higher-numbered history must not have its
        new low-numbered commits deleted out from under the tracker).
        Also sweeps uncommitted step dirs past the staleness grace —
        crashed partial persists would otherwise accumulate forever."""
        import time as _time

        committed = []
        partial = []
        if not os.path.isdir(self.root):
            return
        for name in os.listdir(self.root):
            if not name.isdigit():
                continue
            step = int(name)
            marker = os.path.join(
                self.step_dir(step), CheckpointConstant.COMMIT_FILE
            )
            try:
                committed.append((os.path.getmtime(marker), step))
            except OSError:
                try:
                    partial.append(
                        (os.path.getmtime(self.step_dir(step)), step)
                    )
                except OSError:
                    pass
        committed.sort()
        keep = {step for _, step in committed[-count:]}
        tracked = self.latest_step()
        if tracked is not None:
            keep.add(tracked)  # never delete what the tracker points at
        for _, step in committed[:-count]:
            if step not in keep:
                self.remove_step(step)
        now = _time.time()
        for mtime, step in partial:
            if now - mtime > self.STALE_PARTIAL_GRACE_S and step not in keep:
                logger.info("removing stale partial checkpoint step %s", step)
                self.remove_step(step)
