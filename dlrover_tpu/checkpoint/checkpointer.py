"""User-facing checkpoint API.

Reference: ``Checkpointer`` ABC + ``DdpCheckpointer`` etc.
(``flash_checkpoint/checkpointer.py``, ``ddp.py``) with the
``StorageType.MEMORY/DISK`` selector. One class suffices here — the engine
already derives shard topology from jax shardings.
"""

from typing import Any, Optional, Tuple

from .engine import CheckpointEngine


class StorageType:
    MEMORY = "memory"
    DISK = "disk"


class Checkpointer:
    """``save_checkpoint(step, state, storage_type)`` / ``load_checkpoint``.

    ``state`` is any jax pytree (e.g. a TrainState). Memory saves block
    ~milliseconds; disk saves stage to memory and persist asynchronously
    in the agent.
    """

    def __init__(self, checkpoint_dir: str, mesh=None, **engine_kwargs):
        self.engine = CheckpointEngine(checkpoint_dir, mesh=mesh, **engine_kwargs)

    def save_checkpoint(
        self, step: int, state: Any, storage_type: str = StorageType.DISK
    ) -> bool:
        if storage_type == StorageType.MEMORY:
            return self.engine.save_to_memory(step, state)
        return self.engine.save_to_storage(step, state)

    def load_checkpoint(self, template: Any) -> Tuple[int, Optional[Any]]:
        """Restore into the template pytree; returns (step, state|None)."""
        return self.engine.load(template)

    def wait_latest_checkpoint(self, timeout: float = 300.0) -> bool:
        return self.engine.wait_saving(timeout)

    def close(self) -> None:
        self.engine.close()
