"""Orbax interop: move checkpoints between flash-ckpt storage and Orbax.

The reference's persistence formats are framework-native on purpose —
``MegatronCheckpointSaver`` writes Megatron's tracker files,
``FsdpDcpSaver`` writes torch-DCP ``.metadata`` (``ckpt_saver.py:1276,
1314``) — so users can point their existing tooling at the output.  The
JAX ecosystem's lingua franca is Orbax; this module is the equivalent
bridge:

- :func:`export_to_orbax` — a committed flash-ckpt step (done-dir
  protocol, ``storage.py``) → a standard Orbax checkpoint any Orbax
  user/tool can restore.
- :func:`import_from_orbax` — an Orbax checkpoint → a committed
  flash-ckpt step, so a job migrating onto this runtime resumes straight
  through ``CheckpointEngine.load`` (memory-first path intact).

Arrays travel as host numpy; leaf addressing uses the engine's
``a/b/c`` path-string convention (``shm_handler._path_str``), which maps
1:1 onto nested dicts — the shape Orbax's ``StandardCheckpointer``
saves/restores natively.
"""

import os
from typing import Any, Dict, Optional

import numpy as np

from ..common.log import logger
from .meta import CheckpointMeta, ShardRecord
from .storage import PosixCheckpointStorage


def paths_to_nested(arrays: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """{'a/b': x, 'a/c': y} → {'a': {'b': x, 'c': y}}."""
    root: Dict[str, Any] = {}
    for path, arr in arrays.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            nxt = node.setdefault(p, {})
            if not isinstance(nxt, dict):
                raise ValueError(
                    f"leaf path {path!r} collides with an inner node"
                )
            node = nxt
        node[parts[-1]] = arr
    return root


def nested_to_paths(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    """Inverse of :func:`paths_to_nested` (arbitrary nested dicts)."""
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            key = f"{prefix}/{k}" if prefix else str(k)
            out.update(nested_to_paths(v, key))
        return out
    if prefix == "":
        raise ValueError("checkpoint root must be a mapping")
    out[prefix] = np.asarray(tree)
    return out


def export_to_orbax(
    storage_root: str, orbax_dir: str, step: Optional[int] = None
) -> int:
    """Export a committed flash-ckpt step into ``orbax_dir`` (a fresh
    directory; Orbax refuses to overwrite).  Returns the exported step.
    Multi-host checkpoints are assembled to global arrays first
    (``storage.load_step_host`` re-applies each record's index), so the
    Orbax artifact is topology-free — restorable onto any mesh.
    """
    import orbax.checkpoint as ocp

    storage = PosixCheckpointStorage(storage_root)
    if step is None:
        step = storage.latest_step()
    if step is None:
        raise FileNotFoundError(f"no committed checkpoint under {storage_root}")
    arrays = storage.load_step_host(step)
    if arrays is None:
        raise FileNotFoundError(f"step {step} has no readable shards")
    tree = paths_to_nested(arrays)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(orbax_dir), tree)
    ckptr.wait_until_finished()
    logger.info(
        "exported flash-ckpt step %s (%s leaves) to orbax at %s",
        step,
        len(arrays),
        orbax_dir,
    )
    return step


def import_from_orbax(
    orbax_dir: str, storage_root: str, step: int = 0, force: bool = False
) -> Dict[str, np.ndarray]:
    """Import an Orbax checkpoint as committed flash-ckpt ``step`` (one
    full shard, host_rank 0 — the topology-free layout every engine can
    reshard from on load).  Returns the flat {path: array} map.

    Refuses a ``storage_root`` whose committed history is already ahead
    of ``step``: committing would rewind the latest-step tracker, so
    subsequent loads and retention would operate against the stale low
    step. Pass ``force=True`` (or a larger ``step``) to override.
    """
    import orbax.checkpoint as ocp

    pre = PosixCheckpointStorage(storage_root)
    # max over tracker AND committed dirs: a missing/corrupt tracker must
    # not let the import slip a rewound tracker under committed history.
    existing = max(
        [s for s in (pre.latest_step(),) if s is not None] + pre.list_steps(),
        default=None,
    )
    if existing is not None and existing > step and not force:
        raise ValueError(
            f"storage root {storage_root} already tracks committed step "
            f"{existing} > import step {step}; importing would rewind the "
            "tracker. Use a fresh root, a larger step, or force=True."
        )

    ckptr = ocp.StandardCheckpointer()
    tree = ckptr.restore(os.path.abspath(orbax_dir))
    arrays = nested_to_paths(tree)
    if not arrays:
        raise ValueError(f"orbax checkpoint at {orbax_dir} holds no arrays")

    meta = CheckpointMeta(step=step, host_rank=0, num_hosts=1)
    payload = bytearray()
    for path in sorted(arrays):
        # NOT ascontiguousarray: it promotes 0-d scalars to shape (1,),
        # which would resurrect every scalar leaf as a 1-element vector.
        arr = np.asarray(arrays[path], order="C")
        rec = ShardRecord(
            path=path,
            global_shape=list(arr.shape),
            local_shape=list(arr.shape),
            dtype=str(arr.dtype),
            index=[(0, d) for d in arr.shape],
            offset=len(payload),
            nbytes=int(arr.nbytes),
            spec=[],
        )
        meta.records.append(rec)
        payload += arr.tobytes()
    meta.total_bytes = len(payload)

    storage = PosixCheckpointStorage(storage_root)
    storage.write_shard(meta, bytes(payload))
    if not storage.commit(step, num_shards=1):
        raise RuntimeError(f"commit failed for imported step {step}")
    logger.info(
        "imported orbax checkpoint %s as flash-ckpt step %s (%s leaves)",
        orbax_dir,
        step,
        len(arrays),
    )
    return arrays
