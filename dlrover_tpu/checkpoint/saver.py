"""Agent-side async checkpoint saver.

Reference: ``AsyncCheckpointSaver`` (``ckpt_saver.py:399-1357``) — a
singleton in the *agent* process whose thread drains save events from the
trainer and persists the shm-staged bytes to storage, so the trainer's
blocking cost is only D2H + memcpy. Key behaviors kept:

- factory handshake: the trainer tells the agent what saver to build
  (storage root, shard topology) via a queue (reference ``ClassMeta`` /
  ``_notify_agent_to_create_saver``, engine.py:292-320)
- per-shard lock serializing shm access between trainer and persister
- done-file protocol + commit + ``dlrover_latest.txt`` tracker
- ``save_shm_to_storage``: breakpoint save when workers fail, also wired
  to SIGTERM (reference :533, :758)
"""

import os
import signal
import threading
import queue as _queue
from typing import Dict, Optional

from ..chaos import faults
from ..common.log import logger
from ..common.multi_process import SharedLock, SharedQueue
from .shm_handler import SharedMemoryHandler
from .storage import PosixCheckpointStorage

FACTORY_QUEUE = "ckpt_factory"
EVENT_QUEUE = "ckpt_events"


def lock_name(host_rank: int) -> str:
    return f"ckpt_shard_{host_rank}"


class CheckpointEvent:
    SAVE = "save"
    UPDATE = "update"
    REPLICATE = "replicate"
    EXIT = "exit"


class AsyncCheckpointSaver:
    """Singleton per agent process; one per-host checkpoint shard."""

    _instance: Optional["AsyncCheckpointSaver"] = None
    _cls_lock = threading.Lock()
    _factory_q: Optional[SharedQueue] = None
    _event_q: Optional[SharedQueue] = None
    _runner_thread: Optional[threading.Thread] = None
    _runner_namespace: Optional[str] = None
    _start_lock = threading.Lock()
    _signals_installed = False

    def __init__(
        self,
        storage_root: str,
        host_rank: int = 0,
        num_hosts: int = 1,
        replicate: bool = False,
        replica_peers=None,
        durable_dir: str = "",
        durable_lineage: str = "",
    ):
        self.storage = PosixCheckpointStorage(storage_root)
        self.host_rank = host_rank
        self.num_hosts = num_hosts
        self.shm = SharedMemoryHandler(host_rank)
        # The saver owns the lock server side; trainers connect as clients.
        self._shard_lock = SharedLock(lock_name(host_rank), create=True)
        self._running = True
        self._persisted_steps: Dict[int, bool] = {}
        self.master_client = None  # optional: cross-host step sync
        self.replica_manager = None
        self._replica_peers = replica_peers
        self._replicate_q: Optional[_queue.Queue] = None
        self._replicate_thread: Optional[threading.Thread] = None
        self._durable_writer = None
        self._durable_every = 1
        self._reconfigure_durable(durable_dir, durable_lineage)
        if replicate and num_hosts > 1:
            self._start_replication()

    def _reconfigure_durable(self, durable_dir: str, durable_lineage: str) -> None:
        """(Re)build the durable writer to match the current config and
        shard topology. A stale writer — different root/lineage, or one
        holding the previous world's shm/lock after a re-mesh — is
        stopped and replaced."""
        w = self._durable_writer
        if w is not None and (
            not durable_dir
            or w.layout.root != durable_dir
            or (durable_lineage and w.layout.lineage != durable_lineage)
            or w.host_rank != self.host_rank
            or w.num_hosts != self.num_hosts
            or w.shm is not self.shm
        ):
            w.stop()
            self._durable_writer = None
        if durable_dir and self._durable_writer is None:
            self._setup_durable(durable_dir, durable_lineage)

    def _setup_durable(self, durable_dir: str, durable_lineage: str) -> None:
        """Durable tier hook (checkpoint/durable/): a background writer
        drains each flash-committed image to durable storage off the
        persist path. The commit barrier rides the master's journaled
        kv store when a master is reachable, else the done-file
        fallback."""
        from ..common.config import get_context
        from .durable.writer import DurableWriter
        from .replica import default_master_client

        ctx = get_context()
        lineage = (
            durable_lineage
            or ctx.durable_lineage
            or os.environ.get("DLROVER_JOB_NAME", "")
            or "default"
        )
        client = self.master_client or default_master_client()
        try:
            self._durable_writer = DurableWriter(
                durable_dir,
                lineage,
                self.host_rank,
                self.num_hosts,
                self.shm,
                shard_lock=self._shard_lock,
                master_client=client,
                keep=ctx.durable_keep,
                commit_timeout_s=ctx.durable_commit_timeout_s,
            )
            self._durable_every = max(1, ctx.durable_every)
        except Exception:  # noqa: BLE001 — durable tier is optional; flash tier unaffected
            logger.exception("durable writer failed to start")
            self._durable_writer = None

    def _start_replication(self) -> None:
        """Serve this host's replica store and register its address
        (reference replica.py:73 backup groups; TPU shape: host-level
        push over DCN, see checkpoint/replica.py)."""
        from .replica import ReplicaManager, default_master_client

        client = self.master_client
        if client is None and self._replica_peers is None:
            client = default_master_client()
        try:
            self.replica_manager = ReplicaManager(
                self.host_rank,
                self.num_hosts,
                master_client=client,
                peers=self._replica_peers,
            )
            self.replica_manager.start()
        except Exception:
            logger.exception("replica manager failed to start")
            self.replica_manager = None
            return
        if self._replicate_thread is None or not self._replicate_thread.is_alive():
            self._replicate_q = _queue.Queue(maxsize=64)
            self._replicate_thread = threading.Thread(
                target=self._replicate_worker,
                name="ckpt-replicate",
                daemon=True,
            )
            self._replicate_thread.start()

    # -- factory / lifecycle ----------------------------------------------

    @classmethod
    def start_async_saving_ckpt(cls) -> threading.Thread:
        """Agent entry: create the IPC servers and wait for the trainer's
        factory message, then run the event loop (reference :474).

        Must be called from the agent's main thread so the SIGTERM
        breakpoint-save hook (reference :533) can actually be installed —
        Python only allows signal registration on the main thread.
        """
        from ..common.multi_process import _ipc_namespace

        namespace = _ipc_namespace()
        # _start_lock serializes concurrent starters so a restart (old
        # namespace torn down, new servers coming up) can never be
        # interleaved with — and destroyed by — a second starter acting
        # on a stale snapshot. Separate from _cls_lock because
        # shutdown() takes _cls_lock itself.
        with cls._start_lock:
            with cls._cls_lock:
                alive = (
                    cls._runner_thread is not None
                    and cls._runner_thread.is_alive()
                )
                stale_reason = None
                if alive and cls._runner_namespace == namespace:
                    # Same namespace is necessary but not sufficient: the
                    # socket DIRECTORY may have moved (tests repoint
                    # SOCKET_TMP_DIR per test), leaving a live runner
                    # whose servers listen where no new client looks.
                    # Probe with a FRESH client (current path rules).
                    from ..common.multi_process import LocalSocketClient

                    if LocalSocketClient(
                        "queue_" + FACTORY_QUEUE
                    ).available():
                        return cls._runner_thread
                    stale_reason = "factory socket unreachable"
                elif alive:
                    stale_reason = (
                        f"namespace changed {cls._runner_namespace} -> "
                        f"{namespace}"
                    )
            if alive:
                # A live runner serving stale endpoints (the process was
                # reused across jobs, or the socket dir moved): its
                # queue servers answer on the OLD sockets, so a new
                # engine would time out waiting for servers that never
                # come up.
                logger.info("saver endpoints stale (%s); restarting", stale_reason)
                cls.shutdown()
            with cls._cls_lock:
                cls._factory_q = SharedQueue(FACTORY_QUEUE, create=True)
                cls._event_q = SharedQueue(EVENT_QUEUE, create=True)
                cls._runner_namespace = namespace
            cls._install_signal_handlers()
            factory_q, event_q = cls._factory_q, cls._event_q

            def runner():
                while True:
                    msg = factory_q.get()
                    if msg is None or msg.get("type") == "exit":
                        return
                    try:
                        # Chaos hook: a wedge here leaves the factory
                        # socket answering but the shard-lock server
                        # never created — the trainer engine's wait
                        # must time out and fall back to a standalone
                        # saver in a fresh IPC namespace.
                        faults.inject("ckpt.saver.factory")
                        saver = cls.get_or_create(
                            storage_root=msg["storage_root"],
                            host_rank=msg.get("host_rank", 0),
                            num_hosts=msg.get("num_hosts", 1),
                            replicate=msg.get("replicate", False),
                            replica_peers=msg.get("replica_peers"),
                            durable_dir=msg.get("durable_dir", ""),
                            durable_lineage=msg.get("durable_lineage", ""),
                        )
                        # Lock server must exist before the trainer
                        # acquires it; get_or_create made it. Ack by
                        # re-running the loop.
                        saver._event_loop(event_q)
                    except Exception:
                        logger.exception(
                            "checkpoint saver crashed; waiting again"
                        )

            thread = threading.Thread(
                target=runner, name="ckpt-saver", daemon=True
            )
            thread.start()
            cls._runner_thread = thread
            return thread

    @classmethod
    def get_or_create(
        cls,
        storage_root: str,
        host_rank: int = 0,
        num_hosts: int = 1,
        replicate: bool = False,
        replica_peers=None,
        durable_dir: str = "",
        durable_lineage: str = "",
    ) -> "AsyncCheckpointSaver":
        with cls._cls_lock:
            if cls._instance is None:
                cls._instance = cls(
                    storage_root,
                    host_rank,
                    num_hosts,
                    replicate=replicate,
                    replica_peers=replica_peers,
                    durable_dir=durable_dir,
                    durable_lineage=durable_lineage,
                )
            else:
                inst = cls._instance
                inst.storage = PosixCheckpointStorage(storage_root)
                if (
                    host_rank != inst.host_rank
                    or num_hosts != inst.num_hosts
                ):
                    # Elastic re-mesh changed the shard topology: the old
                    # shm/lock/step bookkeeping belongs to the old world.
                    logger.info(
                        "saver topology change: rank %s/%s -> %s/%s",
                        inst.host_rank,
                        inst.num_hosts,
                        host_rank,
                        num_hosts,
                    )
                    if host_rank != inst.host_rank:
                        inst._shard_lock.close()
                        inst._shard_lock = SharedLock(
                            lock_name(host_rank), create=True
                        )
                        inst.shm.close()
                        inst.shm = SharedMemoryHandler(host_rank)
                    inst.host_rank = host_rank
                    inst.num_hosts = num_hosts
                    inst._persisted_steps.clear()
                    if inst.replica_manager is not None:
                        inst.replica_manager.stop()
                        inst.replica_manager = None
                if replicate and num_hosts > 1:
                    inst._replica_peers = replica_peers
                    if inst.replica_manager is None:
                        inst._start_replication()
                elif inst.replica_manager is not None:
                    # replication turned off with unchanged topology:
                    # stop serving and unregister the stale endpoint
                    inst.replica_manager.stop()
                    inst.replica_manager = None
                inst._reconfigure_durable(durable_dir, durable_lineage)
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._cls_lock:
            cls._instance = None

    @classmethod
    def shutdown(cls, timeout: float = 10.0) -> None:
        """Stop the runner thread, IPC servers, and the instance's
        shm/lock resources. Safe to call repeatedly."""
        with cls._cls_lock:
            factory_q, event_q = cls._factory_q, cls._event_q
            thread, inst = cls._runner_thread, cls._instance
            cls._factory_q = cls._event_q = None
            cls._runner_thread = None
            cls._instance = None
        if inst is not None:
            inst.stop()
        if event_q is not None and thread is not None and thread.is_alive():
            try:
                event_q.put({"type": CheckpointEvent.EXIT}, timeout=2.0)
            except Exception as e:  # noqa: BLE001 — peer may be gone
                logger.debug("saver exit event not delivered: %r", e)
        if factory_q is not None and thread is not None and thread.is_alive():
            try:
                factory_q.put({"type": "exit"}, timeout=2.0)
            except Exception as e:  # noqa: BLE001 — peer may be gone
                logger.debug("saver factory exit not delivered: %r", e)
        if thread is not None:
            thread.join(timeout)
        for q in (factory_q, event_q):
            if q is not None:
                try:
                    q.close()
                except Exception as e:  # noqa: BLE001 — teardown
                    logger.debug("saver queue close: %r", e)
        if inst is not None:
            inst.shm.close()
            try:
                inst._shard_lock.close()
            except Exception as e:  # noqa: BLE001 — teardown
                logger.debug("saver shard lock close: %r", e)

    @classmethod
    def _install_signal_handlers(cls) -> None:
        """Breakpoint-save on SIGTERM (pod eviction / preemption): persist
        whatever step is staged in shm, then resume default termination."""
        if cls._signals_installed:
            return
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "saver started off the main thread; SIGTERM breakpoint "
                "save disabled"
            )
            return
        orig_term = signal.getsignal(signal.SIGTERM)

        def on_term(signum, frame):
            logger.info("SIGTERM: attempting breakpoint checkpoint persist")
            inst = cls._instance
            if inst is not None:
                try:
                    inst.save_shm_to_storage()
                except Exception:
                    logger.exception("breakpoint save on SIGTERM failed")
            if callable(orig_term):
                orig_term(signum, frame)
            else:
                # SIG_DFL/SIG_IGN aren't callable: restore and re-deliver
                # so the process still dies from the signal.
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        try:
            signal.signal(signal.SIGTERM, on_term)
            cls._signals_installed = True
        except ValueError:
            pass

    # -- event loop --------------------------------------------------------

    def _event_loop(self, event_q: SharedQueue) -> None:
        logger.info(
            "checkpoint saver running (host_rank=%s root=%s)",
            self.host_rank,
            self.storage.root,
        )
        while self._running:
            try:
                event = event_q.get(timeout=2.0)
            except _queue.Empty:
                continue
            if event is None:
                continue
            etype = event.get("type")
            if etype == CheckpointEvent.EXIT:
                return
            if etype == CheckpointEvent.SAVE:
                self._persist_step(event.get("step", -1))
            elif etype == CheckpointEvent.REPLICATE:
                self._replicate_step(event.get("step", -1))

    def _persist_step(self, step: int) -> None:
        """Drain shm → storage under the shard lock (reference :925).

        The write streams straight from the shm buffer in chunks — no
        full-payload copy in agent RAM (matters at multi-GB checkpoints).
        The lock is held for the whole persist; the trainer's
        save_to_memory uses a non-blocking acquire and skips the step if
        we're still writing (reference engine.py:351-365).

        Failures land in an on-disk error marker so the trainer's
        ``wait_saving`` fails fast instead of burning its whole timeout
        (VERDICT r1 weak #8: a crashed persist had no error channel back
        to the blocked trainer).
        """
        try:
            # Chaos hook: an error lands in the persist-error marker
            # (wait_saving fails fast); a wedge holds the shard lock —
            # the trainer's non-blocking saves must skip, not stall.
            faults.inject("ckpt.saver.persist", step=step)
            with self._shard_lock:
                meta = self.shm.read_meta()
                if meta is None:
                    logger.warning(
                        "save event for step %s but shm is empty", step
                    )
                    return
                if step >= 0 and meta.step != step:
                    logger.warning(
                        "shm holds step %s, save event wanted %s; "
                        "persisting shm step",
                        meta.step,
                        step,
                    )
                reader = self.shm.payload_reader()
                self.storage.write_shard(meta, reader)
            self._persisted_steps[meta.step] = True
            committed = self.storage.commit(meta.step, self.num_hosts)
            # Only clear the fail-fast marker when THIS persist covers
            # the marker's recorded step: shm holding an older step
            # means that stage never landed (e.g. its async staging died
            # before zeroing the header) and the marker — written by the
            # failed stage, possibly AFTER this persist started — must
            # keep wait_saving from burning its full timeout on a step
            # that will never commit.
            marker = self.storage.persist_error(self.host_rank)
            # No marker read → nothing to clear; calling clear anyway
            # would race a marker recorded between the read and the
            # unlink (trainer staging thread) and delete it.
            if marker is not None and marker[0] <= meta.step:
                self.storage.clear_persist_error(self.host_rank)
            if committed:
                from ..common.config import get_context

                keep = get_context().ckpt_keep_latest
                if keep > 0:
                    # bounded retention (reference keeps a rolling set;
                    # unbounded step dirs eventually fill the volume)
                    self.storage.keep_latest(keep)
                # Durable tier hand-off: submit is a latest-wins slot
                # write + notify — the drain (copy, checksum, barrier,
                # commit) all happens on the writer's own thread, so
                # the persist loop's cost per step does not grow.
                if (
                    self._durable_writer is not None
                    and meta.step % self._durable_every == 0
                ):
                    self._durable_writer.submit(meta.step)
        except Exception as e:  # noqa: BLE001 — reported via marker
            logger.exception("persist failed for step %s", step)
            try:
                self.storage.record_persist_error(self.host_rank, step, repr(e))
            except Exception:  # noqa: BLE001
                logger.exception("could not record persist error marker")

    def _replicate_step(self, step: int) -> None:
        """Hand the push to the replication worker: a multi-GB DCN
        transfer must not stall the persist loop behind it (the SAVE for
        the same step sits on the same serial event queue)."""
        if self.replica_manager is None or self._replicate_q is None:
            return
        try:
            self._replicate_q.put_nowait(step)
        except _queue.Full:
            logger.warning("replication backlog full; dropping step %s", step)

    def _replicate_worker(self) -> None:
        """Optimistic lock-free push with verify-after: the shard lock is
        NOT held during the transfer (a 2-minute push would make the
        trainer skip its memory saves), so the trainer may restage while
        we stream. The staged step is compared before and after; a
        mismatch means the bytes were torn mid-push and the new image is
        pushed again — the receiver's torn copy is overwritten, and its
        header-last protocol keeps even the torn copy unreadable rather
        than silently wrong."""
        while self._running:
            try:
                self._replicate_q.get(timeout=1.0)
            except _queue.Empty:
                continue
            # collapse the backlog: only the newest staged image matters
            try:
                while True:
                    self._replicate_q.get_nowait()
            except _queue.Empty:
                pass
            manager = self.replica_manager
            if manager is None:
                continue
            for _ in range(3):
                meta = self.shm.read_meta()
                total = self.shm.image_size()
                if meta is None or not total:
                    break
                before = meta.step
                if not manager.replicate(total, self.shm.read_image):
                    break
                after = self.shm.read_meta()
                if after is not None and after.step == before:
                    break  # clean push

    def prefetch_restore(self) -> str:
        """Warm-restart fast path, agent side: make this host's shm
        restorable BEFORE the worker boots — called while the agent's
        rendezvous is still polling for the new world (overlapped
        restore). With an image already staged this is a no-op; an
        empty shm (the previous trainer never staged, or the segment
        was torn down) pulls the replica of this host's shard from its
        backup peer, with the same storage-staleness guard as the
        engine-side refill. Returns the outcome for logging/tests:
        ``staged`` | ``refilled`` | ``stale`` | ``empty`` |
        ``unavailable``."""
        if self.shm.read_meta() is not None:
            return "staged"
        if self.replica_manager is None:
            return "unavailable"
        with self._shard_lock:
            if self.shm.read_meta() is not None:
                return "staged"
            # shared refill rule (ReplicaManager.refill_shm): on
            # "stale" the image is dropped and the worker's normal
            # chain picks storage
            return self.replica_manager.refill_shm(self.shm, self.storage)

    @classmethod
    def prefetch_restore_async(cls) -> Optional[threading.Thread]:
        """Kick :meth:`prefetch_restore` on a background thread (the
        agent calls this right before ``next_rendezvous`` so the peer
        fetch rides under the rendezvous poll). None when no saver
        instance exists yet — a first-boot agent has nothing to
        prefetch; the worker engine's own prefetch covers that case."""
        inst = cls._instance
        if inst is None:
            return None

        def run() -> None:
            try:
                logger.info(
                    "agent restore prefetch: %s", inst.prefetch_restore()
                )
            except Exception:  # noqa: BLE001 — an optimization only
                logger.exception("agent restore prefetch failed")

        t = threading.Thread(target=run, name="restore-prefetch", daemon=True)
        t.start()
        return t

    def save_shm_to_storage(self) -> bool:
        """Breakpoint save: persist whatever step is staged in shm
        (reference :758, called from the agent when workers fail)."""
        meta = self.shm.read_meta()
        if meta is None:
            return False
        if self._persisted_steps.get(meta.step):
            return True  # already safe
        logger.info("breakpoint-saving step %s from shm", meta.step)
        self._persist_step(meta.step)
        return True

    def stop(self) -> None:
        self._running = False
        if self.replica_manager is not None:
            self.replica_manager.stop()
        if self._durable_writer is not None:
            self._durable_writer.stop()
