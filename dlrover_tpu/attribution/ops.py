"""Op-bucket accounting over the interposer's trace ring.

Input is the compact timeline the native core dumps
(``profiler.timeline`` reads it: events of (name_id, kind, start_us,
dur_us, step)); output is a per-step device-time table bucketed by
what the op IS — the reduction that turns 256k raw events into "the
residual is N% optimizer-HBM time, attack that".

Classification is two-stage: the native kind wins when it already
names the bucket (``TT_KIND_MATMUL``/``TT_KIND_COLLECTIVE`` are
op-granular in the core), then an ordered fingerprint table matches
the interned op name. XLA program names concatenate the fused ops
(``fusion.123.dot_general.add``), so fingerprints are ordered most-
specific-first: a fused attention softmax must not land in ``vpu``
just because it also contains an ``add``.

Granularity depends on the ring's producer: ``profiler.hooks``
``profile_op`` spans and HLO-named programs bucket precisely; the
bare PJRT interposer records whole-executable envelopes whose names
(``jit_train_step``) mostly land in ``other`` — ``gap_dispatch`` and
``top_ops`` stay meaningful there, bucket fractions do not (see
docs/profiler.md §Performance attribution).
"""

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

# Native kinds: ONE Python mirror of TT_KIND_* lives in
# profiler.native (pure constants at import time — no library load);
# re-exported here because every classifier caller passes them.
from ..profiler.native import (  # noqa: F401 — re-exports
    KIND_COLLECTIVE,
    KIND_COMPILE,
    KIND_D2H,
    KIND_EXECUTE,
    KIND_H2D,
    KIND_HLO_COMM,
    KIND_HLO_FLOPS,
    KIND_MATMUL,
    KIND_OTHER,
    KIND_STEP,
)

# Device-execution kinds that enter the accounting. Step markers bound
# spans, transfers are tallied apart, hlo_* are static analysis rows,
# compiles are one-time.
_DEVICE_KINDS = frozenset({KIND_MATMUL, KIND_COLLECTIVE, KIND_OTHER,
                           KIND_EXECUTE})
_TRANSFER_KINDS = frozenset({KIND_H2D, KIND_D2H})

BUCKETS = (
    "matmul",          # MXU — the work MFU credits
    "attention",       # softmax/flash/attention fusions
    "vpu",             # layernorm/activation/residual element-wise
    "optimizer_hbm",   # optimizer update + casts: params-bytes HBM traffic
    "collective",      # cross-chip
    "transfer",        # H2D/D2H on the device timeline
    "gap_dispatch",    # step span not covered by any device op
    "other",
)

# Ordered fingerprint table: first match wins. Collectives before
# attention before matmul before optimizer before vpu — a fused
# all-reduce-of-gradients name containing "add" is collective time.
_FINGERPRINTS: Tuple[Tuple[str, "re.Pattern"], ...] = tuple(
    (bucket, re.compile(pat, re.IGNORECASE))
    for bucket, pat in (
        ("collective",
         r"all-reduce|all_reduce|allreduce|all-gather|all_gather|"
         r"allgather|reduce-scatter|reduce_scatter|all-to-all|"
         r"collective|ppermute|psum"),
        ("attention",
         r"attention|softmax|flash|mha\b|sdpa"),
        ("matmul",
         r"dot_general|\bdot\b|matmul|einsum|\bconv\b|convolution|gemm"),
        ("optimizer_hbm",
         r"adam|sgd|lamb\b|momentum|optimizer|adafactor|"
         r"apply_grad|weight_update|update_step|convert_element_type|"
         r"\bcast\b|\bcopy\b|transpose"),
        ("vpu",
         r"layer_?norm|rms_?norm|\bnorm\b|gelu|silu|relu|swiglu|"
         r"residual|\badd\b|\bsub\b|\bmul\b|\bexp\b|tanh|reduce|"
         r"iota|select|compare|scatter|gather|slice|pad\b|concatenate"),
    )
)

# The next-lever table the top_residual recommendation reads from —
# what attacking each non-matmul bucket concretely means on this
# runtime (docs/profiler.md §Performance attribution).
RECOMMENDATIONS = {
    "attention": (
        "retune the flash kernel for this shape (block sizes / fwd "
        "residual reads) — softmax-adjacent time is kernel-owned"
    ),
    "vpu": (
        "fuse layernorm/residual chains (XLA fusion barriers around "
        "remat boundaries) — VPU time overlaps the MXU only when fused"
    ),
    "optimizer_hbm": (
        "donate optimizer buffers and fuse the update (2x params bytes "
        "of HBM round-trip per step is the floor to beat)"
    ),
    "collective": (
        "overlap collectives with compute (latency-hiding sharding "
        "rules / async collective start)"
    ),
    "transfer": (
        "keep feeds device-resident: prefetch H2D under the step, "
        "fetch only scalars back"
    ),
    "gap_dispatch": (
        "cut dispatch count: scan-over-layers, larger decode chunks, "
        "fewer host round-trips per step"
    ),
    "other": "inspect top_ops — unclassified names dominate the residual",
}


def classify_op(name: str, kind: Optional[int] = None) -> str:
    """Bucket for one op: native kind first, then the fingerprint
    table over the interned name, then ``other``."""
    if kind == KIND_MATMUL:
        return "matmul"
    if kind in (KIND_COLLECTIVE, KIND_HLO_COMM):
        return "collective"
    if kind in _TRANSFER_KINDS:
        return "transfer"
    for bucket, pat in _FINGERPRINTS:
        if pat.search(name or ""):
            return bucket
    return "other"


@dataclass
class BucketStat:
    time_us: float = 0.0
    count: int = 0
    frac: float = 0.0  # of the accounted step span


@dataclass
class StepRow:
    step: int
    span_us: float
    busy_us: float
    buckets: Dict[str, float] = field(default_factory=dict)


@dataclass
class OpTable:
    """Per-step device-time accounting over one ring."""

    steps: List[StepRow]
    buckets: Dict[str, BucketStat]
    total_span_us: float
    events: int
    top_ops: List[Tuple[str, str, float]]  # (name, bucket, time_us)

    def top_residual(self) -> Dict:
        """The largest non-matmul bucket — the next lever — with the
        concrete recommendation for attacking it."""
        best_name, best = None, None
        for name, stat in self.buckets.items():
            if name == "matmul" or stat.time_us <= 0:
                continue
            if best is None or stat.time_us > best.time_us:
                best_name, best = name, stat
        if best_name is None:
            return {"bucket": None, "frac": 0.0,
                    "recommendation": "no residual: ring empty or all-MXU"}
        return {
            "bucket": best_name,
            "frac": round(best.frac, 4),
            "time_us": round(best.time_us, 1),
            "recommendation": RECOMMENDATIONS.get(
                best_name, RECOMMENDATIONS["other"]
            ),
        }

    def to_dict(
        self,
        max_steps: Optional[int] = None,
        max_top_ops: Optional[int] = None,
    ) -> Dict:
        """Serialize; unbounded by default — the saved Report is the
        FULL payload (the bench LINE is what gets truncated, never the
        artifact). Pass limits only for size-sensitive views."""
        return {
            "events": self.events,
            "total_span_us": round(self.total_span_us, 1),
            "buckets": {
                name: {
                    "time_us": round(s.time_us, 1),
                    "count": s.count,
                    "frac": round(s.frac, 4),
                }
                for name, s in self.buckets.items()
            },
            "top_residual": self.top_residual(),
            "top_ops": [
                {"name": n[:80], "bucket": b, "time_us": round(t, 1)}
                for n, b, t in self.top_ops[:max_top_ops]
            ],
            "steps": [
                {
                    "step": r.step,
                    "span_us": round(r.span_us, 1),
                    "busy_us": round(r.busy_us, 1),
                    "buckets": {
                        k: round(v, 1) for k, v in r.buckets.items()
                    },
                }
                for r in self.steps[:max_steps]
            ],
        }


def account_events(
    events: Sequence, names: Optional[Dict[int, str]] = None
) -> OpTable:
    """Reduce ring events to the per-step bucket table.

    ``events`` are ``profiler.timeline.TimelineEvent``-shaped (any
    object with name_id/kind/start_us/dur_us/step). Step span comes
    from the step-kind marker when one exists for that step id,
    otherwise from the step's own event envelope; ``gap_dispatch`` is
    the span not covered by summed op time (dispatch stalls, host
    round-trips). Concurrent streams can make busy > span — the gap
    clamps at zero rather than going negative.
    """
    names = names or {}
    step_spans: Dict[int, float] = {}
    per_step: Dict[int, Dict] = {}
    name_time: Dict[Tuple[str, str], float] = {}

    for ev in events:
        if ev.kind == KIND_STEP:
            step_spans[ev.step] = step_spans.get(ev.step, 0.0) + ev.dur_us
            continue
        if ev.kind not in _DEVICE_KINDS and ev.kind not in _TRANSFER_KINDS:
            continue
        name = names.get(ev.name_id, f"op_{ev.name_id}")
        bucket = classify_op(name, ev.kind)
        row = per_step.setdefault(
            ev.step,
            {"busy": 0.0, "lo": ev.start_us, "hi": ev.start_us + ev.dur_us,
             "buckets": {}, "counts": {}},
        )
        row["busy"] += ev.dur_us
        row["lo"] = min(row["lo"], ev.start_us)
        row["hi"] = max(row["hi"], ev.start_us + ev.dur_us)
        row["buckets"][bucket] = row["buckets"].get(bucket, 0.0) + ev.dur_us
        row["counts"][bucket] = row["counts"].get(bucket, 0) + 1
        key = (name, bucket)
        name_time[key] = name_time.get(key, 0.0) + ev.dur_us

    # a step MARKER with no surviving device ops (ring overflow ate
    # them, or a pure dispatch stall) is still accounted: its whole
    # span is gap_dispatch — dropping it would hide the worst stalls
    # and inflate every other bucket's fraction
    for step_id in step_spans:
        per_step.setdefault(
            step_id,
            {"busy": 0.0, "lo": 0, "hi": 0, "buckets": {}, "counts": {}},
        )

    steps: List[StepRow] = []
    totals: Dict[str, BucketStat] = {b: BucketStat() for b in BUCKETS}
    total_span = 0.0
    n_events = 0
    for step_id in sorted(per_step):
        row = per_step[step_id]
        span = step_spans.get(step_id) or (row["hi"] - row["lo"])
        gap = max(span - row["busy"], 0.0)
        buckets = dict(row["buckets"])
        if gap > 0:
            buckets["gap_dispatch"] = buckets.get("gap_dispatch", 0.0) + gap
        steps.append(
            StepRow(step=step_id, span_us=max(span, row["busy"]),
                    busy_us=row["busy"], buckets=buckets)
        )
        total_span += max(span, row["busy"])
        for b, t in buckets.items():
            stat = totals.setdefault(b, BucketStat())
            stat.time_us += t
            stat.count += row["counts"].get(b, 0)
            n_events += row["counts"].get(b, 0)
    if total_span > 0:
        for stat in totals.values():
            stat.frac = stat.time_us / total_span
    top = sorted(
        ((n, b, t) for (n, b), t in name_time.items()),
        key=lambda r: -r[2],
    )
    return OpTable(
        steps=steps,
        buckets=totals,
        total_span_us=total_span,
        events=n_events,
        top_ops=top,
    )


def format_table(table) -> str:
    """Human table: bucket rows sorted by time, then the verdict.
    Accepts a live :class:`OpTable` or its ``to_dict()`` form (the
    shape a saved Report carries) — ONE renderer serves the CLI and
    ``Report.format`` so the two can never drift."""
    d = table.to_dict() if isinstance(table, OpTable) else table
    lines = [f"{'bucket':14} {'time_ms':>10} {'frac':>7} {'count':>7}"]
    for name, stat in sorted(
        (d.get("buckets") or {}).items(),
        key=lambda kv: -(kv[1].get("time_us") or 0),
    ):
        if not stat.get("time_us"):
            continue
        lines.append(
            f"{name:14} {stat['time_us'] / 1e3:>10.2f} "
            f"{stat.get('frac', 0.0):>7.3f} {stat.get('count', 0):>7}"
        )
    res = d.get("top_residual") or {}
    lines.append("")
    lines.append(
        f"steps accounted: {len(d.get('steps') or [])}  "
        f"span: {(d.get('total_span_us') or 0.0) / 1e3:.2f} ms  "
        f"events: {d.get('events', 0)}"
    )
    if res.get("bucket"):
        lines.append(
            f"top residual: {res['bucket']} ({res.get('frac', 0.0):.1%})"
            f" — {res.get('recommendation', '')}"
        )
    else:
        lines.append(
            f"top residual: {res.get('recommendation', 'empty table')}"
        )
    return "\n".join(lines)
