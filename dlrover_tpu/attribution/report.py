"""The machine-readable attribution Report.

One ``Report`` holds both pillars (op-bucket table, serving phase
split) plus provenance metadata. The serialization contract follows
the bench-line lesson (the driver's parse window is ~2 KB and the
line budget is 1,800 bytes): the FULL report is saved to its own JSON
artifact, and :meth:`Report.headline` yields the ≤5 floats + pointer
that ride in the bench line. Payloads never enter the line.
"""

import json
from dataclasses import dataclass, field
from typing import Dict, Optional

from .ops import OpTable, format_table
from .phases import PhaseSplit

SCHEMA = "dlrover_tpu.attribution.report/v1"


@dataclass
class Report:
    op_table: Optional[Dict] = None  # OpTable.to_dict()
    serving: Optional[Dict] = None  # PhaseSplit.__dict__-shaped
    # MTTR phase breakdown (recovery.aggregate() shape): rdzv_s /
    # restore_s / compile_s / first_step_s + recovery_samples.
    recovery: Optional[Dict] = None
    meta: Dict = field(default_factory=dict)

    def to_dict(self) -> Dict:
        return {
            "schema": SCHEMA,
            "meta": self.meta,
            "op_table": self.op_table,
            "serving": self.serving,
            "recovery": self.recovery,
        }

    def to_json(self, indent: Optional[int] = 1) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @classmethod
    def from_dict(cls, d: Dict) -> "Report":
        if d.get("schema") != SCHEMA:
            raise ValueError(f"not an attribution report: {d.get('schema')!r}")
        return cls(
            op_table=d.get("op_table"),
            serving=d.get("serving"),
            recovery=d.get("recovery"),
            meta=d.get("meta") or {},
        )

    @classmethod
    def from_json(cls, text: str) -> "Report":
        return cls.from_dict(json.loads(text))

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.to_json())
        return path

    @classmethod
    def load(cls, path: str) -> "Report":
        with open(path) as f:
            return cls.from_json(f.read())

    def headline(self) -> Dict:
        """The ≤5 floats that summarize the whole report for the bench
        line: host fraction, MXU fraction, the residual's size, the
        dispatch gap, and how many rounds/steps back them."""
        out: Dict = {}
        if self.serving:
            out["serving_host_frac"] = round(
                float(self.serving.get("serving_host_frac", 0.0)), 4
            )
        if self.op_table:
            buckets = self.op_table.get("buckets") or {}
            mm = buckets.get("matmul") or {}
            if mm:
                out["matmul_frac"] = round(float(mm.get("frac", 0.0)), 4)
            gap = buckets.get("gap_dispatch") or {}
            if gap:
                out["gap_frac"] = round(float(gap.get("frac", 0.0)), 4)
            res = self.op_table.get("top_residual") or {}
            if res.get("bucket"):
                out["top_residual_frac"] = round(
                    float(res.get("frac", 0.0)), 4
                )
        n = 0
        if self.serving:
            n = int(self.serving.get("rounds", 0) or 0)
        if not n and self.op_table:
            n = len(self.op_table.get("steps") or [])
        out["samples"] = n
        return out

    def top_residual(self) -> Dict:
        if self.op_table and self.op_table.get("top_residual"):
            return self.op_table["top_residual"]
        if self.serving:
            # no ring: the residual IS the host side of the split
            frac = float(self.serving.get("serving_host_frac", 0.0))
            if self.serving.get("overlap_s"):
                rec = (
                    "pipeline already overlapping: residual host time "
                    "is dispatch — raise decode_chunk (auto_chunk) or "
                    "cut per-round dispatch work"
                )
            else:
                rec = (
                    "enable the overlapped scheduler round "
                    "(overlap=True) / raise decode_chunk / batch "
                    "retirement reads"
                )
            return {
                "bucket": "serving_host",
                "frac": round(frac, 4),
                "recommendation": rec,
            }
        return {"bucket": None, "frac": 0.0,
                "recommendation": "empty report"}

    def format(self) -> str:
        parts = []
        if self.meta:
            parts.append(
                "  ".join(f"{k}={v}" for k, v in sorted(self.meta.items()))
            )
        if self.op_table:
            # ops.format_table renders the serialized dict form too —
            # one renderer for the CLI and saved reports
            parts.append(format_table(self.op_table))
        if self.serving:
            parts.append(_format_serving(self.serving))
        if self.recovery:
            parts.append(_format_recovery(self.recovery))
        return "\n\n".join(parts) if parts else "(empty report)"


def _format_recovery(rc: Dict) -> str:
    n = int(rc.get("recovery_samples", 0) or 0)
    lines = [
        f"recovery breakdown over {n} per-host recovery records "
        "(mean per phase):"
    ]
    for key in ("rdzv_s", "restore_s", "compile_s", "first_step_s"):
        lines.append(f"  {key:14} {float(rc.get(key, 0.0) or 0.0):8.3f}s")
    return "\n".join(lines)


def _format_serving(sv: Dict) -> str:
    head = (
        f"serving_host_frac: {sv.get('serving_host_frac', 0.0):.3f} "
        f"over {sv.get('rounds', 0)} rounds "
        f"(host {sv.get('host_s', 0.0):.3f}s / "
        f"device {sv.get('device_s', 0.0):.3f}s)"
    )
    if sv.get("overlap_s"):
        # pipelined scheduler: host work hidden behind in-flight chunks
        head += f" + {sv['overlap_s']:.3f}s host hidden by overlap"
    lines = [head]
    for name, stat in sorted(
        (sv.get("phases") or {}).items(),
        key=lambda kv: -(kv[1].get("total_s") or 0),
    ):
        if name == "overlap_hidden":
            side = "hidden"
        else:
            side = "host" if stat.get("host") else "device"
        lines.append(
            f"  {name:16} {side:6} total {stat.get('total_s', 0.0):8.4f}s"
            f"  mean {stat.get('mean_ms', 0.0):8.3f}ms"
            f"  max {stat.get('max_ms', 0.0):8.3f}ms"
            f"  n={stat.get('count', 0)}"
        )
    return "\n".join(lines)


def build_report(
    op_table: Optional[OpTable] = None,
    serving: Optional[PhaseSplit] = None,
    recovery: Optional[Dict] = None,
    meta: Optional[Dict] = None,
) -> Report:
    """Assemble a Report from live objects (any pillar optional)."""
    return Report(
        op_table=op_table.to_dict() if op_table is not None else None,
        serving=dict(serving.__dict__) if serving is not None else None,
        recovery=dict(recovery) if recovery else None,
        meta=dict(meta or {}),
    )


__all__ = ["Report", "build_report", "SCHEMA", "format_table"]
