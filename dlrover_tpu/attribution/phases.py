"""Serving host/device split: scheduler-round phase accounting.

The continuous-batching engine's ``step()`` stamps five phase spans
per scheduler round (the VERDICT r5 #4 gap — a ~4.6x per-slot
throughput loss vs raw decode that nothing measured):

- ``admission``   host: queue pop, slot bookkeeping, swap adoption
- ``prefill``     device: prompt prefill + admit program (and
                  compaction re-prefills in the frontier layout)
- ``decode_dispatch``  host: tracing/dispatching the decode chunk —
                  on a tunneled chip this is the RTT the cost model
                  is built around
- ``host_sync``   device: blocking fetch of the chunk's tokens — the
                  wait measures device execution on a sync backend
- ``retirement``  host: emit loop, completion bookkeeping
- ``overlap_hidden``  the pipelined scheduler's third category: host
                  work (admission, emission, retirement) performed
                  WHILE a decode chunk is in flight on the device.
                  The device is not idle during it, so it is neither
                  host nor device time — it is the host cost the
                  double-buffered round hid.

``serving_host_frac`` = host time / total — the fraction of a serving
round the DEVICE sits idle while the host schedules. Overlap-hidden
time counts toward the total but not toward host: the pipelined
scheduler's win shows up as a nonzero ``overlap_s`` and a reduced
``serving_host_frac`` over the same stream. The accumulator is pure
arithmetic over (phase, seconds) samples, so the split math is
unit-testable on synthetic timestamps without an engine.
"""

import math
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

PHASES = (
    "admission",
    "prefill",
    "decode_dispatch",
    "host_sync",
    "retirement",
    "overlap_hidden",
)
# Fleet gateway phases (dlrover_tpu/fleet/gateway.py) — a SEPARATE
# accumulator from the engine's: "route" and "redispatch" are
# gateway-host work (replica selection, failover bookkeeping);
# "proxy" is time spent waiting on the chosen replica's engine — the
# gateway's equivalent of device time, so a gateway accumulator's
# serving_host_frac reads as gateway overhead over end-to-end request
# time.
GATEWAY_PHASES = (
    "route",
    "proxy",
    "redispatch",
)
# Chip-pool arbiter phases (dlrover_tpu/pool/arbiter.py) — a third
# separate accumulator: "revoke" and "grant" are arbiter-host work
# (ledger transitions, dispatching the tenant call); "drain" is the
# wall time waiting on the tenant's cooperative reclaim (checkpointed
# training shrink, replica drain) — the arbiter's equivalent of
# backend time, so its host_frac reads as arbitration overhead over
# end-to-end capacity-move latency.
POOL_PHASES = (
    "revoke",
    "drain",
    "grant",
)
HOST_PHASES = frozenset(
    {
        "admission",
        "decode_dispatch",
        "retirement",
        "route",
        "redispatch",
        "revoke",
        "grant",
    }
)
DEVICE_PHASES = frozenset({"prefill", "host_sync", "proxy", "drain"})
OVERLAP_PHASES = frozenset({"overlap_hidden"})

# log2(µs) histogram: bucket i covers [2^i, 2^(i+1)) µs; 20 buckets
# reach ~10 min — far past any sane phase span.
HIST_BUCKETS = 20


@dataclass
class PhaseStat:
    total_s: float = 0.0
    count: int = 0
    max_s: float = 0.0
    hist: List[int] = field(default_factory=lambda: [0] * HIST_BUCKETS)


@dataclass
class PhaseSplit:
    """One reduction of an accumulator: totals, fractions, histogram."""

    total_s: float
    host_s: float
    device_s: float
    serving_host_frac: float
    rounds: int
    phases: Dict[str, Dict]
    # host time hidden behind in-flight device chunks (the pipelined
    # scheduler's round): in total_s, in neither host_s nor device_s
    overlap_s: float = 0.0

    def summary(self) -> Dict:
        """Compact dict for /healthz and bench extras (floats only,
        bounded key count — the 1,800-byte line budget applies)."""
        out = {
            "serving_host_frac": round(self.serving_host_frac, 4),
            "rounds": self.rounds,
        }
        if self.overlap_s:
            out["overlap_hidden_s"] = round(self.overlap_s, 4)
        for name, stat in self.phases.items():
            out[f"{name}_ms"] = round(stat["total_s"] * 1e3, 2)
        return out


def _hist_bucket(dur_s: float) -> int:
    us = dur_s * 1e6
    if us < 1.0:
        return 0
    return min(int(math.log2(us)), HIST_BUCKETS - 1)


class PhaseAccumulator:
    """Running per-phase totals + log2-µs histograms. ``add`` is a few
    dict ops — cheap enough to leave always-on in the serving engine
    (one call per phase per scheduler round, not per token)."""

    def __init__(self):
        self._stats: Dict[str, PhaseStat] = {}
        self.rounds = 0

    def add(self, phase: str, dur_s: float) -> None:
        if dur_s < 0:
            dur_s = 0.0
        stat = self._stats.setdefault(phase, PhaseStat())
        stat.total_s += dur_s
        stat.count += 1
        stat.max_s = max(stat.max_s, dur_s)
        stat.hist[_hist_bucket(dur_s)] += 1

    def add_round(
        self, spans: List[Tuple[str, float]]
    ) -> None:
        """One scheduler round's (phase, seconds) spans — the synthetic
        -timestamp entry point the tests drive."""
        for phase, dur_s in spans:
            self.add(phase, dur_s)
        self.rounds += 1

    def reset(self) -> None:
        self._stats.clear()
        self.rounds = 0

    def split(self) -> PhaseSplit:
        # snapshot first: split() is read from other threads (/healthz
        # handler) while the driver's step() inserts phase keys —
        # dict(d) is a single C-level copy under the GIL, so the
        # iteration below never sees a resize
        stats = dict(self._stats)
        host_s = sum(
            s.total_s for p, s in stats.items() if p in HOST_PHASES
        )
        overlap_s = sum(
            s.total_s for p, s in stats.items() if p in OVERLAP_PHASES
        )
        device_s = sum(
            s.total_s for p, s in stats.items()
            if p not in HOST_PHASES and p not in OVERLAP_PHASES
        )
        total_s = host_s + device_s + overlap_s
        return PhaseSplit(
            total_s=total_s,
            host_s=host_s,
            device_s=device_s,
            overlap_s=overlap_s,
            serving_host_frac=(host_s / total_s) if total_s > 0 else 0.0,
            rounds=self.rounds,
            phases={
                name: {
                    "total_s": round(stat.total_s, 6),
                    "count": stat.count,
                    "mean_ms": round(
                        stat.total_s / stat.count * 1e3, 3
                    )
                    if stat.count
                    else 0.0,
                    "max_ms": round(stat.max_s * 1e3, 3),
                    "host": name in HOST_PHASES,
                    "hist_log2us": list(stat.hist),
                }
                for name, stat in stats.items()
            },
        )
