"""MTTR phase attribution: where does a recovery's time go?

The chaos storm (and production) measure MTTR as one number — the
watermark stall. This module splits it into the four serial phases of
the recovery path so a regression (or a win, like the warm-restart
fast path) is attributable per phase instead of inferred:

- ``rdzv_s``       agent: rendezvous join → world formed (measured in
                   ``ElasticTrainingAgent._initialize_workers``);
- ``restore_s``    worker: ``load_consistent`` wall time (overlapped
                   restore shrinks this — the host read ran during
                   model build);
- ``compile_s``    worker: first-step time minus steady-step time —
                   the XLA (re)compile the persistent cache turns into
                   a disk read;
- ``first_step_s`` worker: the first full step after restore (compile
                   + the step itself), the moment the watermark moves.

Transport is a spool DIRECTORY (``DLROVER_RECOVERY_DIR``): each
participant appends one small JSON file (unique name — no locking, no
partial-read hazard beyond atomic rename), and the storm/bench
aggregates the spool after the run. Files carry enough provenance
(``restart``, ``round``, ``resumed``) for the aggregator to keep
first-boot records out of the recovery means.
"""

import json
import os
import time
from typing import Any, Dict, List, Optional

RECOVERY_DIR_ENV = "DLROVER_RECOVERY_DIR"

PHASES = ("rdzv_s", "restore_s", "compile_s", "first_step_s")


def recovery_dir() -> Optional[str]:
    return os.environ.get(RECOVERY_DIR_ENV) or None


def record_phase_file(kind: str, payload: Dict[str, Any]) -> Optional[str]:
    """Append one record to the spool (no-op when the env is unset).
    ``kind`` prefixes the filename (``rdzv`` / ``worker``). Atomic via
    rename so a concurrently-aggregating storm never reads half a
    record. Never raises — attribution must not take recovery down."""
    root = recovery_dir()
    if not root:
        return None
    try:
        os.makedirs(root, exist_ok=True)
        name = f"{kind}_{os.getpid()}_{time.time_ns()}.json"
        tmp = os.path.join(root, "." + name)
        path = os.path.join(root, name)
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.rename(tmp, path)
        return path
    except OSError:
        return None


def read_records(root: str) -> List[Dict[str, Any]]:
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    out = []
    for name in names:
        if not name.endswith(".json") or name.startswith("."):
            continue
        try:
            with open(os.path.join(root, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        rec["_kind"] = name.split("_", 1)[0]
        out.append(rec)
    return out


def aggregate(root: str) -> Dict[str, Any]:
    """Reduce the spool to the per-recovery breakdown.

    Recovery records only: ``rdzv`` files from a re-rendezvous
    (``round > 0`` — round 0 is first boot) and ``worker`` files whose
    loop actually RESUMED from a checkpoint. Means per phase, plus
    ``recovery_samples`` so a 0.0 from "no recoveries happened" is
    distinguishable from a measured zero. The count is PER-HOST
    records, not recovery events: one kill in an N-host job makes
    every host re-rendezvous and resume, contributing N records to one
    event (the per-host means remain the meaningful statistic).
    """
    records = read_records(root)
    rdzv = [
        float(r["rdzv_s"])
        for r in records
        if r["_kind"] == "rdzv"
        and "rdzv_s" in r
        and int(r.get("round", 0)) > 0
    ]
    workers = [
        r
        for r in records
        if r["_kind"] == "worker" and r.get("resumed")
    ]

    def _mean(vals: List[float]) -> float:
        return round(sum(vals) / len(vals), 3) if vals else 0.0

    out: Dict[str, Any] = {
        "rdzv_s": _mean(rdzv),
        "recovery_samples": max(len(rdzv), len(workers)),
    }
    for key in ("restore_s", "compile_s", "first_step_s"):
        out[key] = _mean(
            [float(w[key]) for w in workers if key in w]
        )
    # Master-crash phases (docs/recovery.md master failover): ``master``
    # records are spooled by a replaying master boot, ``reattach`` by
    # every agent's epoch-fenced re-attach. Only present when a master
    # recovery actually happened, so plain worker storms keep their
    # exact key set.
    replays = [
        float(r["replay_s"])
        for r in records
        if r["_kind"] == "master" and r.get("replayed") and "replay_s" in r
    ]
    if replays:
        out["master_replay_s"] = _mean(replays)
        out["master_boot_samples"] = len(replays)
    reattaches = [
        float(r["reattach_s"])
        for r in records
        if r["_kind"] == "reattach" and "reattach_s" in r
    ]
    if reattaches:
        out["reattach_s"] = _mean(reattaches)
    return out
