"""``tpurun-attr`` — op-bucket table from a saved trace ring.

The offline half of the attribution subsystem: point it at a ring the
native core dumped (``TpuTimer.dump_timeline`` / ``pjrt.dump_timeline``,
or the one a bench run saved) and get the bucketed device-time table
plus the ``top_residual`` recommendation — no jax, no device.

    tpurun-attr RING.timeline                  # human table
    tpurun-attr RING.timeline --json           # machine-readable
    tpurun-attr RING.timeline --out report.json  # full Report artifact

The interned-name sidecar is auto-discovered at ``RING + '.names'``;
override with ``--names``.
"""

import argparse
import json
import sys

from ..profiler import timeline
from .ops import account_events, format_table
from .report import build_report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpurun-attr",
        description="op-bucket device-time attribution from a trace ring",
    )
    ap.add_argument("ring", help="ring file (TPUTL001 format)")
    ap.add_argument(
        "--names", default=None,
        help="interned-name sidecar (default: RING + '.names')",
    )
    ap.add_argument(
        "--json", action="store_true", help="print the table as JSON"
    )
    ap.add_argument(
        "--out", default=None,
        help="also write the full Report artifact to this path",
    )
    ap.add_argument(
        "--top", type=int, default=10,
        help="top-N op names in the --json output (the --out Report "
        "artifact is always written in full)",
    )
    ns = ap.parse_args(argv)

    try:
        events = timeline.read_timeline(ns.ring)
    except (OSError, ValueError) as e:
        print(f"tpurun-attr: {e}", file=sys.stderr)
        return 2
    names = timeline.read_names(ns.names or ns.ring + ".names")
    table = account_events(events, names)

    if ns.out:
        report = build_report(
            op_table=table, meta={"ring": ns.ring, "events": len(events)}
        )
        report.save(ns.out)
        print(f"wrote {ns.out}", file=sys.stderr)
    if ns.json:
        print(json.dumps(table.to_dict(max_top_ops=ns.top)))
    else:
        print(format_table(table))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
