"""``tpurun-attr`` — op-bucket table from a saved trace ring.

The offline half of the attribution subsystem: point it at a ring the
native core dumped (``TpuTimer.dump_timeline`` / ``pjrt.dump_timeline``,
or the one a bench run saved) and get the bucketed device-time table
plus the ``top_residual`` recommendation — no jax, no device.

    tpurun-attr RING.timeline                  # human table
    tpurun-attr RING.timeline --json           # machine-readable
    tpurun-attr RING.timeline --out report.json  # full Report artifact
    tpurun-attr --recovery SPOOL_DIR           # MTTR phase breakdown

The interned-name sidecar is auto-discovered at ``RING + '.names'``;
override with ``--names``. ``--recovery`` points at a
``DLROVER_RECOVERY_DIR`` spool (docs/recovery.md) and folds the
per-recovery ``rdzv_s``/``restore_s``/``compile_s``/``first_step_s``
means into the Report — alone or alongside a ring.
"""

import argparse
import json
import sys

from ..profiler import timeline
from .ops import account_events, format_table
from .recovery import aggregate as aggregate_recovery
from .report import build_report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpurun-attr",
        description="op-bucket device-time attribution from a trace ring",
    )
    ap.add_argument(
        "ring", nargs="?", default=None,
        help="ring file (TPUTL001 format)",
    )
    ap.add_argument(
        "--names", default=None,
        help="interned-name sidecar (default: RING + '.names')",
    )
    ap.add_argument(
        "--json", action="store_true", help="print the table as JSON"
    )
    ap.add_argument(
        "--out", default=None,
        help="also write the full Report artifact to this path",
    )
    ap.add_argument(
        "--top", type=int, default=10,
        help="top-N op names in the --json output (the --out Report "
        "artifact is always written in full)",
    )
    ap.add_argument(
        "--recovery", default=None,
        help="recovery spool directory (DLROVER_RECOVERY_DIR, "
        "docs/recovery.md): fold the MTTR phase breakdown into the "
        "report — alone or alongside a ring",
    )
    ns = ap.parse_args(argv)
    if ns.ring is None and ns.recovery is None:
        ap.error("need a ring file and/or --recovery SPOOL_DIR")

    table = None
    events = []
    if ns.ring is not None:
        try:
            events = timeline.read_timeline(ns.ring)
        except (OSError, ValueError) as e:
            print(f"tpurun-attr: {e}", file=sys.stderr)
            return 2
        names = timeline.read_names(ns.names or ns.ring + ".names")
        table = account_events(events, names)
    recovery = aggregate_recovery(ns.recovery) if ns.recovery else None

    if ns.out:
        meta = {"ring": ns.ring, "events": len(events)}
        if ns.recovery:
            meta["recovery_spool"] = ns.recovery
        report = build_report(op_table=table, recovery=recovery, meta=meta)
        report.save(ns.out)
        print(f"wrote {ns.out}", file=sys.stderr)
    if ns.json:
        out = table.to_dict(max_top_ops=ns.top) if table else {}
        if recovery:
            out["recovery"] = recovery
        print(json.dumps(out))
    else:
        parts = []
        if table is not None:
            parts.append(format_table(table))
        if recovery is not None:
            parts.append(
                build_report(recovery=recovery).format()
            )
        print("\n\n".join(parts))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
