"""Performance attribution — turn raw profiling signals into answers.

The runtime's headline numbers (MFU ~0.50, serving per-slot throughput
~4.6x under raw decode) were unattributed for five rounds: the
interposer's per-op trace ring and the serving engine's per-request
timestamps existed, but nothing reduced them to "where does the time
go, and what is the next lever". This subsystem is that reduction,
in three pillars:

- :mod:`~dlrover_tpu.attribution.ops` — drain the PJRT interposer's
  trace ring, classify device ops into buckets (matmul, attention,
  VPU, optimizer/HBM, collective, gap/dispatch) via a fingerprint
  table, and produce a per-step device-time table with a
  ``top_residual`` recommendation.
- :mod:`~dlrover_tpu.attribution.phases` — the serving host/device
  split: the continuous-batching engine stamps its scheduler round
  boundaries (admission, prefill, decode dispatch, host sync,
  retirement) into a :class:`PhaseAccumulator`, which reduces them to
  ``serving_host_frac`` plus a per-phase histogram.
- :mod:`~dlrover_tpu.attribution.report` — the machine-readable
  :class:`Report` (serialized to bench extras as POINTERS + a handful
  of headline floats, never payloads) and its human table.

CLI: ``tpurun-attr RING.timeline`` dumps the op table from a saved
trace ring (see :mod:`~dlrover_tpu.attribution.cli`).
"""

from .ops import (  # noqa: F401
    BUCKETS,
    OpTable,
    account_events,
    classify_op,
)
from .phases import (  # noqa: F401
    DEVICE_PHASES,
    HOST_PHASES,
    PHASES,
    PhaseAccumulator,
    PhaseSplit,
)
from .recovery import (  # noqa: F401
    RECOVERY_DIR_ENV,
    aggregate as aggregate_recovery,
    record_phase_file,
)
from .recovery import PHASES as RECOVERY_PHASES  # noqa: F401
from .report import Report, build_report  # noqa: F401

__all__ = [
    "RECOVERY_DIR_ENV",
    "RECOVERY_PHASES",
    "aggregate_recovery",
    "record_phase_file",
    "BUCKETS",
    "OpTable",
    "account_events",
    "classify_op",
    "PHASES",
    "HOST_PHASES",
    "DEVICE_PHASES",
    "PhaseAccumulator",
    "PhaseSplit",
    "Report",
    "build_report",
]
