"""ChipPoolArbiter: SLO-driven co-scheduling of one TPU chip pool.

After PR 7 the repo held two complete but *disjoint* elastic systems —
the training runtime (agent/master/remesh/flash-checkpoint) and the
serving fleet (supervisor/gateway/autoscaler) — each assuming it owns
every chip. This module is the missing third piece: a ledger of
device-capacity units with **revocable leases** to two tenant adapters
(``pool/tenants.py``), arbitrated by an explicit SLO policy
(docs/pool.md):

- **Priority preemption**: a serving SLO breach (rolling p95 over
  ``p95_target_s``, or mean queue depth over ``queue_high``) revokes
  training capacity — training checkpoints (flash checkpoint) and
  shrinks to the next valid world on its shrink ladder; the freed
  units are granted to serving, which grows replicas on them.
- **Handback hysteresis**: when traffic subsides for
  ``handback_evals`` consecutive evaluations, the surge units are
  revoked from serving (cooperative drain through the fleet's drain
  path) and granted back to training (grow remesh, pre-warmed by the
  compile-ahead service).
- **Revocation deadlines with escalation**: a cooperative revoke that
  misses ``revoke_deadline_s`` escalates — the arbiter forces the
  reclaim through the tenant's hard path (replica terminate / hard
  relaunch) so a wedged tenant cannot squat on the pool.
- **Floors and ceilings**: no tenant is ever revoked below its floor
  or granted above its ceiling; one in-flight move at a time keeps
  every ledger transition journaled and attributable.

Every decision lands in the **journal** (in-memory ring + optional
JSONL file, same O_APPEND one-write discipline as the fault log), and
the revoke→drain→grant wall time is stamped into an attribution
:class:`PhaseAccumulator` (``POOL_PHASES`` — attribution/phases.py),
so ``/pool/status`` reports arbitration latency next to the ledger.

Locking discipline: ``_mu`` guards the ledger/journal only; every
tenant call (report/grant/revoke/escalate) and every fault-injection
hook runs outside it (snapshot-under-lock / act-outside — the
PodScaler incident class).
"""

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..attribution.phases import PhaseAccumulator
from ..chaos import faults
from ..common.journal import JOURNAL_KEEP, DecisionJournal
from ..common.log import logger
from .config import PoolConfig

__all__ = ["ChipPoolArbiter", "Lease", "LeaseState", "decide", "JOURNAL_KEEP"]

TRAINING = "training"
SERVING = "serving"


class LeaseState:
    REVOKING = "revoking"  # cooperative drain in flight
    RELEASED = "released"  # tenant confirmed; units back in the pool
    ESCALATED = "escalated"  # deadline missed; reclaim was forced


@dataclass
class Lease:
    """One in-flight capacity revocation (grants apply instantly and
    are journal-only; a revoke is the async half that needs a state
    machine: issued → drained/escalated → re-granted)."""

    lease_id: int
    tenant: str
    units: int
    deadline_t: float  # monotonic escalation deadline
    grant_to: str = ""  # tenant the freed units go to ("" = free pool)
    reason: str = ""
    state: str = LeaseState.REVOKING
    created_t: float = field(default_factory=time.monotonic)
    released_units: int = 0

    def snapshot(self) -> Dict:
        return {
            "lease_id": self.lease_id,
            "tenant": self.tenant,
            "units": self.units,
            "state": self.state,
            "grant_to": self.grant_to,
            "reason": self.reason,
            "age_s": round(time.monotonic() - self.created_t, 3),
            "deadline_in_s": round(
                self.deadline_t - time.monotonic(), 3
            ),
        }


def decide(
    serving_sig: Optional[Dict],
    alloc: Dict[str, int],
    free: int,
    cfg: PoolConfig,
    calm_streak: int,
    serve_baseline: int,
    trainable: bool = True,
) -> Dict[str, Any]:
    """Pure policy: one evaluation's move (or none).

    Returns ``{"action": "preempt"|"handback"|"reclaim"|None,
    "units": n, "reason": str, "calm_streak": n}``. Kept free of
    ledger and tenant state so every branch is unit-testable on plain
    dicts.

    - **preempt** — serving SLO breach and serving below its ceiling:
      move ``spike_units`` to serving (free pool first, then training
      down to its floor).
    - **reclaim** — unowned free units while training is below its
      ceiling and serving does not need them (no breach): grant them
      to training immediately — they need no revocation, and without
      this branch grid-overshoot excess and rolled-back grants would
      strand in the free ledger. ``trainable=False`` (no training
      adapter attached) disables it.
    - **handback** — serving calm (no queue, no busy slots, p95 well
      under target) for ``handback_evals`` consecutive evaluations and
      serving above its calm baseline: return one spike step toward
      training (capped by training's ceiling).
    """
    out = {"action": None, "units": 0, "reason": "", "calm_streak": 0}
    if serving_sig is None or serving_sig.get("ready", 0) == 0:
        # nothing healthy to measure: never arbitrate blind (the
        # fleet autoscaler's rule, applied pool-wide)
        out["reason"] = "no serving signal"
        return out
    queue_mean = serving_sig.get("queue_mean") or 0.0
    p95 = serving_sig.get("p95_worst_s")
    over_queue = queue_mean >= cfg.queue_high
    over_latency = (
        cfg.p95_target_s > 0
        and p95 is not None
        and p95 > cfg.p95_target_s
    )
    if over_queue or over_latency:
        headroom = cfg.serve_ceiling - alloc.get(SERVING, 0)
        available = free + max(
            0, alloc.get(TRAINING, 0) - cfg.train_floor
        )
        units = min(cfg.spike_units, headroom, available)
        if units > 0:
            out.update(
                action="preempt",
                units=units,
                reason=(
                    f"queue_mean={queue_mean:.2f}"
                    if over_queue
                    else f"p95={p95:.3f}s>{cfg.p95_target_s:.3f}s"
                ),
            )
            return out
        out["reason"] = "breach but no capacity movable"
        # fall through: free units serving cannot take (its ceiling)
        # may still return to training below
    if trainable and free > 0:
        units = min(free, cfg.train_ceiling - alloc.get(TRAINING, 0))
        if units > 0:
            out.update(
                action="reclaim",
                units=units,
                reason=f"{free} unowned free unit(s)",
                # a breach (stuck at the serving ceiling) resets the
                # calm streak; a quiet reclaim preserves it — the
                # serving-surge hysteresis keeps its own clock
                calm_streak=0 if out["reason"] else calm_streak,
            )
            return out
    if out["reason"]:
        return out  # the breach-but-stuck verdict from above
    calm = (
        queue_mean == 0
        and serving_sig.get("busy_total", 0) == 0
        and (
            cfg.p95_target_s <= 0
            or p95 is None
            or p95 < cfg.p95_target_s / 2
        )
    )
    if not calm:
        out["reason"] = "serving active, within SLO"
        return out
    streak = calm_streak + 1
    out["calm_streak"] = streak
    surge = alloc.get(SERVING, 0) - max(cfg.serve_floor, serve_baseline)
    if streak >= cfg.handback_evals and surge > 0:
        units = min(
            cfg.spike_units,
            surge,
            cfg.train_ceiling - alloc.get(TRAINING, 0),
        )
        if units > 0:
            out.update(
                action="handback",
                units=units,
                reason=f"calm for {streak} evals",
                calm_streak=0,
            )
            return out
    out["reason"] = f"calm ({streak} evals)"
    return out


class ChipPoolArbiter:
    """Owns the unit ledger; issues and reclaims leases.

    ``serving`` is required (the latency tenant whose SLO drives
    preemption); ``training`` is optional — without it, spikes draw
    from the free pool only and handback returns units there (the
    ``tpurun-pool serve`` shape where the training half lives in the
    master)."""

    def __init__(
        self,
        serving,
        training=None,
        config: Optional[PoolConfig] = None,
    ):
        self.cfg = config or PoolConfig.from_env()
        self._mu = threading.Lock()
        self._tenants: Dict[str, Any] = {SERVING: serving}
        if training is not None:
            self._tenants[TRAINING] = training
        alloc_serve = int(getattr(serving, "initial_units", 0)) or (
            self.cfg.serve_floor
        )
        alloc_train = 0
        if training is not None:
            alloc_train = int(getattr(training, "initial_units", 0)) or (
                self.cfg.train_floor
            )
        if alloc_serve + alloc_train > self.cfg.total_units:
            raise ValueError(
                "tenants hold more units than the pool: "
                f"{alloc_serve}+{alloc_train} > {self.cfg.total_units}"
            )
        self._alloc: Dict[str, int] = {
            SERVING: alloc_serve,
            TRAINING: alloc_train,
        }
        self._serve_baseline = alloc_serve
        self._free = self.cfg.total_units - alloc_serve - alloc_train
        self._pending: List[Lease] = []
        self._next_lease_id = 0
        self._calm_streak = 0
        self._journal = DecisionJournal(self.cfg.journal_path)
        self.last_signals: Dict[str, Optional[Dict]] = {}
        self.evaluations = 0
        self.revokes = 0
        self.grants = 0
        self.escalations = 0
        self.phases = PhaseAccumulator()
        # serializes whole evaluations: the periodic loop and a manual
        # POST /pool/step must not both pass the pending-lease check
        # and issue two concurrent moves
        self._step_mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- ledger views ----------------------------------------------------

    def allocations(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._alloc)

    def free_units(self) -> int:
        with self._mu:
            return self._free

    def pending_leases(self) -> List[Lease]:
        with self._mu:
            return list(self._pending)

    def wait_idle(self, timeout: float = 60.0) -> bool:
        """Block until no revocation is in flight (drill/test helper)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._mu:
                if not self._pending:
                    return True
            if self._stop.wait(0.05):
                with self._mu:
                    return not self._pending
        return False

    # -- journal ---------------------------------------------------------

    def _record(self, event: str, **detail) -> Dict:
        """Journal one ledger event. Caller may hold ``_mu`` — the
        shared :class:`DecisionJournal` append is a single O_APPEND
        write (atomic under PIPE_BUF, the fault-log discipline), never
        a blocking wait."""
        return self._journal.record(
            event, self._alloc, self._free, **detail
        )

    def journal(self, tail: int = 0) -> List[Dict]:
        with self._mu:
            return self._journal.tail(tail)

    # -- signal collection -----------------------------------------------

    def _collect(self, name: str) -> Optional[Dict]:
        tenant = self._tenants.get(name)
        if tenant is None:
            return None
        try:
            # chaos hook: an errored report models a tenant whose
            # control plane is dark — the arbiter must skip the eval
            # for that side, never wedge or crash
            faults.inject("pool.tenant_report", tenant=name)
            return tenant.report()
        except Exception as e:  # noqa: BLE001 — one dark report
            logger.warning("pool: %s report failed: %r", name, e)
            with self._mu:
                self._record(
                    "report_error", tenant=name, error=repr(e)[:200]
                )
            return None

    # -- policy loop -----------------------------------------------------

    def step(self) -> Dict:
        """One evaluate→decide→execute round; returns the decision."""
        with self._step_mu:
            return self._step_locked()

    def _step_locked(self) -> Dict:
        self.evaluations += 1
        signals = {
            name: self._collect(name) for name in self._tenants
        }
        self.last_signals = signals
        self._check_deadlines()
        with self._mu:
            if self._pending:
                # one move at a time: a second decision while a drain
                # is in flight would race the ledger it is based on
                return {
                    "action": None,
                    "reason": "revocation in flight",
                    "pending": [l.snapshot() for l in self._pending],
                }
            alloc = dict(self._alloc)
            free = self._free
            calm = self._calm_streak
            baseline = self._serve_baseline
        verdict = decide(
            signals.get(SERVING),
            alloc,
            free,
            self.cfg,
            calm,
            baseline,
            trainable=TRAINING in self._tenants,
        )
        self._calm_streak = verdict.get("calm_streak", 0)
        if verdict["action"] == "preempt":
            self._preempt(verdict["units"], verdict["reason"])
        elif verdict["action"] == "handback":
            self._handback(verdict["units"], verdict["reason"])
        elif verdict["action"] == "reclaim":
            self._grant(
                TRAINING, verdict["units"], reason=verdict["reason"]
            )
        return verdict

    def _check_deadlines(self) -> None:
        with self._mu:
            overdue = [
                l
                for l in self._pending
                if time.monotonic() > l.deadline_t
            ]
        for lease in overdue:
            self._escalate(lease)

    # -- moves -----------------------------------------------------------

    def _preempt(self, units: int, reason: str) -> None:
        """Serving breach: free pool first, then revoke training."""
        with self._mu:
            # free units move inside _grant's ledger transition; here
            # only the split between pool draw and revoke is decided
            from_free = min(self._free, units)
            self._record(
                "breach", reason=reason, units=units, from_free=from_free
            )
        if from_free:
            self._grant(SERVING, from_free, reason="breach:free-pool")
        deficit = units - from_free
        if deficit > 0:
            self._revoke(
                TRAINING, deficit, grant_to=SERVING, reason=reason
            )

    def _handback(self, units: int, reason: str) -> None:
        self._revoke(SERVING, units, grant_to=TRAINING, reason=reason)

    def _revoke(
        self, frm: str, units: int, grant_to: str, reason: str
    ) -> None:
        tenant = self._tenants.get(frm)
        if tenant is None:
            # no adapter on that side (serving-only pool): the units
            # come from / return to the free ledger directly
            with self._mu:
                self._free += units
                self._record(
                    "release", tenant=frm, units=units, reason="no tenant"
                )
            if grant_to:
                self._grant(grant_to, units, reason=reason)
            return
        t0 = time.perf_counter()
        with self._mu:
            lease = Lease(
                lease_id=self._next_lease_id,
                tenant=frm,
                units=units,
                deadline_t=time.monotonic() + self.cfg.revoke_deadline_s,
                grant_to=grant_to,
                reason=reason,
            )
            self._next_lease_id += 1
            self._pending.append(lease)
            self.revokes += 1
            self._record(
                "revoke",
                lease_id=lease.lease_id,
                tenant=frm,
                units=units,
                grant_to=grant_to,
                reason=reason,
                deadline_s=self.cfg.revoke_deadline_s,
            )
        try:
            faults.inject("pool.revoke", tenant=frm, units=units)
            tenant.revoke(
                units,
                self.cfg.revoke_deadline_s,
                lambda released=units, _l=lease: self._on_released(
                    _l, released
                ),
            )
        except Exception as e:  # noqa: BLE001 — dispatch failed: the
            # deadline still stands; escalation reclaims at expiry
            logger.warning(
                "pool: revoke dispatch to %s failed: %r", frm, e
            )
            with self._mu:
                self._record(
                    "revoke_error",
                    lease_id=lease.lease_id,
                    tenant=frm,
                    error=repr(e)[:200],
                )
        self.phases.add("revoke", time.perf_counter() - t0)

    def _on_released(self, lease: Lease, released: int) -> None:
        """Tenant-side confirmation that the drained units are free
        (called from the tenant's drain thread). ``released`` may
        EXCEED the leased units — a node_unit shrink ladder can only
        land on grid worlds — and the ledger must move by what was
        actually freed (the grant is ceiling-clamped; any excess stays
        in the free pool)."""
        with self._mu:
            if lease.state != LeaseState.REVOKING:
                # late cooperative release after an escalation already
                # reclaimed: the ledger moved once; journal and drop
                self._record(
                    "late_release",
                    lease_id=lease.lease_id,
                    tenant=lease.tenant,
                    units=released,
                )
                return
            lease.state = LeaseState.RELEASED
            lease.released_units = released
            self._pending.remove(lease)
            self._alloc[lease.tenant] -= released
            self._free += released
            drain_s = time.monotonic() - lease.created_t
            self._record(
                "release",
                lease_id=lease.lease_id,
                tenant=lease.tenant,
                units=released,
                drain_s=round(drain_s, 3),
            )
        self.phases.add("drain", drain_s)
        if lease.grant_to and released > 0:
            # the grant stays at the leased size (the policy's spike
            # step); any grid-forced excess sits in the free pool for
            # the next eval to place
            self._grant(
                lease.grant_to,
                min(released, lease.units),
                reason=lease.reason,
            )

    def _escalate(self, lease: Lease) -> None:
        """Cooperative drain missed its deadline: force the reclaim."""
        tenant = self._tenants.get(lease.tenant)
        with self._mu:
            if lease.state != LeaseState.REVOKING:
                return
            lease.state = LeaseState.ESCALATED
            self.escalations += 1
            self._record(
                "escalate",
                lease_id=lease.lease_id,
                tenant=lease.tenant,
                units=lease.units,
                overdue_s=round(
                    time.monotonic() - lease.deadline_t, 3
                ),
            )
        freed = 0
        try:
            freed = int(tenant.escalate(lease.units))
        except Exception as e:  # noqa: BLE001 — even the hard path
            # failed: journal it; the units stay with the tenant (the
            # ledger must never claim capacity nobody actually freed)
            logger.error(
                "pool: escalation on %s failed: %r", lease.tenant, e
            )
            with self._mu:
                self._record(
                    "escalate_error",
                    lease_id=lease.lease_id,
                    tenant=lease.tenant,
                    error=repr(e)[:200],
                )
        with self._mu:
            if lease in self._pending:
                self._pending.remove(lease)
            lease.released_units = freed
            self._alloc[lease.tenant] -= freed
            self._free += freed
            drain_s = time.monotonic() - lease.created_t
            if freed:
                self._record(
                    "escalate_freed",
                    lease_id=lease.lease_id,
                    tenant=lease.tenant,
                    units=freed,
                    drain_s=round(drain_s, 3),
                )
        self.phases.add("drain", drain_s)
        if lease.grant_to and freed > 0:
            self._grant(
                lease.grant_to,
                min(freed, lease.units),
                reason=lease.reason,
            )

    def _grant(self, to: str, units: int, reason: str) -> None:
        tenant = self._tenants.get(to)
        ceiling = (
            self.cfg.serve_ceiling if to == SERVING else self.cfg.train_ceiling
        )
        with self._mu:
            # clamp to the FREE ledger too, not just the ceiling: a
            # drain-thread release and a concurrent step() can both
            # try to place the same freed units (the release's
            # deferred grant runs outside _step_mu) — whichever grant
            # runs second must find them already spent, never drive
            # _free negative
            grantable = min(
                units, ceiling - self._alloc.get(to, 0), self._free
            )
            if tenant is None or grantable <= 0:
                # over ceiling / already spent (or no adapter on that
                # side): the units stay in the free ledger
                self._record(
                    "grant_skipped", tenant=to, units=units, reason=reason
                )
                return
            units = grantable
            self._alloc[to] = self._alloc.get(to, 0) + units
            self._free -= units
            self.grants += 1
            self._record(
                "grant", tenant=to, units=units, reason=reason
            )
        t0 = time.perf_counter()
        try:
            faults.inject("pool.grant", tenant=to, units=units)
            tenant.grant(units)
        except Exception as e:  # noqa: BLE001 — the tenant could not
            # apply the capacity: roll the ledger back to free so a
            # later eval can retry the move
            logger.warning("pool: grant to %s failed: %r", to, e)
            with self._mu:
                self._alloc[to] -= units
                self._free += units
                self._record(
                    "grant_error",
                    tenant=to,
                    units=units,
                    error=repr(e)[:200],
                )
            return
        self.phases.add("grant", time.perf_counter() - t0)

    # -- status ----------------------------------------------------------

    def status(self) -> Dict:
        with self._mu:
            out = {
                "total_units": self.cfg.total_units,
                "allocations": dict(self._alloc),
                "free": self._free,
                "pending": [l.snapshot() for l in self._pending],
                "calm_streak": self._calm_streak,
                "counters": {
                    "evaluations": self.evaluations,
                    "revokes": self.revokes,
                    "grants": self.grants,
                    "escalations": self.escalations,
                },
                "journal_tail": self._journal.tail(20),
            }
        out["signals"] = self.last_signals
        out["phase_split"] = self.phases.split().summary()
        out["bounds"] = {
            "train": [self.cfg.train_floor, self.cfg.train_ceiling],
            "serve": [self.cfg.serve_floor, self.cfg.serve_ceiling],
        }
        return out

    # -- periodic driver -------------------------------------------------

    def start(self) -> "ChipPoolArbiter":
        """Periodic evaluation at ``eval_interval_s`` (0 = manual
        ``step()`` only — start() is then a no-op)."""
        if self.cfg.eval_interval_s <= 0:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="pool-arbiter", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — arbiter survives
                logger.exception("pool arbiter error: %s", e)
            self._stop.wait(self.cfg.eval_interval_s)
